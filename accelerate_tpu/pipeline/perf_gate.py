"""CPU-tier perf-regression gate: the PR-4 pipeline wins, asserted forever.

The overlapped execution pipeline was proven with a one-off CPU probe (eager
49 → fused 104 steps/s, 6 → 1 dispatches/step at accum=2) — but a one-off
number in a PR description cannot stop a later change from quietly
re-introducing a per-micro-batch dispatch or a host sync.  And the TPU
benchmark can't either: 4 of 5 bench rounds died to device flake, so "the
benchmark will catch it" means *nothing* catches it.

This gate re-runs a bounded version of that probe on CPU and asserts the
**relative** invariants against a committed baseline
(``benchmarks/perf_baseline_cpu.json``):

- ``dispatches_per_step == 1`` on the fused path (exact and deterministic —
  the sharpest tripwire: any regression to eager-style dispatch shows up as
  an integer, immune to machine noise);
- fused-vs-eager steps/s ratio ≥ a conservative floor (the measured win is
  ~2.1×; the floor is far below it so CI load cannot flake the gate, while a
  real fused-path rot — which lands the ratio at ~1.0 — still fails loudly);
- fused-path host-blocked ms/step under a generous ceiling (catches a
  reintroduced synchronous host round-trip, not scheduler jitter);
- a **ZeRO row** (multi-device runs): the sharded-update fused step must
  report ``zero_active`` (the silent-fallback-to-replicated tripwire),
  still run at ``dispatches/step == 1`` and hold the same fused-vs-eager
  ratio floor — a regression that quietly rebuilds the replicated update
  fails in tier-1, not on the next TPU window;
- an **overlap row** (multi-device runs): a ``jax.profiler`` trace of the
  ZeRO arm is scanned (``telemetry/profile_scan.py``) and the fraction of
  collective time NOT hidden behind concurrent compute must stay under
  ``max_exposed_collective_frac`` — the static byte ledger proves the
  collectives exist; this row proves at runtime that they overlap;
- a **pp row** (multi-device runs): the fused pipeline-parallel train step
  (pp=2 llama through ``make_train_step``) must stay at
  ``max_pp_dispatches_per_step`` == 1 (the whole microbatch schedule +
  backward + update in ONE donated dispatch), the interleaved schedule must
  actually build (``pp_interleaved_active`` — the gpipe-only-fallback
  tripwire, with the analytic tick counts as proof: v·M + S - 1 vs
  M + S - 1), and interleaved-vs-gpipe steps/s must hold
  ``min_interleaved_vs_gpipe_ratio`` (interleaved does
  (v·M+S-1)/(v·(M+S-1)) of gpipe's total layer work — the realized
  bubble-shrink this row keeps honest).

Absolute steps/s are *reported* but never gated — a 2-core CI box drifts
±50% run to run; ratios and dispatch counts don't.

Run it: ``make perf-gate`` (or ``python -m accelerate_tpu.pipeline.perf_gate``);
``tests/test_perf_gate.py`` runs the same gate inside tier-1 so a perf
regression fails the test suite even when no TPU answers.

``ACCELERATE_TPU_PERF_GATE_DEGRADE=eager`` replaces the fused arm with the
eager loop — the knob that *proves* the gate fails when the fused path is
degraded (dispatches/step jumps to ``3 × accum``, the ratio collapses to ~1).
``=zero-fallback`` runs the ZeRO arm with the replicated update — the knob
that proves the ``zero_active`` tripwire catches a silent fallback.
``=no-overlap`` scans the same trace with the concurrent-compute credit
disabled (every collective µs counts as exposed — what stripping the
latency-hiding scheduler flags does to a TPU run) — the knob that proves the
overlap row fails when collectives stop hiding.
``=gpipe-only`` runs the pp row's interleaved arm with the gpipe schedule —
the knob that proves the ``pp_interleaved_active`` tripwire catches a
silently-degraded pipeline schedule.
``=badput`` sleeps between the goodput arm's steps (pure idle badput) — the
knob that proves the **goodput row** (wall-clock productive fraction from
``telemetry/goodput.py``'s attribution ledger, compiles warmed outside the
window) actually judges where the wall clock went.
``=dense-decode`` runs the **serving row**'s paged arm on the dense
gather-view decode program — the knob that proves the
``serving_paged_active`` tripwire and the paged-vs-dense throughput floor
actually judge the serving decode fast path (PR 15: the paged program reads
pool K/V in place through bucketed block tables; a regression back to
"gather the worst-case dense view every token" lands the ratio at ~1.0 and
fails loudly).
``=mem-bloat`` registers four extra live parameter copies in the HBM ledger
under a ``perf_gate.bloat`` owner — the knob that proves the **memory row**
(per-chip train-state and serving-pool byte ceilings from
``telemetry/memledger.py``'s attribution ledger; deterministic shape
arithmetic, not allocator stats, so CI load cannot flake it) actually judges
the footprint.  A change that silently doubles optimizer state or fattens
the KV pool fails in tier-1, not on the next real-model TPU run.
``=no-spec`` runs the **spec row**'s speculative arm with ``spec_tokens=0``
— plain greedy masquerading as the speculative config.  The
``serving_spec_active`` tripwire must catch it: the measured ITL ratio stays
near 1.0 (often ABOVE the 0.9 floor, since greedy-vs-greedy is noise), which
is exactly why the integer tripwires, not the ratio floor, carry exactness
(PR 19: the floor only guards a pathological verify-window slowdown).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from typing import Optional

__all__ = [
    "load_baseline", "run_probe", "run_pp_probe", "run_serving_probe",
    "run_spec_probe",
    "evaluate", "run_gate", "main",
]

ENV_BASELINE = "ACCELERATE_TPU_PERF_BASELINE"
ENV_DEGRADE = "ACCELERATE_TPU_PERF_GATE_DEGRADE"

DEFAULT_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "benchmarks",
    "perf_baseline_cpu.json",
)


def load_baseline(path: Optional[str] = None) -> dict:
    """Parse the committed baseline JSON (``$ACCELERATE_TPU_PERF_BASELINE``
    overrides the path for experiments)."""
    path = path or os.environ.get(ENV_BASELINE) or DEFAULT_BASELINE_PATH
    with open(path) as f:
        return json.load(f)


def run_pp_probe(
    steps: int = 3,
    micro_batches: int = 4,
    virtual_stages: int = 2,
    degrade: Optional[str] = None,
) -> dict:
    """The pp row's measurement: gpipe vs interleaved fused pipeline train
    steps on a pp=4 mesh (llama-tiny through ``make_train_step``), at the
    SAME microbatch count M.  The batch geometry (B=32, seq=64) keeps the
    probe in the compute-dominated regime where the schedule's tick count —
    not the scan's per-tick fixed overhead — sets the step time, so the
    interleaved win ((v·M+S-1)/(v·(M+S-1)) = 11/14 of gpipe's layer work at
    these settings) is measurable on a CPU box.  Returns the ``pp_*``
    measurement keys.  ``degrade="gpipe-only"`` builds the "interleaved" arm
    with the gpipe schedule — the self-test that the
    ``pp_interleaved_active`` tripwire actually judges this row."""
    import numpy as np

    import jax

    from .. import telemetry
    from ..accelerator import Accelerator
    from ..models import llama
    from ..parallel.pipeline import (
        pipeline_bubble_fraction,
        pipeline_llama_model,
        pipeline_ticks,
    )
    from ..parallel.sharding import data_sharding
    from ..state import AcceleratorState, GradientState, PartialState
    from ..utils import set_seed
    from ..utils.dataclasses import ParallelismConfig, PipelineParallelPlugin

    import optax

    pp = 4
    M = micro_batches
    v = virtual_stages
    if jax.device_count() < pp or jax.device_count() % pp:
        raise RuntimeError(
            f"run_pp_probe needs a device count divisible by pp={pp} "
            f"(got {jax.device_count()})"
        )
    if degrade is None:
        degrade = os.environ.get(ENV_DEGRADE, "").strip().lower() or None
    tel = telemetry.get_telemetry()
    owns_telemetry = not tel.enabled
    if owns_telemetry:
        telemetry.enable(dir=tempfile.mkdtemp(prefix="atpu_pp_gate_"))
    dispatches = tel.registry.counter("pipeline.dispatches")

    def arm(schedule, vs):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        set_seed(0)
        acc = Accelerator(
            parallelism_config=ParallelismConfig(pp=pp, dp=max(jax.device_count() // pp, 1)),
            pp_plugin=PipelineParallelPlugin(
                pp_size=pp, num_micro_batches=M, schedule=schedule, virtual_stages=vs
            ),
        )
        cfg = llama.LlamaConfig.tiny(num_layers=8, hidden_size=64, intermediate_size=128)
        params = llama.init_params(cfg, jax.random.key(0))
        model, opt = acc.prepare(pipeline_llama_model(params, cfg), optax.adamw(1e-3))
        step_fn = acc.make_train_step(model, opt)
        rng = np.random.default_rng(0)
        batches = [
            {
                "input_ids": jax.device_put(
                    rng.integers(0, cfg.vocab_size, (32, 64)).astype("int32"),
                    data_sharding(acc.mesh),
                )
            }
            for _ in range(steps)
        ]
        # Warmup compiles AND syncs — its device tail must not bleed into the
        # first timed step's window.
        float(np.asarray(step_fn(batches[0])))
        d0 = dispatches.value
        t0 = time.perf_counter()
        for b in batches[1:]:
            step_fn(b)
        jax.block_until_ready(model.params)
        dt = time.perf_counter() - t0
        timed = max(steps - 1, 1)
        return timed / dt, (dispatches.value - d0) / timed, step_fn

    try:
        gpipe_sps, gpipe_disp, _ = arm("gpipe", 1)
        if degrade == "gpipe-only":
            inter_sps, inter_disp, step_fn = arm("gpipe", 1)
            inter_schedule, inter_v = "gpipe", 1
        else:
            inter_sps, inter_disp, step_fn = arm("interleaved", v)
            inter_schedule, inter_v = "interleaved", v
    finally:
        if owns_telemetry:
            telemetry.disable()
    return {
        "pp_degree": pp,
        "pp_micro_batches": M,
        "pp_virtual_stages": inter_v,
        "pp_gpipe_steps_per_s": round(gpipe_sps, 2),
        "pp_interleaved_steps_per_s": round(inter_sps, 2),
        "pp_interleaved_vs_gpipe_ratio": round(inter_sps / max(gpipe_sps, 1e-9), 3),
        "pp_gpipe_dispatches_per_step": gpipe_disp,
        "pp_dispatches_per_step": inter_disp,
        "pp_active": step_fn.pp_active,
        # The schedule tripwire: interleaved really built iff its analytic
        # tick count differs from gpipe's (v > 1).
        "pp_interleaved_active": inter_schedule == "interleaved" and inter_v > 1,
        "pp_gpipe_ticks": pipeline_ticks(pp, M, 1),
        "pp_interleaved_ticks": pipeline_ticks(pp, M, inter_v),
        "pp_analytic_bubble_gpipe": round(pipeline_bubble_fraction(pp, M, 1), 4),
        "pp_analytic_bubble_interleaved": round(pipeline_bubble_fraction(pp, M, inter_v), 4),
    }


def run_serving_probe(decode_ticks: int = 25, degrade: Optional[str] = None) -> dict:
    """The serving row's measurement: paged vs dense decode throughput on a
    bounded CPU engine pair (gpt2-tiny, identical geometry and request mix).

    The dense arm is the PR 9 program — gather every slot's worst-case
    ``[S, L, 1, M*bs, *r]`` view, vmap ``apply_cached``, flow the updated
    view back out; the paged arm reads pool K/V in place through bucketed
    block tables and returns only the written rows.  The request geometry is
    chosen so the paged arm's table bucket is CONSTANT across the timed
    window (prompt 33 rows + 30 budget stays under the 64-row bucket):
    a bucket crossing recompiles once, which is steady-state-invisible but
    would poison a 25-tick window.  Judged invariants: decode dispatches per
    tick == 1 on the paged path, paged-vs-dense steps/s over the committed
    floor, and ``serving_paged_active`` (the dense-fallback tripwire).
    ``degrade="dense-decode"`` builds the paged arm on the dense program —
    the self-test that this row actually judges the fast path."""
    import numpy as np

    import jax.numpy as jnp

    from ..models import gpt2
    from ..serving import ServingConfig, ServingEngine
    from ..serving.scheduler import RequestState

    if degrade is None:
        degrade = os.environ.get(ENV_DEGRADE, "").strip().lower() or None
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    import jax

    params = gpt2.init_params(cfg, jax.random.key(0))

    def arm(path):
        rng = np.random.default_rng(0)
        eng = ServingEngine(
            gpt2.apply_cached, gpt2.init_cache, params, cfg,
            serving=ServingConfig(
                block_size=8, num_blocks=80, max_slots=4, prefill_chunk=8,
                max_blocks_per_seq=16, decode_path=path, prefix_cache=False,
            ),
        )
        for _ in range(4):
            eng.submit(list(rng.integers(0, cfg.vocab_size, size=33)), 30)
        # Prefill everyone into the decode batch, then warm the decode
        # program for the active bucket outside the timed window.
        while (
            any(s.request.state != RequestState.DECODING for s in eng.sched.slots.values())
            or eng.sched.pending
        ):
            eng.step()
        for _ in range(2):
            eng.step()
        d0 = eng.decode_dispatches
        t0 = time.perf_counter()
        for _ in range(decode_ticks):
            eng.step()
        dt = time.perf_counter() - t0
        stats = eng.stats()
        return (
            decode_ticks / dt,
            (eng.decode_dispatches - d0) / decode_ticks,
            stats["decode_path"],
            stats.get("pool_bytes"),
        )

    dense_sps, dense_disp, _, _ = arm("dense")
    paged_sps, paged_disp, paged_path, pool_bytes = arm(
        "dense" if degrade == "dense-decode" else "paged"
    )
    return {
        "serving_dense_decode_steps_per_s": round(dense_sps, 2),
        "serving_paged_decode_steps_per_s": round(paged_sps, 2),
        "serving_paged_vs_dense_ratio": round(paged_sps / max(dense_sps, 1e-9), 3),
        "serving_decode_dispatches_per_tick": paged_disp,
        "serving_dense_decode_dispatches_per_tick": dense_disp,
        "serving_paged_active": paged_path == "paged",
        # Memory row input: the engine is single-device by design, so the
        # pool's allocation IS its per-chip footprint.
        "serving_pool_bytes_per_chip": pool_bytes,
    }


def run_spec_probe(degrade: Optional[str] = None, max_new: int = 60) -> dict:
    """The serving-spec row's measurement: speculative draft-then-verify vs
    plain greedy decode inter-token latency on a bounded CPU engine pair at
    IDENTICAL geometry (gpt2-tiny, same prompts, same budgets, paged path
    both sides — only ``spec_tokens`` differs).

    The prompts carry a repeated pattern so the default n-gram drafter
    actually hits (the workload speculative serving targets: templated /
    repetitive traffic), and random tiny-model greedy decode promptly falls
    into repetition loops of its own — everything is deterministic per seed,
    so the measured acceptance rate is CI-stable.  Each arm first runs a
    warm-up request end to end (same geometry) so every bucket's program is
    jit-cached before the timed batch; mean inter-token latency then comes
    from the completed requests' own SLO samples.  Judged invariants:
    ``serving_spec_active`` (acceptance > 0 AND tokens/dispatch > 1 — the
    silent-fallback tripwire), per-request token identity vs the greedy
    arm, and the spec-vs-greedy ITL ratio over the committed floor.
    ``degrade="no-spec"`` builds the spec arm with ``spec_tokens=0`` — the
    self-test that this row actually judges speculative decode."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..models import gpt2
    from ..serving import ServingConfig, ServingEngine

    if degrade is None:
        degrade = os.environ.get(ENV_DEGRADE, "").strip().lower() or None
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(13)
    pattern = [int(t) for t in rng.integers(0, cfg.vocab_size, size=8)]
    # Pure pattern repeats at staggered phases: the trailing n-gram recurs
    # from the very first decode tick, so the drafter contributes over the
    # whole run rather than only after the model falls into its own loop.
    prompts = [pattern * 2 + pattern[:j] for j in (0, 2, 4, 6)]
    max_new = int(max_new)  # 60 for the gated row; self-tests run shorter

    def arm(spec_tokens):
        eng = ServingEngine(
            gpt2.apply_cached, gpt2.init_cache, params, cfg,
            serving=ServingConfig(
                block_size=8, num_blocks=80, max_slots=4, prefill_chunk=8,
                max_blocks_per_seq=16, prefix_cache=False,
                spec_tokens=spec_tokens,
            ),
        )
        # Warm every bucket's program (prefill, decode, verify) outside the
        # timed window — same prompt shape as the timed batch.
        eng.submit(list(prompts[0]), max_new)
        eng.run()
        rids = [eng.submit(list(p), max_new) for p in prompts]
        t0 = time.perf_counter()
        outs = eng.run()
        wall = time.perf_counter() - t0
        itl = [
            ms
            for r in eng.pop_finished()
            if r.id in set(rids)
            for ms in r.inter_token_ms
        ]
        stats = eng.stats()
        itl_sorted = sorted(itl)
        return {
            "outputs": [outs[r] for r in rids],
            "itl_ms": sum(itl) / max(len(itl), 1),
            "itl_p95_ms": (
                itl_sorted[min(int(len(itl_sorted) * 0.95), len(itl_sorted) - 1)]
                if itl_sorted else 0.0
            ),
            "wall_s": wall,
            "spec": stats["spec"],
        }

    arm(0)  # discarded: process-level warm-up (first arm pays one-time
    # costs no per-engine warm request covers; measured ~1.4x ITL skew
    # between two IDENTICAL greedy arms without this)
    greedy = arm(0)
    spec = arm(0 if degrade == "no-spec" else 3)
    acceptance = spec["spec"]["acceptance_rate"]
    tokens_per_dispatch = spec["spec"]["tokens_per_dispatch"]
    return {
        "serving_greedy_itl_ms": round(greedy["itl_ms"], 3),
        "serving_spec_itl_ms": round(spec["itl_ms"], 3),
        "serving_greedy_itl_p95_ms": round(greedy["itl_p95_ms"], 3),
        "serving_spec_itl_p95_ms": round(spec["itl_p95_ms"], 3),
        "serving_spec_vs_greedy_itl_ratio": round(
            greedy["itl_ms"] / max(spec["itl_ms"], 1e-9), 3
        ),
        "serving_spec_acceptance_rate": acceptance,
        "serving_spec_tokens_per_dispatch": tokens_per_dispatch,
        "serving_spec_active": bool(acceptance > 0 and tokens_per_dispatch > 1),
        "serving_spec_token_identical": spec["outputs"] == greedy["outputs"],
    }


def run_tiering_probe(cycles: int = 4, degrade: Optional[str] = None) -> dict:
    """The serving-tiering row's measurement: preempt-resume latency with
    the host-DRAM KV tier (demote the victim's blocks on preemption, promote
    on re-admission, zero re-prefill dispatches) vs the re-prefill fallback
    it replaces, at IDENTICAL geometry (gpt2-tiny, same prompt, same preempt
    cadence — only ``host_blocks`` differs).

    Each arm runs one warm request end to end, then repeatedly preempts the
    probe request mid-decode via ``preempt_slot`` and times preemption ->
    next emitted token; one discarded cycle per arm lands the migration /
    re-prefill programs' compiles outside the timed window.  Judged
    invariants: ``serving_tiering_active`` (promotions landed, zero fallback
    re-prefills, and the completed request's prefill dispatches stayed at
    the no-preemption count — the silent-re-prefill tripwire), token
    identity vs the untiered arm, and the migrated-vs-re-prefill resume
    ratio over the committed floor.  ``degrade="no-tiering"`` builds the
    tiered arm with ``host_blocks=0`` — the self-test that this row
    actually judges the migration path."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..models import gpt2
    from ..serving import ServingConfig, ServingEngine
    from ..serving.scheduler import RequestState

    if degrade is None:
        degrade = os.environ.get(ENV_DEGRADE, "").strip().lower() or None
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(7)
    # A long prompt makes the structural gap measurable on CPU: a migrated
    # resume is one promote + one decode tick regardless of prompt length,
    # while the re-prefill fallback pays ceil(rows/chunk) = 13 dispatches.
    # 97 rows keeps the request at EXACTLY 13 blocks through every timed
    # cycle (rows 98..102 as tokens land) — a block-boundary crossing
    # recompiles the demote/promote copies mid-window and poisons the mean.
    prompt = [int(t) for t in rng.integers(0, cfg.vocab_size, size=97)]
    max_new = 12

    def arm(host_blocks):
        eng = ServingEngine(
            gpt2.apply_cached, gpt2.init_cache, params, cfg,
            serving=ServingConfig(
                block_size=8, num_blocks=80, max_slots=4, prefill_chunk=8,
                max_blocks_per_seq=16, prefix_cache=False,
                host_blocks=host_blocks,
            ),
        )
        # Warm every bucket's program end to end outside the timed cycles.
        eng.submit(list(prompt), max_new)
        eng.run()
        eng.pop_finished()
        rid = eng.submit(list(prompt), max_new)
        req = next(r for r in eng.sched.queue if r.id == rid)
        resumes = []
        for cycle in range(cycles + 1):  # cycle 0 discarded: warms the
            # demote/promote (or re-prefill-resume) programs themselves.
            while req.state != RequestState.DECODING or len(req.emitted) <= cycle:
                eng.step()
            idx = next(i for i, s in eng.sched.slots.items() if s.request.id == rid)
            n0 = len(req.emitted)
            t0 = time.perf_counter()
            eng.sched.preempt_slot(idx)
            while len(req.emitted) == n0:
                eng.step()
            if cycle:
                resumes.append((time.perf_counter() - t0) * 1e3)
        outs = eng.run()
        done = next(r for r in eng.pop_finished() if r.id == rid)
        # Raw migration bandwidth: one timed 8-block round trip through the
        # drained cache (second pass — the first warms the per-shape copies).
        demote_ms = promote_ms = None
        if eng.cache.host is not None and eng.cache.host.free_blocks >= 8:
            blocks = eng.sched.allocator.alloc(8)
            for _ in range(2):
                t0 = time.perf_counter()
                host_ids = eng.cache.demote(blocks)
                demote_ms = (time.perf_counter() - t0) * 1e3
                t0 = time.perf_counter()
                eng.cache.promote(host_ids, blocks)
                jax.block_until_ready(list(eng.cache.pool.values()))
                promote_ms = (time.perf_counter() - t0) * 1e3
            eng.sched.allocator.free(blocks)
        return {
            "resume_ms": sum(resumes) / max(len(resumes), 1),
            "outputs": outs[rid],
            "tiering": eng.stats()["tiering"],
            "prefill_dispatches": done.prefill_dispatches,
            "migrations": done.migrations,
            "block_bytes": eng.cache.block_bytes(),
            "demote_ms": demote_ms,
            "promote_ms": promote_ms,
        }

    base = arm(0)  # the re-prefill resume path the tier replaces
    tier = arm(0 if degrade == "no-tiering" else 16)
    tiering = tier["tiering"]
    active = bool(
        tiering is not None
        and tiering["promotions"] >= 1
        and tiering["fallback_reprefills"] == 0
        # Zero re-prefill: the completed request's prefill dispatches must
        # equal the single-admission chunk count despite every preemption.
        and tier["prefill_dispatches"] == -(-len(prompt) // 8)
    )
    def bw(ms):
        return round(8 * tier["block_bytes"] / (ms / 1e3) / 1e6, 1) if ms else None
    return {
        "serving_reprefill_resume_ms": round(base["resume_ms"], 3),
        "serving_migrated_resume_ms": round(tier["resume_ms"], 3),
        "serving_migrated_vs_reprefill_ratio": round(
            base["resume_ms"] / max(tier["resume_ms"], 1e-9), 3
        ),
        "serving_tiering_active": active,
        "serving_tiering_token_identical": tier["outputs"] == base["outputs"],
        "serving_tier_migrations": tier["migrations"],
        "serving_tier_fallback_reprefills": (
            tiering["fallback_reprefills"] if tiering is not None else None
        ),
        "serving_tier_demote_mb_per_s": bw(tier["demote_ms"]),
        "serving_tier_promote_mb_per_s": bw(tier["promote_ms"]),
    }


def run_probe(
    accum: int = 2,
    steps: int = 10,
    dim: int = 128,
    batch: int = 8,
    epochs: int = 3,
    prefetch: int = 2,
    degrade: Optional[str] = None,
    pp: bool = True,
    serving: bool = True,
) -> dict:
    """Bounded eager-vs-fused micro-benchmark (the bench.py pipeline probe,
    trimmed for a test-suite budget).  Returns the measurements dict the gate
    judges.  ``degrade="eager"`` runs the eager loop in the fused arm — the
    self-test knob.  ``pp=False`` / ``serving=False`` skip the
    pipeline-parallel / serving-decode rows (targeted self-tests of the
    other rows don't pay for their extra compiles)."""
    import numpy as np
    import torch

    from .. import telemetry
    from ..accelerator import Accelerator
    from ..state import AcceleratorState, GradientState, PartialState
    from ..utils import DataLoaderConfiguration, set_seed

    if degrade is None:
        degrade = os.environ.get(ENV_DEGRADE, "").strip().lower() or None
    tel = telemetry.get_telemetry()
    owns_telemetry = not tel.enabled
    if owns_telemetry:
        telemetry.enable(dir=tempfile.mkdtemp(prefix="atpu_perf_gate_"))
    dispatches = tel.registry.counter("pipeline.dispatches")

    class MLPWithLoss(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.net = torch.nn.Sequential(
                torch.nn.Linear(dim, dim),
                torch.nn.Tanh(),
                torch.nn.Linear(dim, 1),
            )

        def forward(self, x, y):
            pred = self.net(x)
            return {"loss": torch.nn.functional.mse_loss(pred, y), "logits": pred}

    n_batches = accum * steps

    def build():
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        set_seed(0)
        acc = Accelerator(
            gradient_accumulation_steps=accum,
            dataloader_config=DataLoaderConfiguration(prefetch_to_device=prefetch),
        )
        model = MLPWithLoss()
        opt = torch.optim.AdamW(model.parameters(), lr=1e-3)
        rng = np.random.default_rng(0)
        data = [
            {
                "x": torch.from_numpy(rng.standard_normal((batch, dim)).astype("float32")),
                "y": torch.from_numpy(rng.standard_normal((batch, 1)).astype("float32")),
            }
            for _ in range(n_batches)
        ]
        model, opt = acc.prepare(model, opt)
        dl = acc.prepare_data_loader(data)
        return acc, model, opt, dl

    def eager_arm():
        import jax

        acc, model, opt, dl = build()

        def one_epoch():
            blocked = 0.0
            it = iter(dl)
            t_start = time.perf_counter()
            while True:
                t0 = time.perf_counter()
                try:
                    batch_data = next(it)
                except StopIteration:
                    break
                blocked += time.perf_counter() - t0
                with acc.accumulate(model):
                    out = model(**batch_data)
                    acc.backward(out.loss)
                    opt.step()
                    opt.zero_grad()
            jax.block_until_ready(model.params)
            return time.perf_counter() - t_start, blocked

        one_epoch()  # warmup: compiles
        best_dt, best_blocked, d0 = float("inf"), 0.0, dispatches.value
        for _ in range(epochs):
            dt, blocked = one_epoch()
            if dt < best_dt:
                best_dt, best_blocked = dt, blocked
        per_step_dispatch = (dispatches.value - d0) / (epochs * steps)
        return steps / best_dt, per_step_dispatch, best_blocked / steps * 1e3

    def fused_arm(zero=None, trace_dir=None):
        import jax

        acc, model, opt, dl = build()
        step_fn = acc.make_train_step(model, opt, zero=zero)

        def one_epoch():
            blocked = 0.0
            window = []
            it = iter(dl)
            t_start = time.perf_counter()
            while True:
                t0 = time.perf_counter()
                try:
                    batch_data = next(it)
                except StopIteration:
                    break
                blocked += time.perf_counter() - t0
                window.append(batch_data)
                if len(window) == accum:
                    step_fn(window)
                    window = []
            jax.block_until_ready(model.params)
            return time.perf_counter() - t_start, blocked

        one_epoch()
        best_dt, best_blocked, d0 = float("inf"), 0.0, dispatches.value
        for _ in range(epochs):
            dt, blocked = one_epoch()
            if dt < best_dt:
                best_dt, best_blocked = dt, blocked
        per_step_dispatch = (dispatches.value - d0) / (epochs * steps)
        if trace_dir is not None:
            # One extra, untimed epoch under the profiler: the overlap audit
            # must not tax the steps/s measurement it rides along with.
            jax.profiler.start_trace(trace_dir)
            try:
                one_epoch()
            finally:
                jax.profiler.stop_trace()
        return (
            steps / best_dt,
            per_step_dispatch,
            best_blocked / steps * 1e3,
            step_fn.zero_active,
        )

    try:
        eager_sps, eager_disp, eager_blocked = eager_arm()
        if degrade == "eager":
            fused_sps, fused_disp, fused_blocked = eager_arm()
        else:
            # zero=False pinned: the baseline arm must measure the replicated
            # fused step even when the operator exports ACCELERATE_TPU_ZERO=1
            # (zero=None would defer to that env and skew every ratio).
            fused_sps, fused_disp, fused_blocked, _ = fused_arm(zero=False)
        # ZeRO row: only meaningful on a multi-device mesh (a 1-device run
        # has no dp axis to shard over — the arm is skipped, and evaluate()
        # skips its judgments when zero_active is None).
        import jax
        import warnings

        zero_sps = zero_disp = zero_blocked = None
        zero_active = None
        zero_exposed_frac = None
        zero_profile = None
        zero_profile_error = None
        if jax.device_count() >= 2:
            trace_dir = tempfile.mkdtemp(prefix="atpu_perf_gate_trace_")
            with warnings.catch_warnings():
                # The deliberate zero-fallback degrade warns; the probe's
                # numbers are the signal, not the warning.
                warnings.simplefilter("ignore")
                zero_sps, zero_disp, zero_blocked, zero_active = fused_arm(
                    zero=False if degrade == "zero-fallback" else True,
                    trace_dir=trace_dir,
                )
            # Overlap audit over the captured trace: the only *runtime* proof
            # that the ZeRO collectives hide behind compute.  The "no-overlap"
            # degrade disables the concurrent-compute credit — the self-test
            # that shows the exposed-comms row actually judges this number.
            try:
                from ..telemetry import profile_scan

                zero_profile = profile_scan.analyze_trace_dir(
                    trace_dir, assume_no_overlap=(degrade == "no-overlap")
                )
                if zero_profile.collective_ms > 0:
                    zero_exposed_frac = round(
                        zero_profile.exposed_collective_ms / zero_profile.collective_ms,
                        4,
                    )
                else:
                    zero_profile_error = "trace has no collective ops"
            except Exception as e:
                zero_profile_error = str(e)[:200]
        # pp row: the probe builds a pp=4 mesh, so it needs a device count
        # divisible by 4 (the ZeRO row's >= 2 condition is not enough here —
        # a 2-device run must SKIP the row, not crash the gate).
        pp_row = None
        if pp and jax.device_count() >= 4 and jax.device_count() % 4 == 0:
            pp_row = run_pp_probe(degrade=degrade)

        # serving row: paged vs dense decode on the continuous-batching
        # engine — single-device by design (the engine is mesh-agnostic), so
        # unlike the ZeRO/pp rows it runs on every probe.
        serving_row = None
        if serving:
            serving_row = run_serving_probe(degrade=degrade)
            # spec row: speculative vs greedy decode on the same engine
            # geometry (one more paired probe; rides the serving flag).
            serving_row.update(run_spec_probe(degrade=degrade))
            # tiering row: migrated preempt-resume vs re-prefill on the same
            # engine geometry (the host-DRAM KV tier's paired probe).
            serving_row.update(run_tiering_probe(degrade=degrade))

        # goodput row: one fused epoch (compiles warmed OUTSIDE the window)
        # through the wall-clock attribution ledger — the productive fraction
        # is the runtime proof that steps, not overhead, own the wall clock.
        # ``degrade="badput"`` sleeps between steps: pure idle badput, the
        # self-test that this row actually judges the fraction.
        def goodput_arm():
            from ..telemetry import goodput as goodput_mod

            acc, model, opt, dl = build()
            step_fn = acc.make_train_step(model, opt, zero=False)
            # Pre-staged windows: the row judges the step-dominated regime
            # (loader overhead has its own host-blocked row above).
            windows, window = [], []
            for batch_data in dl:
                window.append(batch_data)
                if len(window) == accum:
                    windows.append(window)
                    window = []
            # Warmup epoch: BOTH compiles (the uncommitted-params first call
            # and the committed-sharding steady-state program) land outside
            # the measured window.
            for w in windows:
                step_fn(w)
            jax.block_until_ready(model.params)
            badput_sleep = 0.1 if degrade == "badput" else 0.0
            # attached() restores any pre-existing ledger: the gate running
            # inside a goodput-enabled process must not destroy its host
            # run's accounting.
            with goodput_mod.attached() as led:
                for _ in range(epochs):
                    for w in windows:
                        step_fn(w)
                        if badput_sleep:
                            time.sleep(badput_sleep)
                jax.block_until_ready(model.params)
                return led.summary(), model.params

        goodput_summary, probe_params = goodput_arm()

        # memory row: the per-chip train-state footprint from the HBM ledger
        # (``make_train_step``'s build registers ``train.params`` and
        # ``train.opt_state`` after ZeRO placement).  Deterministic shape
        # arithmetic, not allocator stats — CI load cannot flake it.
        # ``degrade="mem-bloat"`` registers four real extra parameter copies
        # under ``perf_gate.bloat``: the self-test that the committed per-chip
        # ceiling actually judges this row.
        def memory_arm():
            from ..telemetry.memledger import get_memory_ledger

            # The goodput arm's ``make_train_step`` build just registered
            # ``train.params``/``train.opt_state`` at this exact geometry
            # (zero=False, same build()) and registrations outlive the arm —
            # read the ledger rather than paying another build + compile.
            ledger = get_memory_ledger()
            bloat = None
            if degrade == "mem-bloat":
                # Live copies (leaf + 1 forces fresh buffers), registered
                # like any other owner; released once the number is read.
                bloat = [
                    jax.tree_util.tree_map(lambda leaf: leaf + 1, probe_params)
                    for _ in range(4)
                ]
                ledger.register("perf_gate.bloat", tree=bloat)
            try:
                by_owner = {r.owner: r.device_bytes for r in ledger.owners()}
                return sum(
                    by_owner.get(k, 0)
                    for k in ("train.params", "train.opt_state", "perf_gate.bloat")
                ) or None
            finally:
                if bloat is not None:
                    del bloat
                    ledger.unregister("perf_gate.bloat")

        train_state_bytes = memory_arm()
    finally:
        if owns_telemetry:
            telemetry.disable()
    measurements = {
        "probe": {
            "accum_steps": accum,
            "optimizer_steps": steps,
            "dim": dim,
            "batch": batch,
            "epochs": epochs,
            "prefetch": prefetch,
            "degrade": degrade,
        },
        "eager_steps_per_s": round(eager_sps, 2),
        "fused_steps_per_s": round(fused_sps, 2),
        "fused_vs_eager_ratio": round(fused_sps / max(eager_sps, 1e-9), 3),
        "eager_dispatches_per_step": eager_disp,
        "dispatches_per_step": fused_disp,
        "fused_host_blocked_ms_per_step": round(fused_blocked, 3),
        "eager_host_blocked_ms_per_step": round(eager_blocked, 3),
        "zero_active": zero_active,
        "goodput_productive_frac": round(goodput_summary["goodput_fraction"], 4),
        "goodput_elapsed_s": round(goodput_summary["elapsed_s"], 3),
        "goodput_conservation_error_s": goodput_summary["conservation_error_s"],
        "train_state_bytes_per_chip": train_state_bytes,
    }
    if zero_sps is not None:
        measurements.update(
            {
                "zero_steps_per_s": round(zero_sps, 2),
                "zero_vs_eager_ratio": round(zero_sps / max(eager_sps, 1e-9), 3),
                "zero_dispatches_per_step": zero_disp,
                "zero_host_blocked_ms_per_step": round(zero_blocked, 3),
                "zero_exposed_collective_frac": zero_exposed_frac,
            }
        )
        if zero_profile is not None and zero_exposed_frac is not None:
            measurements["zero_overlap_fraction"] = zero_profile.overlap_fraction
            measurements["zero_collective_ms"] = zero_profile.collective_ms
            measurements["zero_exposed_collective_ms"] = zero_profile.exposed_collective_ms
        if zero_profile_error is not None:
            measurements["zero_profile_error"] = zero_profile_error
    if pp_row is not None:
        measurements.update(pp_row)
    if serving_row is not None:
        measurements.update(serving_row)
    return measurements


def evaluate(measurements: dict, baseline: dict) -> list:
    """Judge measurements against the baseline; returns failure strings
    (empty == gate passes)."""
    failures = []
    max_disp = baseline.get("max_dispatches_per_step")
    if max_disp is not None and measurements["dispatches_per_step"] > max_disp + 1e-9:
        failures.append(
            f"dispatches/step {measurements['dispatches_per_step']:.2f} > "
            f"baseline max {max_disp} — the fused train step is no longer one "
            "dispatch per optimizer step"
        )
    min_ratio = baseline.get("min_fused_vs_eager_ratio")
    if min_ratio is not None and measurements["fused_vs_eager_ratio"] < min_ratio:
        failures.append(
            f"fused-vs-eager steps/s ratio {measurements['fused_vs_eager_ratio']:.3f} < "
            f"baseline min {min_ratio} — the fused-path speedup regressed"
        )
    max_blocked = baseline.get("max_fused_host_blocked_ms_per_step")
    if (
        max_blocked is not None
        and measurements["fused_host_blocked_ms_per_step"] > max_blocked
    ):
        failures.append(
            f"fused host-blocked {measurements['fused_host_blocked_ms_per_step']:.1f} "
            f"ms/step > baseline max {max_blocked} — a synchronous host wait "
            "crept back into the hot loop"
        )
    # ZeRO row: judged only when the arm ran (multi-device probe).  A run
    # where the sharded update silently fell back to the replicated one is
    # exactly the regression this row exists to catch.
    zero_active = measurements.get("zero_active")
    if zero_active is not None or "zero_dispatches_per_step" in measurements:
        if baseline.get("require_zero_active") and zero_active is False:
            failures.append(
                "zero_active is False — the ZeRO sharded update silently fell "
                "back to the replicated fused update"
            )
        max_zero_disp = baseline.get("max_zero_dispatches_per_step")
        if (
            max_zero_disp is not None
            and measurements.get("zero_dispatches_per_step") is not None
            and measurements["zero_dispatches_per_step"] > max_zero_disp + 1e-9
        ):
            failures.append(
                f"ZeRO dispatches/step {measurements['zero_dispatches_per_step']:.2f} > "
                f"baseline max {max_zero_disp} — the sharded update broke the "
                "one-dispatch fused window"
            )
        min_zero_ratio = baseline.get("min_zero_vs_eager_ratio")
        if (
            min_zero_ratio is not None
            and measurements.get("zero_vs_eager_ratio") is not None
            and measurements["zero_vs_eager_ratio"] < min_zero_ratio
        ):
            failures.append(
                f"ZeRO-vs-eager steps/s ratio {measurements['zero_vs_eager_ratio']:.3f} < "
                f"baseline min {min_zero_ratio} — the sharded update lost the "
                "fused-path speedup"
            )
        # Overlap row: the runtime comms/compute-overlap invariant from the
        # trace scan of the ZeRO arm.  A broken capture is a broken check —
        # it fails loudly rather than silently skipping the row.
        max_exposed = baseline.get("max_exposed_collective_frac")
        if max_exposed is not None:
            exposed_frac = measurements.get("zero_exposed_collective_frac")
            if exposed_frac is None:
                failures.append(
                    "exposed-collective audit produced no number ("
                    f"{measurements.get('zero_profile_error') or 'no trace analyzed'}) — "
                    "the overlap invariant went unchecked"
                )
            elif exposed_frac > max_exposed:
                failures.append(
                    f"exposed-collective fraction {exposed_frac:.3f} > baseline max "
                    f"{max_exposed} — ZeRO collectives are no longer hidden behind "
                    "compute (comms/compute overlap regressed)"
                )
    # goodput row: the wall-clock productive fraction of a fused epoch (the
    # attribution-ledger audit).  Like the overlap row, a missing number is
    # a broken check and fails loudly; the conservation residual must also
    # stay at float noise — a ledger that double-counts is no ledger.
    min_goodput = baseline.get("min_goodput_productive_frac")
    if min_goodput is not None:
        frac = measurements.get("goodput_productive_frac")
        if frac is None:
            failures.append(
                "goodput audit produced no number — the goodput row went "
                "unchecked"
            )
        elif frac < min_goodput:
            failures.append(
                f"goodput productive fraction {frac:.3f} < baseline min "
                f"{min_goodput} — wall-clock is leaking into badput "
                "(idle/input-wait) around the fused step"
            )
    max_conservation = baseline.get("max_goodput_conservation_error_s")
    if (
        max_conservation is not None
        and measurements.get("goodput_conservation_error_s") is not None
        and abs(measurements["goodput_conservation_error_s"]) > max_conservation
    ):
        failures.append(
            f"goodput conservation error "
            f"{measurements['goodput_conservation_error_s']} s exceeds "
            f"{max_conservation} — the ledger's categories no longer sum to "
            "the elapsed wall-clock window"
        )
    # memory row: per-chip footprint ceilings from the HBM ledger.  Like the
    # overlap and goodput rows, a missing number is a broken check and fails
    # loudly — a deleted registration hook must not silently un-gate memory.
    max_train_bytes = baseline.get("max_train_state_bytes_per_chip")
    if max_train_bytes is not None:
        train_bytes = measurements.get("train_state_bytes_per_chip")
        if train_bytes is None:
            failures.append(
                "memory audit produced no number — the train-state memory row "
                "went unchecked (ledger registration missing?)"
            )
        elif train_bytes > max_train_bytes:
            failures.append(
                f"train-state footprint {train_bytes} B/chip > baseline max "
                f"{max_train_bytes} — params+optimizer memory bloated past "
                "the committed per-chip ceiling"
            )
    max_pool_bytes = baseline.get("max_serving_pool_bytes_per_chip")
    if max_pool_bytes is not None and "serving_paged_vs_dense_ratio" in measurements:
        pool_bytes = measurements.get("serving_pool_bytes_per_chip")
        if pool_bytes is None:
            failures.append(
                "serving pool audit produced no number — the serving memory "
                "row went unchecked"
            )
        elif pool_bytes > max_pool_bytes:
            failures.append(
                f"serving KV pool {pool_bytes} B/chip > baseline max "
                f"{max_pool_bytes} — the paged pool's footprint bloated past "
                "the committed per-chip ceiling"
            )
    # pp row: judged only when the arm ran (multi-device probe).  An
    # "interleaved" request that silently built gpipe, a fused pp step that
    # regressed to per-tick dispatches, or an interleaved schedule slower
    # than gpipe are exactly the regressions this row exists to catch.
    if "pp_dispatches_per_step" in measurements:
        if baseline.get("require_pp_interleaved") and not measurements.get(
            "pp_interleaved_active"
        ):
            failures.append(
                "pp_interleaved_active is False — the interleaved pipeline "
                "schedule silently fell back to gpipe "
                f"(ticks {measurements.get('pp_interleaved_ticks')} vs gpipe "
                f"{measurements.get('pp_gpipe_ticks')})"
            )
        max_pp_disp = baseline.get("max_pp_dispatches_per_step")
        if max_pp_disp is not None:
            # BOTH schedules' fused steps must hold the one-dispatch invariant
            # (a schedule-conditional regression could break just one arm).
            for key, label in (
                ("pp_dispatches_per_step", "interleaved"),
                ("pp_gpipe_dispatches_per_step", "gpipe"),
            ):
                disp = measurements.get(key)
                if disp is not None and disp > max_pp_disp + 1e-9:
                    failures.append(
                        f"pp dispatches/step ({label}) {disp:.2f} > baseline max "
                        f"{max_pp_disp} — the fused pipeline-parallel train step "
                        "is no longer one dispatch per optimizer step"
                    )
        min_pp_ratio = baseline.get("min_interleaved_vs_gpipe_ratio")
        if (
            min_pp_ratio is not None
            and measurements.get("pp_interleaved_vs_gpipe_ratio") is not None
            and measurements["pp_interleaved_vs_gpipe_ratio"] < min_pp_ratio
        ):
            failures.append(
                f"interleaved-vs-gpipe steps/s ratio "
                f"{measurements['pp_interleaved_vs_gpipe_ratio']:.3f} < baseline min "
                f"{min_pp_ratio} — the interleaved schedule lost its bubble-shrink "
                "win over gpipe"
            )
    # serving row: judged only when the arm ran.  A paged decode that
    # silently fell back to the dense gather-view program, a tick that grew a
    # second dispatch, or a paged path slower than the dense one it replaces
    # are exactly the regressions this row exists to catch.
    if "serving_paged_vs_dense_ratio" in measurements:
        if baseline.get("require_serving_paged") and not measurements.get(
            "serving_paged_active"
        ):
            failures.append(
                "serving_paged_active is False — the serving decode silently "
                "fell back to the dense gather-view program"
            )
        max_serving_disp = baseline.get("max_serving_decode_dispatches_per_tick")
        if max_serving_disp is not None:
            disp = measurements.get("serving_decode_dispatches_per_tick")
            if disp is not None and disp > max_serving_disp + 1e-9:
                failures.append(
                    f"serving decode dispatches/tick {disp:.2f} > baseline max "
                    f"{max_serving_disp} — the paged decode is no longer one "
                    "fused dispatch per engine tick"
                )
        min_serving_ratio = baseline.get("min_paged_vs_dense_ratio")
        if (
            min_serving_ratio is not None
            and measurements["serving_paged_vs_dense_ratio"] < min_serving_ratio
        ):
            failures.append(
                f"paged-vs-dense decode steps/s ratio "
                f"{measurements['serving_paged_vs_dense_ratio']:.3f} < baseline min "
                f"{min_serving_ratio} — the serving decode fast path lost its "
                "win over the dense gather-view program"
            )
    # spec row: judged only when the arm ran.  A speculative config that
    # silently decodes greedily (drafter never fires, verify program lost),
    # an accept/rewind bug that diverges from greedy, or a verify dispatch
    # slower per token than the single-token program it replaces are exactly
    # the regressions this row exists to catch.
    if "serving_spec_vs_greedy_itl_ratio" in measurements:
        if baseline.get("require_spec_active"):
            if not measurements.get("serving_spec_active"):
                failures.append(
                    "serving_spec_active is False — speculative decode "
                    "silently fell back to plain greedy (no drafts accepted "
                    "or no multi-token dispatches landed)"
                )
            if measurements.get("serving_spec_token_identical") is False:
                failures.append(
                    "speculative serving outputs diverged from the greedy "
                    "arm — the per-slot accept/rewind contract is broken"
                )
        min_spec_ratio = baseline.get("min_spec_vs_greedy_itl_ratio")
        if (
            min_spec_ratio is not None
            and measurements["serving_spec_vs_greedy_itl_ratio"] < min_spec_ratio
        ):
            failures.append(
                f"spec-vs-greedy inter-token latency ratio "
                f"{measurements['serving_spec_vs_greedy_itl_ratio']:.3f} < baseline "
                f"min {min_spec_ratio} — draft-then-verify stopped beating "
                "one-token-per-dispatch greedy decode"
            )
    # tiering row: judged only when the arm ran.  A preempted request that
    # silently re-prefills instead of resuming from its host-demoted blocks,
    # a migration round trip that corrupts the KV (token divergence), or a
    # migrated resume slower than the re-prefill it replaces are exactly the
    # regressions this row exists to catch.
    if "serving_migrated_vs_reprefill_ratio" in measurements:
        if baseline.get("require_tiering_active"):
            if not measurements.get("serving_tiering_active"):
                failures.append(
                    "serving_tiering_active is False — preempted requests are "
                    "not resuming from host-demoted KV blocks (no promotions "
                    "landed, a fallback re-prefill fired, or prefill "
                    "dispatches grew past the single-admission count)"
                )
            if measurements.get("serving_tiering_token_identical") is False:
                failures.append(
                    "tiered serving outputs diverged from the untiered arm — "
                    "the HBM->host->HBM round trip corrupted KV state"
                )
        min_tier_ratio = baseline.get("min_migrated_resume_vs_reprefill_ratio")
        if (
            min_tier_ratio is not None
            and measurements["serving_migrated_vs_reprefill_ratio"] < min_tier_ratio
        ):
            failures.append(
                f"migrated-vs-re-prefill resume ratio "
                f"{measurements['serving_migrated_vs_reprefill_ratio']:.3f} < "
                f"baseline min {min_tier_ratio} — resuming a preempted request "
                "from the host tier stopped beating re-prefilling it from "
                "scratch"
            )
    return failures


def run_gate(baseline_path: Optional[str] = None, probe_kwargs: Optional[dict] = None) -> int:
    """Run probe + evaluate; prints the verdict, returns a process rc."""
    baseline = load_baseline(baseline_path)
    probe_cfg = dict(baseline.get("probe") or {})
    probe_cfg.update(probe_kwargs or {})
    measurements = run_probe(**probe_cfg)
    print(json.dumps({"perf_gate": measurements}), flush=True)
    failures = evaluate(measurements, baseline)
    if failures:
        for failure in failures:
            print(f"PERF GATE FAIL: {failure}", file=sys.stderr, flush=True)
        return 1
    zero_note = ""
    if measurements.get("zero_vs_eager_ratio") is not None:
        zero_note = (
            f", ZeRO {measurements['zero_vs_eager_ratio']}x at "
            f"{measurements['zero_dispatches_per_step']:.0f} dispatch/step"
        )
        if measurements.get("zero_exposed_collective_frac") is not None:
            zero_note += (
                f", exposed comms {measurements['zero_exposed_collective_frac']:.2f} "
                "of collective time"
            )
    elif measurements.get("zero_active") is None:
        zero_note = ", ZeRO row skipped (single-device probe)"
    if measurements.get("pp_interleaved_vs_gpipe_ratio") is not None:
        zero_note += (
            f", pp interleaved/gpipe {measurements['pp_interleaved_vs_gpipe_ratio']}x "
            f"at {measurements['pp_dispatches_per_step']:.0f} dispatch/step "
            f"(analytic bubble {measurements['pp_analytic_bubble_gpipe']} -> "
            f"{measurements['pp_analytic_bubble_interleaved']})"
        )
    if measurements.get("goodput_productive_frac") is not None:
        zero_note += (
            f", goodput {measurements['goodput_productive_frac']:.2f} productive"
        )
    if measurements.get("serving_paged_vs_dense_ratio") is not None:
        zero_note += (
            f", serving paged/dense {measurements['serving_paged_vs_dense_ratio']}x "
            f"at {measurements['serving_decode_dispatches_per_tick']:.0f} "
            "dispatch/tick"
        )
    if measurements.get("serving_migrated_vs_reprefill_ratio") is not None:
        zero_note += (
            f", tiering migrated/re-prefill resume "
            f"{measurements['serving_migrated_vs_reprefill_ratio']}x"
        )
    if measurements.get("train_state_bytes_per_chip") is not None:
        zero_note += (
            f", train state {measurements['train_state_bytes_per_chip']} B/chip"
        )
        if measurements.get("serving_pool_bytes_per_chip") is not None:
            zero_note += (
                f", serving pool {measurements['serving_pool_bytes_per_chip']} B/chip"
            )
    print(
        "perf-gate OK — "
        f"fused/eager {measurements['fused_vs_eager_ratio']}x "
        f"({measurements['eager_steps_per_s']} -> {measurements['fused_steps_per_s']} steps/s), "
        f"{measurements['dispatches_per_step']:.0f} dispatch/step, "
        f"host-blocked {measurements['fused_host_blocked_ms_per_step']} ms/step"
        + zero_note
    )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m accelerate_tpu.pipeline.perf_gate",
        description="CPU-tier perf-regression gate for the fused train step.",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help=f"baseline JSON (default: {os.path.normpath(DEFAULT_BASELINE_PATH)})",
    )
    args = parser.parse_args(argv)
    return run_gate(args.baseline)


if __name__ == "__main__":
    sys.exit(main())
