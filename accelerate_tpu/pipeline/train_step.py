"""Fused train step: ONE jitted, buffer-donated dispatch per optimizer step.

The eager hot loop pays three Python→XLA dispatch sites per micro-batch
(the fused forward+backward jit, the host-side gradient scale/accumulate,
and the jitted optax update at the window boundary) — ``3 × accum_steps``
dispatches per optimizer step, with the device idling on host work between
each.  ``accelerator.make_train_step(model, optimizer)`` collapses the whole
window into one compiled program:

- forward + backward for every micro-batch (``lax.scan`` over the stacked
  micro-batch window when ``gradient_accumulation_steps > 1``),
- gradient accumulation (same ``g * (1/accum)`` scaling and addition order
  as the eager ``backward()`` path, so numerics are bit-exact),
- optional value/global-norm clipping and the optax update — literally the
  eager path's ``_update_body``, traced into the same program.

Params and optimizer state are donated, so the update is in-place in device
memory and the gradient window never materializes on the host.

``zero=True`` (or ``ACCELERATE_TPU_ZERO=1``) swaps the window's gradient
engine for the ZeRO cross-replica sharded update (``parallel/zero.py``):
per-device forward+backward under a manual dp region, per-leaf
reduce-scatter instead of the monolithic gradient all-reduce, the clip +
optax update on the local shard (opt state lives dp-sharded in HBM between
steps), and one params all-gather per window — still a single dispatch, and
bit-exact with the unsharded step on power-of-two dp degrees.

Pipeline parallelism composes the same way: on a pp mesh the prepared
model's forward IS the compiled pipeline scan (the torch-bridge pipelined
lowering, or ``parallel.pipeline.pipeline_llama_model`` for the native
flagship path), so the fused step wraps the whole microbatch schedule —
gpipe or interleaved — plus backward, clipping, the health gate and the
optax update in ONE donated dispatch per optimizer step.  ``pp_active`` /
``pp_degree`` record that the built program pipelines (the observability
twin of ``zero_active``); ZeRO requests on a pp mesh keep their existing
warning-fallback (``zero.supported`` declines model axes).
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

import jax
import jax.numpy as jnp

from ..telemetry import get_telemetry as _get_telemetry
from ..telemetry import span as _span

__all__ = ["TrainStep", "make_train_step"]


def _as_args_kwargs(batch):
    """One micro-batch → the (args, kwargs) the prepared model is called with:
    mappings become keyword arguments (the ``model(**batch)`` shape), tuples
    positional, anything else a single positional argument.  (An accumulation
    WINDOW is a ``list`` — only lists are unpacked by ``__call__``, so a
    tuple micro-batch is never mistaken for a window.)"""
    if isinstance(batch, Mapping):
        return (), dict(batch)
    if isinstance(batch, tuple):
        return batch, {}
    return (batch,), {}


class TrainStep:
    """Callable returned by :meth:`Accelerator.make_train_step`.

    ``step_fn(batch)`` runs one full optimizer step from one micro-batch
    (``accum_steps == 1``); ``step_fn([b1, ..., bN])`` (or ``step_fn(b1, ...,
    bN)``) runs the whole N-micro-batch accumulation window in the same single
    dispatch.  Returns the micro-batch loss (scalar when ``accum_steps == 1``,
    else the per-micro-batch loss vector) — bit-exact with the eager
    ``model(...)`` / ``backward()`` / ``optimizer.step()`` sequence.

    The wrapped model/optimizer stay the source of truth: parameters and
    optimizer state are read from them at every call and written back after,
    so checkpointing (``save_state``/``load_state``/``resume_from_latest``),
    LR scheduling and ``check_preemption()`` step boundaries keep working
    unchanged around the fused loop.
    """

    def __init__(
        self,
        accelerator,
        model,
        optimizer,
        accum_steps: Optional[int] = None,
        clip_norm: Optional[float] = None,
        clip_value: Optional[float] = None,
        zero=None,
    ):
        from ..accelerator import PreparedModel
        from ..optimizer import AcceleratedOptimizer

        if not isinstance(model, PreparedModel):
            raise TypeError(
                "make_train_step needs the PreparedModel returned by prepare(); "
                f"got {type(model).__name__}"
            )
        if not isinstance(optimizer, AcceleratedOptimizer):
            raise TypeError(
                "make_train_step needs the AcceleratedOptimizer returned by "
                f"prepare(); got {type(optimizer).__name__}"
            )
        if optimizer.model is not model:
            raise ValueError(
                "optimizer is not paired with this model — prepare them together "
                "(the optax state is built from the model's sharded params)"
            )
        self.accelerator = accelerator
        self.model = model
        self.optimizer = optimizer
        self.accum_steps = int(
            accum_steps
            if accum_steps is not None
            else accelerator.gradient_accumulation_steps
        )
        if self.accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {self.accum_steps}")
        # Persistent clips for this step fn; None defers to the optimizer's
        # (dialect-configured) persistent clips.  One-shot arms from
        # ``accelerator.clip_grad_{norm,value}_`` still win for one call.
        self.clip_norm = clip_norm
        self.clip_value = clip_value
        self.last_grad_norm = None
        # Pre-clip global grad norm of the last call, forced non-finite when
        # the in-program health gate skipped the update (loss or grads went
        # NaN/Inf) — what HealthGuard.check() reads.  Device scalar; floating
        # it is the caller's sync.
        self.last_health_norm = None
        self.step_count = 0
        # Python-side dispatch tally (telemetry-independent; the
        # ``pipeline.dispatches`` counter is the observable twin).
        self.dispatch_count = 0
        self._jit = None
        self._introspect_pending = True
        self._poison_armed = False  # resolved at trace time in _build_jit
        # ZeRO sharded weight update (parallel/zero.py): resolved here (arg >
        # ACCELERATE_TPU_ZERO env), eligibility-checked against the mesh at
        # _build_jit.  ``zero_active`` is the observable truth of which
        # program was built.
        from ..parallel.zero import ZeROConfig

        self.zero_config = ZeROConfig.resolve(zero)
        self.zero_active = False
        # pp observability: a fused step built on a pp mesh runs the whole
        # pipeline schedule (microbatch scan + backward + update) inside its
        # one dispatch.  The schedule itself lives in the prepared model's
        # forward; these fields are the perf gate's / bench's truth of what
        # was built (the zero_active pattern).
        mesh = getattr(accelerator, "mesh", None)
        self.pp_degree = int(dict(mesh.shape).get("pp", 1)) if mesh is not None else 1
        self.pp_active = self.pp_degree > 1

    # -- program construction -------------------------------------------------

    def _resolve_zero(self):
        """Eligibility-check the requested ZeRO config against the live mesh;
        arms ``zero_active`` and (on TPU) the overlap scheduler flags."""
        from ..parallel import zero as zero_mod

        if not self.zero_config.enabled:
            return
        ok, reason = zero_mod.supported(self.accelerator.mesh)
        if not ok:
            import warnings

            warnings.warn(
                f"ZeRO sharded update requested but unsupported here: {reason}. "
                "Falling back to the replicated fused update."
            )
            return
        self.zero_active = True
        if self.zero_config.overlap_effective:
            zero_mod.enable_overlap_flags()

    def _build_jit(self):
        if self._jit is not None:
            return
        from ..optimizer import _update_body
        from ..parallel import zero as zero_mod
        from ..resilience import faultinject

        self._resolve_zero()
        model = self.model
        mesh = self.accelerator.mesh
        tx_update = self.optimizer.tx.update
        accum = self.accum_steps
        scale = 1.0 / accum
        # Canonical-norm chunking degree: set on any ZeRO-capable mesh so
        # eager / fused / fused+ZeRO clip with the same reduction association
        # (optimizer._update_body) — ZeRO on or off.  Meshes with active
        # model axes keep the legacy norm: ZeRO can't run there, and chunked
        # reshapes of fsdp/tp-sharded gradients would invite resharding.
        ndp = zero_mod.zero_degree(mesh) if zero_mod.supported(mesh)[0] else 1
        norm_ndp = ndp if ndp > 1 else None
        # Trace-time fork: only a NaN-fault-armed process carries the poison
        # scalar in its program signature — production programs are untouched.
        # Either way the window stays ONE dispatch (the health-smoke proof).
        poison_armed = self._poison_armed = faultinject.nan_armed()
        # DDP comm-hook parity: the eager path casts each scaled micro-grad
        # to the sync dtype (bf16 under fp16/bf16 hooks) before accumulating
        # (PreparedModel._accumulate); the fused window must do the same or
        # switching to make_train_step silently changes numerics.
        sync_dtype = model._grad_sync_dtype

        def _scaled(g):
            s = g * scale
            if sync_dtype is not None and jnp.issubdtype(s.dtype, jnp.floating):
                s = s.astype(sync_dtype)
            return s

        def _loss_and_grads(params, batch):
            args, kwargs = batch

            def lossf(p):
                out = model._forward(p, args, kwargs)
                loss = out["loss"] if isinstance(out, dict) else out[0]
                return jnp.asarray(loss, jnp.float32).mean()

            return jax.value_and_grad(lossf)(params)

        if self.zero_active:
            grads_and_losses = self._build_zero_grads_fn(_loss_and_grads, _scaled)
            # Where the updated param shards gather back to: each leaf's live
            # sharding (replicated over dp on a pure-dp mesh).
            from jax.sharding import NamedSharding, PartitionSpec

            gather_sh = jax.tree_util.tree_map(
                lambda p: p.sharding
                if isinstance(p, jax.Array) and isinstance(p.sharding, NamedSharding)
                else NamedSharding(mesh, PartitionSpec()),
                model.params,
            )
        else:
            grads_and_losses = None
            gather_sh = None

        def step(params, opt_state, batches, clip_norm, clip_value, *fault):
            if grads_and_losses is not None:
                # ZeRO: per-device fwd/bwd + per-leaf reduce-scatter inside a
                # manual dp region; grads come back dp-SHARDED and the update
                # below runs on the local shard only.
                grads, losses = grads_and_losses(params, batches)
            elif accum == 1:
                loss, grads = _loss_and_grads(params, batches[0])
                # Eager parity: backward() accumulates grads * (1/accum) —
                # at accum == 1 the scale is exactly 1.0 (a no-op multiply).
                grads = jax.tree_util.tree_map(_scaled, grads)
                losses = loss
            else:
                stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)

                def body(acc, micro):
                    loss, grads = _loss_and_grads(params, micro)
                    # Same op order as the eager accumulation buffer:
                    # scale (and sync-dtype-cast) each micro-grad, then add
                    # (0 + g*s == g*s bitwise, so the zeros init matches
                    # "first assign").
                    acc = jax.tree_util.tree_map(
                        lambda a, g: a + _scaled(g), acc, grads
                    )
                    return acc, loss

                def _zeros_like_accum(p):
                    dtype = p.dtype
                    if sync_dtype is not None and jnp.issubdtype(dtype, jnp.floating):
                        dtype = sync_dtype
                    return jnp.zeros(jnp.shape(p), dtype)

                zeros = jax.tree_util.tree_map(_zeros_like_accum, params)
                grads, losses = jax.lax.scan(body, zeros, stacked)
            if poison_armed:
                # In-program fault injection: grads *= grad_scale (1.0 or NaN)
                # rides the existing dispatch instead of adding one.
                grads = jax.tree_util.tree_map(lambda g: g * fault[0], grads)
            # Health gate: the update must also zero out when any micro-loss
            # went non-finite — grads usually follow the loss, but an Inf loss
            # with (pathologically) finite grads must not slip an update in.
            losses_ok = jnp.all(jnp.isfinite(jnp.asarray(losses)))
            new_params, new_opt_state, gnorm, health_norm = _update_body(
                tx_update, params, opt_state, grads, clip_norm, clip_value,
                health_ok=losses_ok, norm_ndp=norm_ndp,
            )
            if grads_and_losses is not None:
                # All-gather: the dp-sharded updated params return to each
                # replica's layout for the next forward (the param-bytes
                # all-gather of the ZeRO ledger signature).
                new_params = jax.tree_util.tree_map(
                    jax.lax.with_sharding_constraint, new_params, gather_sh
                )
            return new_params, new_opt_state, losses, gnorm, health_norm

        donate = (0, 1)
        out_shardings = None
        if self.zero_active:
            # Re-place the live opt state onto its dp shards (host-offloaded
            # leaves keep their pinned-host kind: shard *then* offload), and
            # pin the carried-state outputs there via out_shardings.
            opt = self.optimizer
            opt.opt_state, _ = zero_mod.shard_opt_state(opt.opt_state, mesh)
            opt_sh = zero_mod.opt_state_shardings(opt.opt_state, mesh)
            # Donate params ONLY: donating params AND opt state together into
            # the shard_map program deterministically corrupts the XLA CPU
            # runtime heap (segfault after a few steps on jaxlib 0.4.x;
            # either donation alone is clean).  The un-donated opt-state copy
            # is dp-fold smaller under ZeRO than the replicated state it
            # replaces, so the transient costs less HBM than the feature
            # saves.
            donate = (0,)
            param_sh = jax.tree_util.tree_map(
                lambda x: x.sharding
                if isinstance(x, jax.Array)
                and isinstance(getattr(x, "sharding", None), jax.sharding.NamedSharding)
                else None,
                model.params,
            )
            out_shardings = (param_sh, opt_sh, None, None, None)
        elif self.optimizer._host_offload_requested:
            if jax.default_backend() == "tpu":
                # Pinned-host opt state must come back pinned (same contract
                # as the eager update, optimizer.py:_init_state).
                opt_sh = jax.tree_util.tree_map(
                    lambda x: x.sharding if isinstance(x, jax.Array) else None,
                    self.optimizer.opt_state,
                )
                out_shardings = (None, opt_sh, None, None, None)
            else:
                # CPU smoke path: donating a pinned_host input against a
                # device-kind output crashes; donate params only.
                donate = (0,)
        if out_shardings is not None:
            self._jit = jax.jit(step, donate_argnums=donate, out_shardings=out_shardings)
        else:
            self._jit = jax.jit(step, donate_argnums=donate)
        # Manifest observability: record the layout the carried opt state
        # will have from now on (checkpointing threads it into manifest.json).
        self.optimizer._opt_state_layout = zero_mod.opt_state_layout(
            mesh, self.zero_active
        )
        # HBM ledger: the train state's long-lived reservations, computed
        # from the live trees' per-device sharded bytes AFTER ZeRO placement
        # (so the sharded opt state charges each chip its shard, and
        # host-offloaded moments land under host_bytes, not HBM).  The
        # ledger stores integers only — no reference survives to fight the
        # donated-buffer lifetimes.
        try:
            from ..telemetry.memledger import get_memory_ledger

            ledger = get_memory_ledger()
            ledger.register(
                "train.params",
                tree=self.model.params,
                detail={"zero_active": self.zero_active},
            )
            ledger.register(
                "train.opt_state",
                tree=self.optimizer.opt_state,
                detail={"zero_active": self.zero_active},
            )
        except Exception:
            pass

    def _build_zero_grads_fn(self, _loss_and_grads, _scaled):
        """Build the manual-dp gradient engine of the ZeRO step: a shard_map
        over the whole mesh in which each device runs forward+backward on its
        LOCAL micro-batch shard, ``psum_scatter``s every gradient leaf over
        the dp axes (the reduce-scatter — emitted per leaf, so the XLA
        latency-hiding scheduler can overlap each leaf's collective with the
        remaining backward), and accumulates accum windows on the local shard
        (one reduce-scatter per micro keeps the replica-sum-then-micro-sum
        association of the eager/fused paths — bit-exactness over comms
        volume; the scatter is still half an all-reduce per micro and the
        gather happens once per window).

        Returns ``grads_and_losses(params, batches) -> (shard_grads, losses)``
        where ``shard_grads`` is the dp-sharded global gradient tree and
        ``losses`` matches the unsharded step's shape (scalar, or [accum]).
        """
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from ..parallel import zero as zero_mod
        from ..parallel.sharding import manual_region

        mesh = self.accelerator.mesh
        model = self.model
        accum = self.accum_steps
        axes = zero_mod.zero_axes(mesh)
        degree = zero_mod.zero_degree(mesh)
        psum_axes = axes if len(axes) > 1 else axes[0]
        axis_entry = axes if len(axes) > 1 else axes[0]
        # 1/degree un-scales the per-lane loss seed (each lane differentiates
        # its LOCAL mean; the global mean is the lane-mean mean).  Exactly a
        # power of two on pow2 dp degrees — where the ZeRO step is bit-exact
        # against the unsharded one (docs/usage_guides/performance.md).
        lane_scale = 1.0 / degree
        params = model.params
        pspecs = jax.tree_util.tree_map(
            lambda p: zero_mod.shard_spec(tuple(jnp.shape(p)), axes, degree), params
        )

        def batch_spec(leaf):
            # Batch leaves are batch-major (dim 0) by the loader contract
            # (_GlobalBatchPlacer shards dim 0 of every ndim>=1 leaf).  A
            # non-divisible or scalar leaf stays replicated: every lane sees
            # the full value — identical math, no silent slicing.
            if hasattr(leaf, "ndim") and leaf.ndim >= 1 and leaf.shape[0] % degree == 0 and leaf.shape[0] > 0:
                return P(*((axis_entry,) + (None,) * (leaf.ndim - 1)))
            return P()

        def scatter(g):
            d = zero_mod.shard_dim(tuple(g.shape), degree)
            if d is None:
                # Unshardable leaf (no dim divisible by the dp degree): plain
                # psum — it stays replicated, and its update is replicated
                # too (same rule the norm chunking and opt-state placement
                # use, so all three agree).
                return jax.lax.psum(g, psum_axes)
            return jax.lax.psum_scatter(g, psum_axes, scatter_dimension=d, tiled=True)

        def one_micro(p, batch):
            # Per-device: fwd+bwd on the local lane, then the per-leaf
            # reduce-scatter, then the exact-pow2 lane unscale — giving each
            # device precisely the replica-summed global-mean gradient SHARD
            # the unsharded path's all-reduce would have given it in full.
            loss, grads = _loss_and_grads(p, batch)
            shards = jax.tree_util.tree_map(scatter, grads)
            shards = jax.tree_util.tree_map(lambda g: g * lane_scale, shards)
            return shards, loss

        def wrapped(p, *micros):
            if accum == 1:
                shards, loss = one_micro(p, micros[0])
                shards = jax.tree_util.tree_map(_scaled, shards)
                losses = loss
            else:
                stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *micros)

                def body(acc, micro):
                    shards, loss = one_micro(p, micro)
                    # Eager-order accumulation on the SHARD: replica-sum
                    # (the scatter) first, then scale/cast, then add — the
                    # same per-element association as the unsharded window.
                    acc = jax.tree_util.tree_map(
                        lambda a, g: a + _scaled(g), acc, shards
                    )
                    return acc, loss

                sync_dtype = model._grad_sync_dtype

                def _zeros_shard(leaf):
                    dtype = leaf.dtype
                    if sync_dtype is not None and jnp.issubdtype(dtype, jnp.floating):
                        dtype = sync_dtype
                    return jnp.zeros(
                        zero_mod.shard_shape(tuple(leaf.shape), degree), dtype
                    )

                zeros = jax.tree_util.tree_map(_zeros_shard, params)
                shards, losses = jax.lax.scan(body, zeros, stacked)
            # Lane losses ride out stacked on a leading dp dim; the caller
            # means over lanes (== the global mean, bit-exactly so when the
            # per-lane element count is a power of two).
            losses = jnp.expand_dims(jnp.asarray(losses), 0)
            return shards, losses

        lane_losses_spec = (
            P(axis_entry) if accum == 1 else P(axis_entry, None)
        )

        def grads_and_losses(params, batches):
            in_specs = (
                jax.tree_util.tree_map(lambda _: P(), params),
            ) + tuple(
                jax.tree_util.tree_map(batch_spec, b) for b in batches
            )
            with manual_region():
                shards, lane_losses = shard_map(
                    wrapped,
                    mesh=mesh,
                    in_specs=in_specs,
                    out_specs=(pspecs, lane_losses_spec),
                    check_rep=False,
                )(params, *batches)
            losses = jnp.mean(lane_losses, axis=0)
            if accum == 1:
                losses = jnp.squeeze(losses)
            return shards, losses

        return grads_and_losses

    def _maybe_introspect(self, jit_args):
        """First-call AOT capture of the fused program
        (``ACCELERATE_TPU_INTROSPECT=1``): cost/memory analysis, comms ledger
        and resharding lint flow through the same ``capture()`` hook the
        eager fused step uses — the one-dispatch program is observable too."""
        if not self._introspect_pending:
            return
        self._introspect_pending = False
        from ..telemetry import introspect as _introspect

        if not _introspect.enabled_from_env():
            return
        _introspect.capture(
            self._jit,
            jit_args,
            name=f"{self.model._program_label}.train_step",
            mesh=self.accelerator.mesh,
            declared_specs=self.model._param_specs,
            count_in_step=True,
        )

    # -- execution ------------------------------------------------------------

    def __call__(self, *batches):
        from ..accelerator import _torch_to_jax_tree

        # Only a LIST unpacks as the accumulation window: a tuple is a valid
        # single micro-batch (positional model args) and must not be split
        # into per-element "micro-batches".
        if len(batches) == 1 and isinstance(batches[0], list):
            batches = tuple(batches[0])
        if len(batches) != self.accum_steps:
            raise ValueError(
                f"fused train step was built for {self.accum_steps} micro-batch"
                f"{'es' if self.accum_steps > 1 else ''} per optimizer step but "
                f"received {len(batches)} — pass the whole accumulation window "
                "in one call as a LIST of micro-batches (a tuple is treated as "
                "one positional-args micro-batch)."
            )
        batches = tuple(
            _as_args_kwargs(_torch_to_jax_tree(b)) for b in batches
        )
        self._build_jit()
        opt = self.optimizer
        # Clip resolution mirrors the eager update: one-shot arms win once,
        # then this step fn's persistent clips, then the optimizer's.
        clip_norm = (
            opt._clip_norm_once
            if opt._clip_norm_once is not None
            else (self.clip_norm if self.clip_norm is not None else opt._clip_norm)
        )
        clip_value = (
            opt._clip_value_once
            if opt._clip_value_once is not None
            else (self.clip_value if self.clip_value is not None else opt._clip_value)
        )
        opt._clip_norm_once = None
        opt._clip_value_once = None
        jit_args = (
            self.model.params,
            opt.opt_state,
            batches,
            jnp.asarray(clip_norm if clip_norm is not None else -1.0, jnp.float32),
            jnp.asarray(clip_value if clip_value is not None else -1.0, jnp.float32),
        )
        if self._poison_armed:
            from ..resilience import faultinject

            poison = faultinject.grad_poison_scale(opt._step_count + 1)
            jit_args = jit_args + (
                jnp.asarray(1.0 if poison is None else poison, jnp.float32),
            )
        self._maybe_introspect(jit_args)
        try:
            with _span("pipeline.train_step"):
                new_params, new_opt_state, losses, gnorm, health_norm = self._jit(*jit_args)
        except Exception as e:
            # Params/opt-state are DONATED: an execution failure (e.g.
            # RESOURCE_EXHAUSTED mid-step) may have consumed the buffers the
            # model/optimizer still reference.  Trace-time failures leave
            # them intact (donation only consumes at execution) — in that
            # case re-raise as-is and the step is safely retryable.
            leaves = jax.tree_util.tree_leaves((self.model.params, opt.opt_state))
            consumed = any(
                x.is_deleted() for x in leaves
                if isinstance(x, jax.Array) and hasattr(x, "is_deleted")
            )
            if consumed:
                raise RuntimeError(
                    "fused train step failed AFTER its donated parameter/"
                    "optimizer buffers were consumed; in-process model state "
                    "is unrecoverable. Do not retry the step (e.g. via "
                    "find_executable_batch_size) — restore from the latest "
                    "checkpoint (accelerator.resume_from_latest / load_state) "
                    "or rebuild via prepare()."
                ) from e
            raise
        # Write-back: the model/optimizer stay the source of truth for
        # checkpointing, schedulers and any interleaved eager steps.
        self.model._set_params(new_params)
        self.model._clear_grads()
        opt.opt_state = new_opt_state
        opt._last_grad_norm = gnorm
        opt._last_health_norm = health_norm
        self.last_health_norm = health_norm
        opt._step_was_skipped = False
        opt._step_count += 1
        if opt.torch_optimizer is not None:
            opt.torch_optimizer._opt_called = True
            opt.torch_optimizer._step_count = (
                getattr(opt.torch_optimizer, "_step_count", 0) + 1
            )
        # A fused call IS a sync step — schedulers gate on this flag.
        opt.gradient_state._set_sync_gradients(True)
        self.last_grad_norm = gnorm
        self.step_count += 1
        self.dispatch_count += 1
        tel = _get_telemetry()
        tel.count_dispatch()
        tel.record_step()
        return losses


def make_train_step(
    accelerator,
    model,
    optimizer,
    accum_steps: Optional[int] = None,
    clip_norm: Optional[float] = None,
    clip_value: Optional[float] = None,
    zero=None,
) -> TrainStep:
    """Build a :class:`TrainStep` (the function behind
    :meth:`Accelerator.make_train_step`).  ``zero`` opts into the
    cross-replica sharded weight update (``parallel/zero.py``): ``True`` /
    ``False`` / a :class:`~accelerate_tpu.parallel.zero.ZeROConfig`; ``None``
    defers to ``ACCELERATE_TPU_ZERO``."""
    return TrainStep(
        accelerator,
        model,
        optimizer,
        accum_steps=accum_steps,
        clip_norm=clip_norm,
        clip_value=clip_value,
        zero=zero,
    )
