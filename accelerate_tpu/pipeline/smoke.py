"""Pipeline smoke: eager vs fused train step on CPU, dispatch-count proof.

Run via ``make pipeline-smoke`` (or ``python -m accelerate_tpu.pipeline.smoke``).
One process trains the same recipe twice over ``gradient_accumulation_steps=4``
windows:

1. **eager** — ``model(...)`` / ``backward()`` / ``optimizer.step()`` per
   micro-batch, with the prefetching dataloader (``prefetch_to_device=2``);
2. **fused** — ``accelerator.make_train_step(model, optimizer)``: the whole
   accumulation window in ONE jitted dispatch.

Asserts, from the telemetry ``pipeline.dispatches`` counter and the
``pipeline.dispatches_per_step`` gauge:

- the eager path costs ``3 × accum_steps`` dispatch sites per optimizer step,
- the fused path costs exactly **1** dispatch per accumulation window,
- per-micro-batch losses and final parameters are BIT-EXACT equal between the
  two paths, and
- the prefetcher preserved batch order (losses again bit-exact vs eager with
  prefetch off).

Exit code 0 only when every assertion holds.
"""

from __future__ import annotations

import os
import sys
import tempfile

ACCUM = 4
WINDOWS = 4


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()
    # Hermetic: the smoke proves dispatch counts, not the persistent cache.
    os.environ.setdefault("ACCELERATE_TPU_COMPILE_CACHE", "")

    import numpy as np

    from accelerate_tpu import telemetry

    tel = telemetry.enable(dir=tempfile.mkdtemp(prefix="atpu_pipeline_smoke_"))

    import torch
    from torch.utils.data import DataLoader

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.test_utils import RegressionDataset, RegressionModelWithLoss
    from accelerate_tpu.test_utils.training import regression_collate
    from accelerate_tpu.utils import DataLoaderConfiguration, set_seed

    # 2 virtual devices x batch_size 2 = global batch 4; ACCUM x WINDOWS
    # global batches per epoch.
    n_samples = 4 * ACCUM * WINDOWS

    def build(prefetch: int):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        set_seed(1234)
        accelerator = Accelerator(
            gradient_accumulation_steps=ACCUM,
            dataloader_config=DataLoaderConfiguration(prefetch_to_device=prefetch),
        )
        model = RegressionModelWithLoss()
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        dl = DataLoader(
            list(RegressionDataset(length=n_samples)),
            batch_size=2,
            collate_fn=regression_collate,
        )
        model, opt, dl = accelerator.prepare(model, opt, dl)
        return accelerator, model, opt, dl

    dispatches = tel.registry.counter("pipeline.dispatches")

    # -- eager path (prefetch ON: also proves ordering under the prefetcher) --
    accelerator, model, opt, dl = build(prefetch=2)
    eager_losses = []
    mark = dispatches.value
    windows = 0
    for batch in dl:
        with accelerator.accumulate(model):
            out = model(x=batch["x"], y=batch["y"])
            accelerator.backward(out.loss)
            opt.step()
            opt.zero_grad()
            eager_losses.append(float(out.loss.detach()))
        if accelerator.sync_gradients:
            windows += 1
    eager_dispatches = dispatches.value - mark
    eager_params = model.state_dict()
    eager_gauge = tel.registry.gauge("pipeline.dispatches_per_step").value
    assert windows == WINDOWS, f"expected {WINDOWS} windows, got {windows}"
    assert eager_dispatches == 3 * ACCUM * windows, (
        f"eager path: expected 3 x accum x windows = {3 * ACCUM * windows} "
        f"dispatches, counted {eager_dispatches}"
    )
    assert eager_gauge == 3 * ACCUM, f"eager dispatches/step gauge: {eager_gauge}"

    # -- eager path, prefetch OFF: the prefetcher must not reorder batches ----
    accelerator, model, opt, dl = build(prefetch=0)
    sync_losses = []
    for batch in dl:
        with accelerator.accumulate(model):
            out = model(x=batch["x"], y=batch["y"])
            accelerator.backward(out.loss)
            opt.step()
            opt.zero_grad()
            sync_losses.append(float(out.loss.detach()))
    assert sync_losses == eager_losses, "prefetch-on losses diverged from prefetch-off"

    # -- fused path -----------------------------------------------------------
    accelerator, model, opt, dl = build(prefetch=2)
    step_fn = accelerator.make_train_step(model, opt)
    fused_losses = []
    mark = dispatches.value
    window = []
    for batch in dl:
        window.append(batch)
        if len(window) == ACCUM:
            losses = step_fn(window)
            fused_losses.extend(float(x) for x in np.asarray(losses))
            window = []
    fused_dispatches = dispatches.value - mark
    fused_params = model.state_dict()
    fused_gauge = tel.registry.gauge("pipeline.dispatches_per_step").value
    assert fused_dispatches == WINDOWS, (
        f"fused path: expected 1 dispatch per window ({WINDOWS}), "
        f"counted {fused_dispatches}"
    )
    assert fused_gauge == 1, f"fused dispatches/step gauge: {fused_gauge}"

    # -- numerics: bit-exact equivalence --------------------------------------
    assert fused_losses == eager_losses, (
        f"fused losses diverged: {fused_losses[:4]} vs {eager_losses[:4]}"
    )
    for key in eager_params:
        assert np.array_equal(eager_params[key], fused_params[key]), (
            f"param {key} diverged: {eager_params[key]} vs {fused_params[key]}"
        )

    host_blocked = tel.registry.histogram("pipeline.host_blocked_ms").summary()
    print(
        "pipeline-smoke OK — "
        f"eager {eager_dispatches} dispatches ({3 * ACCUM}/window), "
        f"fused {fused_dispatches} ({WINDOWS} windows, 1/window), "
        f"{len(eager_losses)} micro-losses bit-exact, "
        f"prefetch host-blocked p50 {host_blocked.get('p50', 0):.2f} ms"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
