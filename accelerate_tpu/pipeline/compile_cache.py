"""Persistent XLA compilation cache — default-on.

Warmup compiles are the dominant startup cost of a large GSPMD program
(minutes at scale); XLA can serialize compiled executables and re-load them
keyed by (HLO, flags, topology).  This module turns that cache on by default
for every :class:`Accelerator` run:

- ``ACCELERATE_TPU_COMPILE_CACHE`` unset → cache at
  ``~/.cache/accelerate_tpu/xla_cache`` (created on demand);
- ``ACCELERATE_TPU_COMPILE_CACHE=/path`` → cache there;
- ``ACCELERATE_TPU_COMPILE_CACHE=`` (set but empty) → cache OFF.

Because the cache is default-on (and caches every program, however small),
the directory is bounded: jax's LRU eviction is configured to
``ACCELERATE_TPU_COMPILE_CACHE_MAX_BYTES`` (default 1 GiB; ``0`` or negative
→ unbounded) so long-lived dev machines and shared ``$HOME`` filesystems
never grow it without limit.

Cache *hits* are surfaced through the telemetry compile counters: jax emits a
``/jax/compilation_cache/cache_hits`` monitoring event per hit, which
telemetry's listener tallies as ``jit.cache_hits`` next to the existing
``jit.compiles`` miss counter (every backend compile is, by definition, a
persistent-cache miss).
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = [
    "ENV_COMPILE_CACHE",
    "ENV_COMPILE_CACHE_MAX_BYTES",
    "DEFAULT_COMPILE_CACHE_DIR",
    "DEFAULT_COMPILE_CACHE_MAX_BYTES",
    "compile_cache_dir_from_env",
    "compile_cache_max_bytes_from_env",
    "enable_compile_cache",
    "maybe_enable_compile_cache_from_env",
]

ENV_COMPILE_CACHE = "ACCELERATE_TPU_COMPILE_CACHE"
ENV_COMPILE_CACHE_MAX_BYTES = "ACCELERATE_TPU_COMPILE_CACHE_MAX_BYTES"
DEFAULT_COMPILE_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "accelerate_tpu", "xla_cache"
)
DEFAULT_COMPILE_CACHE_MAX_BYTES = 1 << 30  # 1 GiB LRU bound

_applied_dir: Optional[str] = None


def compile_cache_dir_from_env() -> Optional[str]:
    """Resolve the cache directory from the environment: ``None`` means
    explicitly disabled (env set to empty), otherwise the directory to use."""
    raw = os.environ.get(ENV_COMPILE_CACHE)
    if raw is None:
        return DEFAULT_COMPILE_CACHE_DIR
    raw = raw.strip()
    if not raw:
        return None
    return os.path.expanduser(raw)


def compile_cache_max_bytes_from_env() -> int:
    """Size bound for the cache directory: default 1 GiB; ``0`` or negative
    (or unparseable) opts out of eviction (jax's ``-1`` = unbounded)."""
    raw = os.environ.get(ENV_COMPILE_CACHE_MAX_BYTES)
    if raw is None or not raw.strip():
        return DEFAULT_COMPILE_CACHE_MAX_BYTES
    try:
        max_bytes = int(raw.strip())
    except ValueError:
        import warnings

        warnings.warn(
            f"{ENV_COMPILE_CACHE_MAX_BYTES}={raw!r} is not an integer; "
            "leaving the compilation cache unbounded"
        )
        return -1
    return max_bytes if max_bytes > 0 else -1


def enable_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent compilation cache at ``cache_dir`` (default:
    the env-resolved directory).  Returns the active directory, or ``None``
    when the cache is disabled.  Idempotent; never raises — a read-only
    filesystem must not take down training, it just forfeits the cache."""
    global _applied_dir
    if cache_dir is None:
        cache_dir = compile_cache_dir_from_env()
    if cache_dir is None:
        return None
    if _applied_dir == cache_dir:
        return _applied_dir
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache every program: the default 1s floor skips exactly the small
        # programs a CPU-smoke run compiles, and at TPU scale everything
        # worth running clears 1s anyway.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # ...but a default-on cache-everything policy needs a bound, or the
        # directory grows forever on long-lived machines: LRU-evict past
        # the configured size (default 1 GiB).
        jax.config.update(
            "jax_compilation_cache_max_size", compile_cache_max_bytes_from_env()
        )
        # jax latches "cache unused/initialized" on the FIRST compile; a
        # process that already compiled something (warmup, an earlier
        # Accelerator with the cache off) must reset that latch or the new
        # dir is silently ignored.
        from jax.experimental.compilation_cache import compilation_cache as _cc

        _cc.reset_cache()
    except Exception as e:  # pragma: no cover - fs/backend specific
        import warnings

        warnings.warn(f"persistent compilation cache unavailable ({e}); continuing without it")
        return None
    _applied_dir = cache_dir
    return _applied_dir


def maybe_enable_compile_cache_from_env() -> Optional[str]:
    """Default-on hook called by ``Accelerator.__init__``: enable the cache
    unless ``$ACCELERATE_TPU_COMPILE_CACHE`` is set to the empty string."""
    return enable_compile_cache()
