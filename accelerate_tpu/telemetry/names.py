"""Canonical registry of every telemetry name the codebase emits.

The metric surface is now large enough to drift: a renamed counter silently
breaks dashboards, the Prometheus exporter, the report renderer, and every
consumer grepping a JSONL stream.  This module is the single source of truth
— one frozen set per kind — and ``tests/test_metric_names.py`` is the lint:
it greps every emit site in ``accelerate_tpu/`` and fails when

- an emitted name is missing from this registry (undocumented drift), or
- a registered name never appears under ``docs/`` (documented nowhere).

Adding a metric therefore means three edits, on purpose: the emit site, this
registry, and the docs table (``docs/package_reference/telemetry.md`` holds
the full catalogue).  Dynamic (f-string) names must match a pattern in
:data:`DYNAMIC_PATTERNS`.
"""

from __future__ import annotations

import re

__all__ = [
    "COUNTERS",
    "GAUGES",
    "HISTOGRAMS",
    "EVENTS",
    "DYNAMIC_PATTERNS",
    "all_names",
    "matches_dynamic",
]

COUNTERS = frozenset({
    "chaos.cycles",
    "dataloader.batches",
    "elastic.reshards",
    "fleet.deadline_errors",
    "fleet.elastic_restarts",
    "fleet.wedged_workers",
    "fleet.worker_deaths",
    "health.nonfinite_grads",
    "health.quarantine_skips",
    "health.quarantined_batches",
    "health.rewinds",
    "health.skipped_steps",
    "jit.cache_hits",
    "jit.compiles",
    "memory.oom_halvings",
    "memory.oom_postmortems",
    "pipeline.dispatches",
    "resilience.gave_up",
    "resilience.preempt_checkpoints",
    "resilience.preempt_signals",
    "resilience.retries",
    "sentinel.anomalies",
    "serving.completed",
    "serving.deadline_expired",
    "serving.decode_dispatches",
    "serving.decode_gather_bytes",
    "serving.drains",
    "serving.journal_recoveries",
    "serving.preempted",
    "serving.prefill_dispatches",
    "serving.prefix_blocks_reused",
    "serving.prefix_cow_copies",
    "serving.prefix_hits",
    "serving.quarantined",
    "serving.requests",
    "serving.shed",
    "serving.spec.accepted",
    "serving.spec.proposed",
    "serving.spec.rounds",
    "serving.tier.demoted_blocks",
    "serving.tier.demotions",
    "serving.tier.fallback_reprefills",
    "serving.tier.promotions",
    "serving.tokens",
    "stall.count",
    "step.count",
})

GAUGES = frozenset({
    "goodput.attributed_s",
    "goodput.elapsed_s",
    "goodput.fleet_fraction",
    "goodput.fleet_hosts",
    "goodput.fraction",
    "goodput.straggler_count",
    # per-category ledger gauges (goodput.{category}_s)
    "goodput.compile_s",
    "goodput.checkpoint_s",
    "goodput.device_acquire_s",
    "goodput.input_wait_s",
    "goodput.rewind_replay_s",
    "goodput.productive_s",
    "goodput.preempt_s",
    "goodput.idle_s",
    "hbm.bytes_in_use",
    "hbm.fleet_min_headroom_bytes",
    "hbm.peak_bytes",
    "hbm.stats_available",
    "health.last_grad_norm",
    "memory.attributed_bytes",
    "memory.headroom_bytes",
    "memory.unattributed_bytes",
    "pipeline.dispatches_per_step",
    "profile.collective_ms",
    "profile.device_busy_ms",
    "profile.exposed_collective_ms",
    "profile.overlap_fraction",
    "serving.active_slots",
    "serving.block_occupancy",
    "serving.blocks_used",
    "serving.decode_bucket_width",
    "serving.headroom_bytes",
    "serving.prefix_cache_blocks",
    "serving.queue_depth",
    "serving.slo.ttft_target_ms",
    "serving.slo.ttft_burn_rate",
    "serving.slo.inter_token_target_ms",
    "serving.slo.inter_token_burn_rate",
    "serving.spec.acceptance_rate",
    "serving.tier.host_bytes",
    "serving.tier.host_occupancy",
    "serving.tokens_per_dispatch",
    "step.mfu",
    "step.tokens_per_sec",
})

HISTOGRAMS = frozenset({
    "jit.compile_ms",
    "pipeline.host_blocked_ms",
    "serving.inter_token_ms",
    "serving.queue_wait_ms",
    "serving.requeue_wait_ms",
    "serving.tokens_per_s",
    "serving.ttft_ms",
    "step.time_ms",
})

EVENTS = frozenset({
    "chaos.cycle",
    "checkpoint.publish",
    "elastic.reshard",
    "fleet.deadline_error",
    "fleet.drain",
    "fleet.postmortem",
    "fleet.relaunch",
    "fleet.teardown",
    "fleet.wedged",
    "fleet.worker_dead",
    "health.rewind",
    "health.skip",
    "memory.low_headroom",
    "memory.oom_halving",
    "memory.oom_postmortem",
    "resilience.gave_up",
    "resilience.preempt_checkpoint",
    "resilience.preempt_signal",
    "resilience.retry",
    "sentinel.anomaly",
    "sentinel.profile_analysis_failed",
    "sentinel.profile_captured",
    "sentinel.profile_digest",
    "sentinel.profile_failed",
    "sentinel.profile_start",
    "sentinel.straggler",
    "serving.bucket_compile",
    "serving.drained",
    "serving.journal_recovered",
    "serving.quarantined",
    "serving.request_complete",
    "smoke.retried",
})

# Templates for f-string emit sites: the lint rewrites ``{expr}`` holes to a
# wildcard and requires the result to match one of these.
DYNAMIC_PATTERNS = (
    re.compile(r"^span\..+_ms$"),                 # span.{name}_ms histograms
    re.compile(r"^introspect\..+\.(flops|comms_bytes)$"),
    re.compile(r"^goodput\..+_s$"),               # goodput.{category}_s gauges
    # memory.owner.{slug}_bytes — per-owner HBM-ledger gauges (memledger.py)
    re.compile(r"^memory\.owner\..+_bytes$"),
    re.compile(r"^serving\.slo\..+_(target_ms|burn_rate)$"),
    # serving.trace.blame.{phase} counters + serving.trace.unattributed_ms
    # (the per-request trace family — see docs/package_reference/serving_tracing.md)
    re.compile(r"^serving\.trace\..+$"),
)


def all_names() -> frozenset:
    return COUNTERS | GAUGES | HISTOGRAMS | EVENTS


def matches_dynamic(name: str) -> bool:
    """True when ``name`` (an f-string template with ``{...}`` holes replaced
    by a placeholder, or a concrete runtime name) fits a dynamic pattern."""
    probe = re.sub(r"\{[^{}]*\}", "X", name)
    return any(p.match(probe) for p in DYNAMIC_PATTERNS)
