"""AOT compiled-program inspector: XLA cost/memory analysis, comms ledger,
resharding lint.

PR 1's telemetry answers *when* a step is slow; this module answers *why*:
given a compiled jax program it reports

- ``cost_analysis()`` — FLOPs and bytes accessed by the optimized executable
  (measured cost, not the 6ND estimate);
- ``memory_analysis()`` — the HBM breakdown: argument / output / temp /
  generated-code bytes;
- the **comms ledger** (``hlo_scan``): every collective XLA's SPMD partitioner
  inserted, with byte volumes per mesh axis and an estimated comms/compute
  time ratio;
- the **resharding lint**: arrays entering the step whose live sharding
  differs from what the compiled program expects (each call pays a
  device-to-device resharding copy), and large parameters left
  replicated-by-default on a mesh with active model axes (the
  under-constrained-annotation failure mode of GSPMD propagation).

Default-off.  ``ACCELERATE_TPU_INTROSPECT=1`` hooks it transparently into the
first call of every prepared model's compiled step (one extra AOT compile per
program — the jit cache is not shared with ``lower().compile()``); or call
:func:`inspect_compiled` / :func:`capture` directly.  Reports are written to
the telemetry JSONL sink as ``{"kind": "introspect", ...}`` records when
telemetry is enabled, and surfaced by ``telemetry.report``.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Any, Optional

import numpy as np

from .hlo_scan import CommsLedger, scan_hlo
from .metrics import peak_flops_per_chip

__all__ = [
    "ENV_INTROSPECT",
    "ProgramReport",
    "LintFinding",
    "enabled_from_env",
    "inspect_compiled",
    "capture",
    "lint_reshardings",
    "estimate_comms_compute_ratio",
]

ENV_INTROSPECT = "ACCELERATE_TPU_INTROSPECT"

_TRUTHY = {"1", "true", "yes", "on"}

# Per-chip interconnect bandwidth (bytes/s) by device kind — rough ICI figures
# for the comms/compute time ratio ONLY (order-of-magnitude triage, not a
# roofline).  Checked in order; "v5 lite"/"v5e" before "v5" (see
# metrics._PEAK_FLOPS_TABLE).
_ICI_BW_TABLE = (
    ("v5 lite", 1.6e11),
    ("v5e", 1.6e11),
    ("v5p", 4.8e11),
    ("v5", 4.8e11),
    ("v4", 2.4e11),
    ("v6", 3.6e11),
    ("trillium", 3.6e11),
)
_DEFAULT_ICI_BW = 1.0e11

# Params below this byte size are fine replicated (the min_num_params analog:
# sharding tiny arrays costs more in collective latency than it saves in HBM).
_REPLICATED_LINT_MIN_BYTES = 1 << 20

# Count of capture() invocations this process — the "zero overhead when the
# env flag is unset" tests assert this stays 0.
CAPTURE_COUNT = 0


def enabled_from_env() -> bool:
    return os.environ.get(ENV_INTROSPECT, "").strip().lower() in _TRUTHY


def _ici_bandwidth(device=None) -> float:
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        kind = device.device_kind.lower()
    except Exception:
        return _DEFAULT_ICI_BW
    for key, bw in _ICI_BW_TABLE:
        if key in kind:
            return bw
    return _DEFAULT_ICI_BW


@dataclasses.dataclass
class LintFinding:
    """One resharding-lint warning."""

    kind: str  # "implicit-reshard" | "replicated-by-default"
    path: str  # input pytree path
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ProgramReport:
    """Everything the inspector learned about one compiled program."""

    name: str
    flops: float  # cost_analysis FLOPs (per device, optimized program)
    bytes_accessed: float  # cost_analysis memory traffic
    memory: dict  # argument/output/temp/generated_code bytes (per device)
    ledger: CommsLedger
    comms_compute_ratio: Optional[float]  # est. comm time / compute time
    lint: list  # list[LintFinding]

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "memory": self.memory,
            "comms": self.ledger.to_dict(),
            "comms_compute_ratio": self.comms_compute_ratio,
            "lint": [f.to_dict() for f in self.lint],
        }


def estimate_comms_compute_ratio(
    comm_bytes: float, flops: float, device=None
) -> Optional[float]:
    """Estimated collective-time / compute-time ratio for one program.

    ``comm_bytes / ICI_bw`` over ``flops / peak_flops`` — both per device.  A
    ratio near or above 1 means the step is communication-bound and no kernel
    work will move the roofline; far below 1 means collectives are not the
    bottleneck.  Rough by construction (no overlap modeling, flat per-kind
    cost): use it to rank programs, not to predict step time.
    """
    if not flops or flops <= 0:
        return None
    try:
        peak = peak_flops_per_chip(device)
    except Exception:
        return None
    compute_s = flops / peak
    comm_s = float(comm_bytes) / _ici_bandwidth(device)
    if compute_s <= 0:
        return None
    return comm_s / compute_s


def _cost_analysis(compiled) -> dict:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    # jax < 0.5 returns a per-computation list; newer returns one dict.
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def _memory_analysis(compiled) -> dict:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    out = {}
    for key in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "generated_code_size_in_bytes",
        "alias_size_in_bytes",
    ):
        val = getattr(ma, key, None)
        if val is not None:
            out[key.replace("_size_in_bytes", "_bytes")] = int(val)
    return out


def _spec_of(sharding) -> Optional[tuple]:
    spec = getattr(sharding, "spec", None)
    return tuple(spec) if spec is not None else None


def _is_fully_replicated(sharding, ndim: int) -> bool:
    try:
        return bool(sharding.is_fully_replicated)
    except Exception:
        spec = _spec_of(sharding)
        return spec is None or all(s is None for s in spec)


def _leaf_paths(tree) -> list[str]:
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [
        "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        for path, _ in flat
    ]


def lint_reshardings(
    compiled,
    args: tuple,
    mesh=None,
    declared_specs: Any = None,
) -> list[LintFinding]:
    """Compare the shardings of arrays entering a compiled step against what
    the program expects (and, for params, what ``prepare()`` declared).

    ``args`` is the positional-arg tuple the program is called with (the same
    one it was lowered from).  Two findings:

    - **implicit-reshard** — a live input's sharding differs from the compiled
      program's expected input sharding: every call pays a resharding copy
      before the step body runs (the silent device_put GSPMD inserts).
    - **replicated-by-default** — a large (>=1 MiB) floating-point input ends
      up fully replicated although the mesh has active model axes
      (``fsdp``/``tp``/``ep``): nothing constrained it, so propagation fell
      back to replication — the under-constrained-annotation case of
      arXiv:2105.04663.  ``declared_specs`` (the PartitionSpec tree
      ``prepare()`` built, pytree-prefix of ``args[0]``) suppresses this for
      leaves the rules *deliberately* replicate.
    """
    import jax

    findings: list[LintFinding] = []
    try:
        expected, _ = compiled.input_shardings
        # One entry per *argument*, each a pytree of shardings mirroring that
        # argument's structure — flatten to align with the args' leaves.
        expected = jax.tree_util.tree_leaves(expected)
    except Exception:
        return findings
    leaves, _ = jax.tree_util.tree_flatten(args)
    paths = _leaf_paths(args)
    if len(expected) != len(leaves):
        return findings  # donated/pruned args changed the flat arity; bail

    model_axes_active = False
    if mesh is not None:
        model_axes_active = any(
            a in mesh.axis_names and mesh.shape[a] > 1 for a in ("fsdp", "tp", "ep")
        )

    declared_flat = None
    if declared_specs is not None:
        from jax.sharding import PartitionSpec

        try:
            declared_flat = jax.tree_util.tree_leaves(
                declared_specs,
                is_leaf=lambda s: s is None or isinstance(s, PartitionSpec),
            )
        except Exception:
            declared_flat = None

    for i, (leaf, want) in enumerate(zip(leaves, expected)):
        if not isinstance(leaf, jax.Array):
            continue
        path = paths[i] if i < len(paths) else str(i)
        have = leaf.sharding
        ndim = leaf.ndim
        equivalent = True
        try:
            equivalent = have.is_equivalent_to(want, ndim)
        except Exception:
            equivalent = _spec_of(have) == _spec_of(want)
        if not equivalent:
            findings.append(
                LintFinding(
                    kind="implicit-reshard",
                    path=path,
                    message=(
                        f"input {path!r} arrives as {_spec_of(have)} but the "
                        f"compiled step wants {_spec_of(want)} — every call "
                        "pays a resharding copy before the step runs. "
                        "device_put it onto the expected sharding once (or fix "
                        "the producing op's constraint)."
                    ),
                )
            )
            continue
        # Under-constrained check: large floating leaf, fully replicated, on a
        # mesh that could shard it — unless the declared spec says replicate.
        if not model_axes_active:
            continue
        if not np.issubdtype(np.dtype(leaf.dtype), np.floating):
            continue
        if leaf.size * leaf.dtype.itemsize < _REPLICATED_LINT_MIN_BYTES:
            continue
        if not _is_fully_replicated(want, ndim):
            continue
        if declared_flat is not None and i < len(declared_flat):
            spec = declared_flat[i]
            if spec is not None and any(s is not None for s in tuple(spec)):
                # Declared sharded but compiled replicated — propagation
                # dropped the annotation; that IS the finding.
                findings.append(
                    LintFinding(
                        kind="implicit-reshard",
                        path=path,
                        message=(
                            f"param {path!r} was declared {tuple(spec)} but the "
                            "compiled program runs it fully replicated — the "
                            "sharding annotation was lost before partitioning."
                        ),
                    )
                )
                continue
            if spec is not None:
                continue  # deliberately replicated by the rules: no finding
        findings.append(
            LintFinding(
                kind="replicated-by-default",
                path=path,
                message=(
                    f"input {path!r} ({leaf.size * leaf.dtype.itemsize} bytes) is "
                    "fully replicated on a mesh with active model axes — no "
                    "sharding rule constrained it, so GSPMD propagation fell "
                    "back to replication. Add a partition rule (or an "
                    "auto-fsdp spec) if this array should be sharded."
                ),
            )
        )
    return findings


def inspect_compiled(
    compiled,
    name: str = "program",
    mesh=None,
    args: Optional[tuple] = None,
    declared_specs: Any = None,
    device=None,
) -> ProgramReport:
    """Build a :class:`ProgramReport` from a ``jax.stages.Compiled`` — pure
    analysis, never executes the program."""
    cost = _cost_analysis(compiled)
    flops = float(cost.get("flops", 0.0) or 0.0)
    bytes_accessed = float(cost.get("bytes accessed", 0.0) or 0.0)
    memory = _memory_analysis(compiled)
    if memory:
        # Feed the HBM ledger's conservation contract: temp/scratch +
        # generated-code bytes are memory the *program* owns — neither a
        # registered live array nor unattributed residue (argument/output
        # bytes ARE live arrays and would double-count).
        from .memledger import get_memory_ledger

        get_memory_ledger().note_program_bytes(
            name,
            int(memory.get("temp_bytes", 0)) + int(memory.get("generated_code_bytes", 0)),
        )
    try:
        hlo = compiled.as_text()
    except Exception:
        hlo = ""
    ledger = scan_hlo(hlo, mesh)
    ratio = estimate_comms_compute_ratio(ledger.total_bytes, flops, device)
    lint = (
        lint_reshardings(compiled, args, mesh, declared_specs)
        if args is not None
        else []
    )
    return ProgramReport(
        name=name,
        flops=flops,
        bytes_accessed=bytes_accessed,
        memory=memory,
        ledger=ledger,
        comms_compute_ratio=ratio,
        lint=lint,
    )


def capture(
    jitted,
    args: tuple,
    name: str = "program",
    mesh=None,
    declared_specs: Any = None,
    warn: bool = True,
    count_in_step: bool = True,
) -> Optional[ProgramReport]:
    """AOT lower+compile ``jitted`` on ``args`` and inspect the result.

    The transparent hook behind ``ACCELERATE_TPU_INTROSPECT=1``: writes the
    report to the telemetry sink (when telemetry is enabled), feeds the
    measured FLOPs into the MFU collector, and emits each lint finding as a
    Python warning.  Never raises — introspection must not take down the
    training step it is observing.

    ``count_in_step``: whether this program runs once per training step and
    should therefore count toward the measured-cost MFU (the fused train
    step does; an eval forward or a bridge-mode partial program does not —
    summing those would systematically skew ``step.mfu``).
    """
    global CAPTURE_COUNT
    CAPTURE_COUNT += 1
    try:
        compiled = jitted.lower(*args).compile()
        report = inspect_compiled(
            compiled, name=name, mesh=mesh, args=args, declared_specs=declared_specs
        )
    except Exception as e:  # pragma: no cover - backend-specific failures
        warnings.warn(f"introspection of {name!r} failed: {e}")
        return None
    _publish(report, count_in_step=count_in_step)
    if warn:
        for finding in report.lint:
            warnings.warn(f"[resharding lint] {finding.message}")
    return report


def _publish(report: ProgramReport, count_in_step: bool = True) -> None:
    """Write the report into the telemetry stream and the MFU collector."""
    from .core import get_telemetry

    tel = get_telemetry()
    if report.flops > 0:
        # Measured-cost MFU: the step timer prefers the summed analyzed FLOPs
        # of the inspected step programs over any static 6ND estimate.
        if count_in_step:
            tel.step_timer.record_measured_flops(report.name, report.flops)
        tel.registry.gauge(f"introspect.{report.name}.flops").set(report.flops)
    if report.ledger.total_bytes:
        tel.registry.gauge(f"introspect.{report.name}.comms_bytes").set(
            report.ledger.total_bytes
        )
    if not tel.enabled:
        return
    tel.write({"kind": "introspect", **report.to_dict()})
