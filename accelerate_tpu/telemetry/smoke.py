"""Telemetry smoke: a 3-step CPU training loop with telemetry ON.

Run via ``make telemetry-smoke`` (or ``python -m accelerate_tpu.telemetry.smoke``).
Drives the instrumented hot paths end-to-end — Accelerator.prepare, data-loader
placement, backward, optimizer.step — then asserts the per-process JSONL file is
non-empty and fully parseable and prints the report summary.  Exit code 0 only
when every assertion holds.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    out_dir = tempfile.mkdtemp(prefix="atpu_telemetry_smoke_")

    from accelerate_tpu import telemetry

    tel = telemetry.enable(dir=out_dir, stall_timeout_s=300)

    import torch
    from torch.utils.data import DataLoader

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.test_utils import RegressionDataset, RegressionModelWithLoss

    def _collate(samples):
        return {
            "x": torch.tensor([s["x"] for s in samples]),
            "y": torch.tensor([s["y"] for s in samples]),
        }

    accelerator = Accelerator()
    ds = RegressionDataset(length=12)
    dl = DataLoader(list(ds), batch_size=4, collate_fn=_collate)
    model = RegressionModelWithLoss()
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    model, opt, dl = accelerator.prepare(model, opt, dl)

    steps = 0
    for batch in dl:  # 12 samples / batch 4 = exactly 3 steps
        out = model(x=batch["x"], y=batch["y"])
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        steps += 1
    assert steps == 3, f"expected 3 steps, ran {steps}"

    path = tel.jsonl_path
    telemetry.disable()  # flush the final metrics snapshot

    assert path is not None and os.path.exists(path), f"telemetry JSONL missing: {path}"
    with open(path) as f:
        lines = [line for line in f if line.strip()]
    assert lines, f"telemetry JSONL is empty: {path}"
    records = [json.loads(line) for line in lines]  # every line must parse

    kinds = {rec.get("kind") for rec in records}
    assert "span" in kinds, f"no span records in {path} (kinds: {kinds})"
    assert "metrics" in kinds, f"no final metrics snapshot in {path} (kinds: {kinds})"
    snapshot = next(r["snapshot"] for r in reversed(records) if r.get("kind") == "metrics")
    assert snapshot.get("step.count") == 3, f"step.count != 3 in snapshot: {snapshot}"
    assert snapshot.get("jit.compiles", 0) >= 1, f"no compiles recorded: {snapshot}"

    from .report import format_report, summarize

    print(format_report(summarize(records)))
    print(f"\ntelemetry-smoke OK — {len(records)} records in {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
