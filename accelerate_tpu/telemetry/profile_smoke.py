"""Profile-scanner smoke: capture a real trace of the fused ZeRO step on an
8-device CPU mesh and audit it (``make profile-smoke``, wired into
``make test``).

Asserts, end to end through the public surface:

1. ``jax.profiler`` capture of the ZeRO fused train step produces a trace the
   scanner can reconstruct (non-empty device timeline);
2. the timeline holds >= 1 collective-bucket op, the realized overlap
   fraction is finite, and exposed-collective ms <= total collective ms (the
   interval-arithmetic invariant);
3. the per-step segmentation finds the fused dispatches;
4. the SAME parser passes offline on the committed fixture in a subprocess
   with **no JAX devices at all** (``JAX_PLATFORMS=''`` never imported) —
   the postmortem workflow (analyze a trace from a dead TPU run on a laptop)
   needs exactly that;
5. ``telemetry.report --profile <dir> --json`` emits the machine-readable
   block bench/CI consume.

Run: ``env JAX_PLATFORMS=cpu python -m accelerate_tpu.telemetry.profile_smoke``
(docs/package_reference/profile.md).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "tests", "fixtures", "profile", "sample.trace.json.gz",
)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import numpy as np

    import jax
    import jax.numpy as jnp
    import optax

    from ..accelerator import Accelerator, JaxModel
    from ..parallel.sharding import data_sharding
    from ..state import AcceleratorState, GradientState, PartialState
    from ..utils.dataclasses import ParallelismConfig
    from . import profile_scan

    ndp = jax.device_count()
    assert ndp == 8, f"expected the forced 8-device CPU mesh, got {ndp}"
    steps, dim, batch = 4, 128, 16

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator(parallelism_config=ParallelismConfig(dp=ndp))
    params = {
        "w1": jax.random.normal(jax.random.PRNGKey(0), (dim, dim), jnp.float32) * 0.05,
        "w2": jax.random.normal(jax.random.PRNGKey(1), (dim, dim), jnp.float32) * 0.05,
    }

    def apply_fn(p, x, y):
        return {"loss": jnp.mean((jnp.tanh(x @ p["w1"]) @ p["w2"] - y) ** 2)}

    model, opt = acc.prepare(JaxModel(apply_fn, params), optax.adam(1e-3))
    step_fn = acc.make_train_step(model, opt, clip_norm=1.0, zero=True)
    sh = data_sharding(acc.mesh)

    def make_batch(i):
        x = np.asarray(jax.random.normal(jax.random.PRNGKey(10 + i), (batch, dim)), np.float32)
        y = np.asarray(jax.random.normal(jax.random.PRNGKey(20 + i), (batch, dim)), np.float32)
        return {"x": jax.device_put(x, sh), "y": jax.device_put(y, sh)}

    batches = [make_batch(i) for i in range(steps + 1)]
    float(np.asarray(step_fn(batches[0])))  # warmup: compiles outside the trace
    assert step_fn.zero_active, "ZeRO did not activate on the dp=8 mesh"

    # 1-3: live capture + audit ------------------------------------------------
    trace_dir = tempfile.mkdtemp(prefix="atpu_profile_smoke_")
    jax.profiler.start_trace(trace_dir)
    try:
        for i in range(1, steps + 1):
            float(np.asarray(step_fn(batches[i])))
    finally:
        jax.profiler.stop_trace()
    report = profile_scan.analyze_trace_dir(trace_dir)
    assert report.n_device_events > 0, "empty device timeline"
    assert report.collective_ms > 0, "no collective bucket in the ZeRO step trace"
    assert report.overlap_fraction is not None, "overlap fraction not finite"
    assert 0.0 <= report.overlap_fraction <= 1.0, report.overlap_fraction
    assert report.exposed_collective_ms <= report.collective_ms + 1e-9, (
        report.exposed_collective_ms, report.collective_ms,
    )
    assert report.steps, "no step segmentation"
    print(profile_scan.format_profile_report(report))

    # 4: same parser, zero JAX devices ----------------------------------------
    # JAX_PLATFORMS='' makes any backend/device touch raise in the child, so
    # a parse that survives proves the offline path needs no devices.
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = ""
    env.pop("XLA_FLAGS", None)
    check = (
        "from accelerate_tpu.telemetry import profile_scan\n"
        f"r = profile_scan.analyze_trace_file({FIXTURE!r})\n"
        "assert r.collective_ms == 0.18 and r.exposed_collective_ms == 0.11\n"
        "print('offline fixture OK', r.overlap_fraction)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", check], env=env, capture_output=True, text=True, timeout=120
    )
    if proc.returncode != 0:
        print(proc.stdout)
        print(proc.stderr, file=sys.stderr)
        raise RuntimeError("offline (deviceless) fixture parse failed")
    sys.stdout.write(proc.stdout)

    # 5: the machine-readable report path -------------------------------------
    from .report import main as report_main
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = report_main(["--profile", trace_dir, "--json"])
    assert rc == 0, "telemetry.report --profile --json failed"
    payload = json.loads(buf.getvalue())
    assert payload["profile"]["collective_ms"] == report.collective_ms

    print(
        "profile-smoke OK — ZeRO step trace: "
        f"{report.collective_ms} ms collective ({report.exposed_collective_ms} ms exposed, "
        f"overlap {100.0 * report.overlap_fraction:.1f}%) over {len(report.steps)} steps; "
        "offline fixture parse needed no devices; --json round-trips"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
