"""Black-box flight recorder: a durable timeline of the training hot path.

Telemetry (``core.py``) answers "how is the run doing" *while the process is
alive*; when a TPU run dies (preemption, OOM kill, a wedged tunnel, an
unhandled exception) the in-process registry evaporates with it and the
postmortem starts from nothing.  The flight recorder is the black box: a
bounded ring buffer of structured per-step events — step time, dispatches per
step, host-blocked ms, compile events, health-guard verdicts, checkpoint
publishes and I/O retries, preemption signals — flushed to a crash-safe JSONL
snapshot periodically and on every way a process can die that leaves Python
running long enough to write a file:

- **SIGTERM/SIGINT** — a *chaining* handler (records a ``signal`` event,
  flushes, then invokes whatever handler was installed before it).  It
  composes with :class:`~accelerate_tpu.resilience.PreemptionGuard`'s
  flags-only handler in either install order and never replaces it; with no
  other handler installed the default die-on-SIGTERM semantics are re-raised
  after the flush.
- **atexit** — normal interpreter shutdown.
- **unhandled exception** — a ``sys.excepthook`` wrapper records a ``crash``
  event (exception type + message) before delegating to the previous hook.

Only SIGKILL and a hard machine loss can outrun it, and even then the last
periodic flush (every ``flush_every`` events) is on disk.

The flush rewrites the whole ring snapshot into ``flightrec_p<proc>.jsonl``
via write-temp + atomic rename, so a crash *during* a flush leaves the
previous snapshot intact — the file on disk is always a complete, parseable
view of the last ``capacity`` events.  Summarize one with
``python -m accelerate_tpu.telemetry.report <dir>`` (the postmortem block).

An :class:`~accelerate_tpu.telemetry.sentinel.AnomalySentinel` watches the
step stream online: rolling-median slow-step detection, watchdog stalls, and
per-host straggler hooks.  The first anomaly triggers a one-shot
``jax.profiler`` trace window (``ACCELERATE_TPU_SENTINEL_PROFILE=0``
disables — the test suite does) so the profile of the *bad* steps is captured
without anyone watching the run.  The capture is then auto-analyzed off the
hot path by ``profile_scan`` and its attribution digest (exposed-collective
ms, overlap fraction, top ops) lands back in the ring as a
``sentinel.profile_digest`` event — the postmortem explains *why* the slow
step was slow, not just that it happened.

Default-off.  ``ACCELERATE_TPU_FLIGHTREC=1`` (honored by ``Accelerator()``
via ``telemetry.maybe_enable_from_env``) or ``flightrec.enable()`` turn it
on; enabling the recorder also enables telemetry — the recorder is fed by
telemetry's hooks (``record_step``, the compile listener, ``event()``), so a
recorder without telemetry would record nothing.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import signal
import sys
import threading
import time
from typing import Optional

from .sentinel import AnomalySentinel

__all__ = [
    "FlightRecorder",
    "get_flight_recorder",
    "enable",
    "disable",
    "maybe_enable_from_env",
    "ENV_ENABLE",
    "ENV_DIR",
    "ENV_CAPACITY",
    "ENV_FLUSH_EVERY",
    "ENV_SENTINEL_PROFILE",
]

ENV_ENABLE = "ACCELERATE_TPU_FLIGHTREC"
ENV_DIR = "ACCELERATE_TPU_FLIGHTREC_DIR"
ENV_CAPACITY = "ACCELERATE_TPU_FLIGHTREC_CAPACITY"
ENV_FLUSH_EVERY = "ACCELERATE_TPU_FLIGHTREC_FLUSH_EVERY"
ENV_SENTINEL_PROFILE = "ACCELERATE_TPU_SENTINEL_PROFILE"

DEFAULT_CAPACITY = 4096
DEFAULT_FLUSH_EVERY = 64
PROFILE_WINDOW_STEPS = 3

_TRUTHY = {"1", "true", "yes", "on"}
_OFF = {"0", "false", "no", "off"}


def _env_int(key: str, default: int) -> int:
    try:
        return int(os.environ.get(key, "") or default)
    except ValueError:
        return default


def _fsync_enabled() -> bool:
    # Shares the resilience subsystem's durability switch: the test suite
    # (and throwaway runs) set ACCELERATE_TPU_CHECKPOINT_FSYNC=0 once and
    # both checkpoint publishes and recorder flushes skip the fsync.
    return os.environ.get("ACCELERATE_TPU_CHECKPOINT_FSYNC", "1").strip().lower() not in _OFF


class FlightRecorder:
    """Process-wide ring buffer of structured events with crash-safe flush.

    Thread-safe: ``record()`` may be called from any thread (the prefetcher,
    the watchdog, user threads).  The lock is reentrant because the
    flush-on-signal handler runs *on the main thread between bytecodes* — it
    must be able to flush even when it interrupted a ``record()`` that
    already holds the lock.
    """

    def __init__(self):
        self.enabled = False
        self.dir: Optional[str] = None
        self.capacity = DEFAULT_CAPACITY
        self.flush_every = DEFAULT_FLUSH_EVERY
        self.sentinel: Optional[AnomalySentinel] = None
        self._ring: collections.deque = collections.deque(maxlen=DEFAULT_CAPACITY)
        self._lock = threading.RLock()
        self._seq = 0
        self._since_flush = 0
        self._proc: Optional[int] = None
        self._prev_handlers: dict = {}
        self._in_signal: dict = {}
        self._prev_excepthook = None
        self._atexit_registered = False
        # one-shot profiler window: "armed" -> "tracing" -> "done"
        self._profile_state = "armed"
        self._profile_stop_step: Optional[int] = None
        self._profile_dir: Optional[str] = None
        self._profile_trigger_step: Optional[int] = None
        self._analysis_thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def enable(
        self,
        dir: Optional[str] = None,
        capacity: Optional[int] = None,
        flush_every: Optional[int] = None,
        sentinel: Optional[AnomalySentinel] = None,
    ) -> "FlightRecorder":
        """Turn the recorder on (idempotent).  ``dir`` defaults to
        ``$ACCELERATE_TPU_FLIGHTREC_DIR``, then the telemetry dir.  Also
        enables telemetry — the recorder is fed by its hooks."""
        if self.enabled:
            return self
        from . import core

        tel = core.get_telemetry()
        explicit = dir or os.environ.get(ENV_DIR)
        if not tel.enabled:
            # Telemetry lands in the recorder's dir (one run directory) when
            # the recorder names one; otherwise telemetry's own defaults win.
            tel.enable(dir=explicit)
        self.dir = explicit or tel.dir
        os.makedirs(self.dir, exist_ok=True)
        self.capacity = int(capacity or _env_int(ENV_CAPACITY, DEFAULT_CAPACITY))
        self.flush_every = max(1, int(flush_every or _env_int(ENV_FLUSH_EVERY, DEFAULT_FLUSH_EVERY)))
        self.sentinel = sentinel or AnomalySentinel()
        with self._lock:
            self._ring = collections.deque(maxlen=self.capacity)
            self._seq = 0
            self._since_flush = 0
        self._profile_state = "armed"
        self._profile_stop_step = None
        self._profile_dir = None
        self._profile_trigger_step = None
        self._analysis_thread = None
        self.enabled = True
        self._install_signal_flush()
        self._install_excepthook()
        if not self._atexit_registered:
            self._atexit_registered = True
            atexit.register(self._atexit_flush)
        self.record(
            "meta",
            event="enabled",
            pid=os.getpid(),
            capacity=self.capacity,
            flush_every=self.flush_every,
        )
        return self

    def disable(self):
        """Final flush, restore signal handlers / excepthook, turn off."""
        if not self.enabled:
            return
        self._join_analysis(timeout=30.0)
        self.record("meta", event="disabled")
        self.flush(reason="disable")
        self.enabled = False
        self._uninstall_signal_flush()
        self._uninstall_excepthook()

    # -- identity --------------------------------------------------------------

    def _process_index(self) -> int:
        if self._proc is None:
            try:
                import jax

                self._proc = int(jax.process_index())
            except Exception:
                self._proc = 0
        return self._proc

    @property
    def jsonl_path(self) -> Optional[str]:
        if self.dir is None:
            return None
        return os.path.join(self.dir, f"flightrec_p{self._process_index()}.jsonl")

    # -- recording -------------------------------------------------------------

    def record(self, kind: str, **fields):
        """Append one event to the ring; flush every ``flush_every`` events."""
        if not self.enabled:
            return
        rec = {"kind": kind, "t": time.time(), "proc": self._process_index(), **fields}
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            self._since_flush += 1
            if self._since_flush >= self.flush_every:
                self._flush_locked()

    def note_step(
        self,
        step: Optional[int] = None,
        dur_ms: Optional[float] = None,
        dispatches: Optional[float] = None,
        host_blocked_ms: Optional[float] = None,
        **fields,
    ):
        """One completed optimizer step (called by ``Telemetry.record_step``).
        Feeds the sentinel; an anomalous verdict is recorded, flushed
        immediately (an anomaly is exactly when the timeline matters), and
        triggers the one-shot profiler window."""
        if not self.enabled:
            return
        ev: dict = {"step": step}
        if dur_ms is not None:
            ev["dur_ms"] = round(float(dur_ms), 3)
        if dispatches is not None:
            ev["dispatches"] = dispatches
        if host_blocked_ms is not None:
            ev["host_blocked_ms"] = round(float(host_blocked_ms), 3)
        ev.update(fields)
        self.record("step", **ev)
        anomaly = None
        if dur_ms is not None and self.sentinel is not None:
            anomaly = self.sentinel.observe(dur_ms)
        if anomaly is not None:
            self.record("anomaly", step=step, **anomaly)
            self._count_anomaly(anomaly)
            self._maybe_start_profile(step)
            self.flush(reason="anomaly")
        self._maybe_stop_profile(step)

    def note_stall(self, elapsed_s: float, deadline_s: float):
        """A watchdog stall (forwarded from the telemetry sink): always an
        anomaly, immediately flushed — the run may be about to be killed."""
        if not self.enabled:
            return
        anomaly = (self.sentinel or AnomalySentinel()).stall(elapsed_s, deadline_s)
        self.record("anomaly", **anomaly)
        self._count_anomaly(anomaly)
        self._maybe_start_profile(None)
        self.flush(reason="stall")

    def _count_anomaly(self, anomaly: dict):
        from . import core

        tel = core.get_telemetry()
        if tel.enabled:
            tel.registry.counter("sentinel.anomalies").inc()
            tel.write({"kind": "event", "name": "sentinel.anomaly", **anomaly})

    # -- flushing --------------------------------------------------------------

    def flush(self, reason: Optional[str] = None, timeout: Optional[float] = None):
        """Rewrite the JSONL snapshot atomically (write-temp + rename).  A
        bounded ``timeout`` is used from signal context so a lock held by a
        wedged writer thread cannot deadlock the handler."""
        if not self.enabled or self.dir is None:
            return False
        if timeout is not None:
            acquired = self._lock.acquire(timeout=timeout)
        else:
            acquired = self._lock.acquire()
        if not acquired:
            return False
        try:
            self._flush_locked()
            return True
        finally:
            self._lock.release()

    def _flush_locked(self):
        path = self.jsonl_path
        tmp = f"{path}.tmp"
        try:
            with open(tmp, "w") as f:
                for rec in self._ring:
                    f.write(json.dumps(rec, default=str) + "\n")
                f.flush()
                if _fsync_enabled():
                    try:
                        os.fsync(f.fileno())
                    except OSError:
                        pass
            os.replace(tmp, path)
            self._since_flush = 0
        except OSError:
            # The recorder must never take the run down; the previous
            # snapshot (if any) is still intact on disk.
            pass

    # -- crash paths -----------------------------------------------------------

    def _atexit_flush(self):
        if self.enabled:
            self._join_analysis(timeout=10.0)
            self.record("meta", event="exit")
            self.flush(reason="atexit")

    def _install_signal_flush(self):
        """Chain onto SIGTERM/SIGINT without replacing whoever is installed
        (``PreemptionGuard``'s flags-only handler keeps firing)."""
        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                prev = signal.signal(signum, self._on_signal)
            except (ValueError, OSError):
                # Not the main thread (or an embedded interpreter): periodic
                # + atexit + excepthook flushes still cover this process.
                return
            self._prev_handlers[signum] = prev

    def _uninstall_signal_flush(self):
        for signum, prev in list(self._prev_handlers.items()):
            # Only restore when we are still the registered handler — someone
            # (e.g. PreemptionGuard) may have installed over us and now chains
            # to us; yanking the registration out from under them would break
            # their chain.
            if signal.getsignal(signum) == self._on_signal:
                try:
                    signal.signal(signum, prev)
                except (ValueError, TypeError, OSError):
                    # e.g. called off the main thread: we are still the
                    # registered handler, so the chain entry must survive.
                    continue
                del self._prev_handlers[signum]

    def _on_signal(self, signum, frame):
        if self._in_signal.get(signum):
            # Re-entered through a handler CYCLE (enable -> guard install ->
            # disable-while-covered -> re-enable leaves this handler both
            # registered and in the guard's chain): the outer invocation
            # already recorded + flushed; break the loop.
            return
        self._in_signal[signum] = True
        try:
            self.record("signal", signum=int(signum), name=signal.Signals(signum).name)
            self.flush(reason="signal", timeout=5.0)
            prev = self._prev_handlers.get(signum)
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL and signal.getsignal(signum) == self._on_signal:
                # We are the OUTERMOST handler over the default disposition:
                # preserve die-on-signal semantics (a flight recorder must never
                # make a process unkillable).  When we are a chained inner
                # handler (a guard installed over us and invoked us), the outer
                # handler owns the policy — do not re-raise.
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)
        finally:
            self._in_signal[signum] = False

    def _install_excepthook(self):
        if self._prev_excepthook is not None:
            return
        self._prev_excepthook = sys.excepthook

        def _hook(exc_type, exc, tb):
            try:
                self.record(
                    "crash",
                    error=getattr(exc_type, "__name__", str(exc_type)),
                    message=str(exc)[:500],
                )
                self.flush(reason="crash")
            except Exception:
                pass
            prev = self._prev_excepthook or sys.__excepthook__
            prev(exc_type, exc, tb)

        self._flightrec_hook = _hook
        sys.excepthook = _hook

    def _uninstall_excepthook(self):
        if self._prev_excepthook is None:
            return
        if sys.excepthook is getattr(self, "_flightrec_hook", None):
            sys.excepthook = self._prev_excepthook
        self._prev_excepthook = None

    # -- one-shot profiler window ---------------------------------------------

    def _profile_enabled(self) -> bool:
        return os.environ.get(ENV_SENTINEL_PROFILE, "1").strip().lower() not in _OFF

    def _maybe_start_profile(self, step: Optional[int]):
        if self._profile_state != "armed" or not self._profile_enabled():
            return
        trace_dir = os.path.join(self.dir, "anomaly_trace")
        try:
            import jax

            jax.profiler.start_trace(trace_dir)
        except Exception as e:
            self._profile_state = "done"  # no second attempt on a broken profiler
            self.record("event", name="sentinel.profile_failed", error=str(e)[:200])
            return
        self._profile_state = "tracing"
        self._profile_stop_step = (step or 0) + PROFILE_WINDOW_STEPS
        self._profile_dir = trace_dir
        self._profile_trigger_step = step
        self.record("event", name="sentinel.profile_start", dir=trace_dir, step=step)

    def _maybe_stop_profile(self, step: Optional[int]):
        if self._profile_state != "tracing":
            return
        if step is not None and self._profile_stop_step is not None and step < self._profile_stop_step:
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        self._profile_state = "done"
        # The capture is a flight-recorder fact (path + trigger step, so the
        # postmortem can link it to its anomaly); analysis runs on a worker
        # thread — the training loop never blocks on the scanner.
        self.record(
            "event",
            name="sentinel.profile_captured",
            dir=self._profile_dir,
            trigger_step=self._profile_trigger_step,
            stop_step=step,
        )
        self.flush(reason="profile_captured")
        self._analysis_thread = threading.Thread(
            target=self._analyze_capture,
            args=(self._profile_dir, self._profile_trigger_step),
            name="flightrec-profile-scan",
            daemon=True,
        )
        self._analysis_thread.start()

    def _analyze_capture(self, trace_dir: Optional[str], trigger_step: Optional[int]):
        """Off-hot-path worker: scan the captured trace and append the
        attribution digest to the ring, so the postmortem explains *why* the
        slow step was slow, not just that it happened."""
        report = None
        try:
            from . import profile_scan

            report = profile_scan.analyze_trace_dir(trace_dir)
            self.record(
                "event",
                name="sentinel.profile_digest",
                trigger_step=trigger_step,
                dir=trace_dir,
                **profile_scan.digest(report),
            )
        except Exception as e:
            # The analyzer must never take the run (or its shutdown) down.
            self.record(
                "event",
                name="sentinel.profile_analysis_failed",
                trigger_step=trigger_step,
                dir=trace_dir,
                error=str(e)[:200],
            )
        if report is not None:
            # Outside the failure-recording try: a publish hiccup must not
            # shadow the valid digest already sitting in the ring.
            try:
                from . import core

                tel = core.get_telemetry()
                if tel.enabled:
                    profile_scan.publish(report, telemetry=tel)
            except Exception:
                pass
        self.flush(reason="profile_digest")

    def _join_analysis(self, timeout: float):
        """Give an in-flight capture analysis a bounded chance to land its
        digest in the snapshot before the recorder goes away."""
        thread = self._analysis_thread
        if thread is not None and thread.is_alive() and thread is not threading.current_thread():
            thread.join(timeout=timeout)

    # -- views -----------------------------------------------------------------

    def snapshot(self) -> list:
        """Copy of the current ring contents (oldest first)."""
        with self._lock:
            return list(self._ring)


_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _RECORDER


def enable(
    dir: Optional[str] = None,
    capacity: Optional[int] = None,
    flush_every: Optional[int] = None,
    sentinel: Optional[AnomalySentinel] = None,
) -> FlightRecorder:
    return _RECORDER.enable(dir=dir, capacity=capacity, flush_every=flush_every, sentinel=sentinel)


def disable():
    _RECORDER.disable()


def maybe_enable_from_env() -> bool:
    """Enable iff ``$ACCELERATE_TPU_FLIGHTREC`` is truthy (called from
    ``telemetry.maybe_enable_from_env``, which ``Accelerator.__init__`` runs —
    env-only runs need no code changes)."""
    if not _RECORDER.enabled and os.environ.get(ENV_ENABLE, "").strip().lower() in _TRUTHY:
        _RECORDER.enable()
    return _RECORDER.enabled
