"""HBM-ledger smoke: attribution, conservation, OOM forensics, end to end
on an 8-device CPU dryrun mesh (``make memory-smoke``, wired into
``make test``).

Asserts, through the public surfaces only:

1. **attribution** — registered pytrees charge each device its actual shard
   bytes (dp-sharded leaf → 1/8 per device, replicated leaf → full size per
   device), ``subset_of`` entries are ranked but excluded from conservation,
   and ``note_program_bytes`` feeds the program-estimate term;
2. **conservation** — with an injected per-device ``stats_fn``,
   ``attributed + program_estimate + unattributed == bytes_in_use`` holds
   exactly on every device, a *negative* residual (stale registration) is
   exposed rather than clamped, and the default CPU path honestly reports
   ``stats_available: 0`` with no invented arithmetic;
3. **OOM forensics** — a synthetic RESOURCE_EXHAUSTED
   (``ACCELERATE_TPU_FAULT_OOM_ONCE=1``) thrown under
   ``find_executable_batch_size`` halves the batch AND lands a
   ``memory.oom_postmortem`` in the flight-recorder ring blaming the planted
   largest owner, which the telemetry report renders by name;
4. **export** — the Prometheus endpoint scrapes the ``memory.*`` gauge
   family and ``GET /debug/memory`` returns the ranked-ledger JSON.

Run: ``env JAX_PLATFORMS=cpu python -m accelerate_tpu.telemetry.memledger_smoke``
(docs/usage_guides/telemetry.md, "Where did my HBM go?").
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import json
    import tempfile
    import urllib.request

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from .. import telemetry
    from ..resilience import faultinject
    from ..telemetry import flightrec, report
    from ..telemetry.export import MetricsExporter
    from ..telemetry.memledger import get_memory_ledger
    from ..utils.memory import find_executable_batch_size

    ndev = 8
    assert jax.device_count() == ndev, jax.device_count()
    work = tempfile.mkdtemp(prefix="atpu_memledger_smoke_")
    tel = telemetry.enable(dir=work)
    flightrec.enable(dir=os.path.join(work, "flightrec"))
    ledger = get_memory_ledger()
    ledger.reset()

    # -- 1. attribution on a real mesh ---------------------------------------
    mesh = jax.make_mesh((ndev,), ("dp",))
    sharded = jax.device_put(
        jnp.zeros((ndev * 16, 32), jnp.float32),
        NamedSharding(mesh, PartitionSpec("dp", None)),
    )  # 16*32*4 = 2048 B per device
    replicated = jax.device_put(
        jnp.ones((64,), jnp.float32), NamedSharding(mesh, PartitionSpec())
    )  # 256 B per device
    ledger.register("smoke.params", tree={"w": sharded, "b": replicated})
    hog = jax.device_put(
        jnp.zeros((4096,), jnp.float32), NamedSharding(mesh, PartitionSpec())
    )  # 16384 B per device — the planted blame
    hog_token = ledger.register("smoke.hog", tree=hog)
    ledger.register("smoke.cache_resident", nbytes=512, subset_of="smoke.hog")
    ledger.note_program_bytes("smoke.step", 1000)

    att = ledger.attributed_per_device()
    expect = {d.id: 2048 + 256 + 16384 for d in jax.local_devices()}
    assert att == expect, (att, expect)
    ranked = ledger.owners()
    assert ranked[0].owner == "smoke.hog", [r.owner for r in ranked]
    print(f"# attribution: {att[0]} B/chip across {ndev} devices", file=sys.stderr)

    # -- 2. conservation with an injected allocator view ---------------------
    def stats_fn(device):
        return {
            "bytes_in_use": att[device.id] + 1000 + 777,  # program + residual
            "peak_bytes_in_use": att[device.id] + 5000,
            "bytes_limit": 1 << 20,
        }

    records = ledger.reconcile(stats_fn=stats_fn)
    assert len(records) == ndev, records
    for rec in records:
        assert rec["stats_available"] == 1
        assert (
            rec["attributed_bytes"]
            + rec["program_estimate_bytes"]
            + rec["unattributed_bytes"]
            == rec["bytes_in_use"]
        ), rec
        assert rec["unattributed_bytes"] == 777, rec
        assert rec["headroom_bytes"] == (1 << 20) - rec["bytes_in_use"], rec
    # A stale registration (attribution above the allocator's count) must
    # surface as a NEGATIVE residual, not be clamped away.
    neg = ledger.reconcile(stats_fn=lambda d: {"bytes_in_use": 10})[0]
    assert neg["unattributed_bytes"] < 0, neg
    # The default CPU path reports no stats — and invents no arithmetic.
    bare = ledger.reconcile()[0]
    assert bare["stats_available"] == 0 and "bytes_in_use" not in bare, bare
    ledger.reconcile(stats_fn=stats_fn)  # restore the synthetic watermark
    ledger.publish(tel.registry)
    snap = tel.registry.snapshot()
    assert snap["memory.attributed_bytes"] == max(att.values()), snap
    assert snap["memory.unattributed_bytes"] == 777, snap
    assert snap["memory.owner.smoke_hog_bytes"] == 16384, snap
    print("# conservation: residual 777 B on all 8 devices, exactly", file=sys.stderr)

    # -- 3. OOM forensics under fault injection ------------------------------
    os.environ[faultinject.ENV_OOM_ONCE] = "1"
    faultinject.reload()
    calls = []

    @find_executable_batch_size(starting_batch_size=8)
    def train(batch_size):
        calls.append(batch_size)
        faultinject.maybe_oom()
        return batch_size

    try:
        landed = train()
    finally:
        os.environ.pop(faultinject.ENV_OOM_ONCE, None)
        faultinject.reload()
    assert landed == 4 and calls == [8, 4], (landed, calls)
    assert ledger.oom_postmortems, "no postmortem recorded"
    pm = ledger.oom_postmortems[-1]
    assert pm["source"] == "find_executable_batch_size", pm
    assert pm["blame"] == "smoke.hog" and pm["blame_bytes"] == 16384, pm
    assert pm["batch_size"] == 8, pm
    ring = [
        r
        for r in flightrec.get_flight_recorder().snapshot()
        if r.get("kind") == "event" and r.get("name") == "memory.oom_postmortem"
    ]
    assert ring and ring[-1]["blame"] == "smoke.hog", ring
    fsum = report.summarize_flight(flightrec.get_flight_recorder().snapshot())
    text = report.format_flight_report(fsum)
    assert "memory postmortem" in text and "smoke.hog" in text, text
    mem_lines = "\n".join(report.format_memory_block(tel.registry.snapshot()))
    assert "smoke_hog" in mem_lines, mem_lines  # gauge slug of smoke.hog
    print("# forensics: postmortem blames smoke.hog, report renders it", file=sys.stderr)

    # -- 4. export: Prometheus scrape + /debug/memory ------------------------
    exporter = MetricsExporter().start(port=0)
    try:
        base = f"http://127.0.0.1:{exporter.port}"
        scrape = urllib.request.urlopen(base + "/metrics", timeout=10).read().decode()
        for needle in (
            "accelerate_tpu_memory_attributed_bytes",
            "accelerate_tpu_memory_owner_smoke_hog_bytes",
        ):
            assert needle in scrape, f"{needle} missing from scrape"
        debug = json.loads(
            urllib.request.urlopen(base + "/debug/memory", timeout=10).read()
        )
        assert debug["owners"][0]["owner"] == "smoke.hog", debug["owners"]
        assert debug["oom_postmortems"] >= 1, debug
    finally:
        exporter.stop(final_snapshot=False)

    # GC-path hygiene: a token-guarded unregister after a replacement keeps
    # the replacement (the engine finalizer contract).
    new_token = ledger.register("smoke.hog", nbytes=64)
    assert not ledger.unregister("smoke.hog", hog_token)
    assert ledger.unregister("smoke.hog", new_token)

    telemetry.disable()
    flightrec.disable()
    print(
        "memledger-smoke OK — attribution exact on 8 devices, conservation "
        "residual 777 B by construction, negative residual exposed, OOM "
        "postmortem blamed smoke.hog through find_executable_batch_size, "
        "memory.* scraped and /debug/memory served"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
