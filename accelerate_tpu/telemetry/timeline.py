"""Device-timeline reconstruction from ``jax.profiler`` trace dumps.

``jax.profiler.start_trace(dir)`` writes a TensorBoard profile bundle under
``<dir>/plugins/profile/<run>/``; the piece this module consumes is the
Chrome trace-event file ``<host>.trace.json.gz`` (plain ``.trace.json`` also
accepted), which both the CPU and TPU backends emit.  The schema assumed here
(see ``docs/package_reference/profile.md`` for the full contract):

- top level is an object with a ``traceEvents`` list;
- ``ph == "M"`` metadata events name processes (``process_name``) and
  threads (``thread_name``);
- ``ph == "X"`` complete events carry ``ts``/``dur`` in microseconds; XLA op
  executions carry ``args.hlo_op`` (CPU/GPU) or live on a device process's
  ``XLA Ops`` lane (TPU) — everything else is host-side bookkeeping.

Everything in this module is dependency-free stdlib (no ``jax`` import): the
same parser that audits a live capture also runs offline on a committed
fixture with no accelerator present.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from dataclasses import dataclass, field
from typing import Optional

__all__ = [
    "TraceParseError",
    "TraceEvent",
    "Timeline",
    "load_trace_events",
    "find_trace_files",
    "build_timeline",
    "classify_op",
    "merge_intervals",
    "intervals_total",
    "subtract_intervals",
    "COMPUTE",
    "COLLECTIVE",
    "INFEED",
]

# Bucket names (the taxonomy the attribution report speaks).  Idle time is
# derived (window minus device-busy), not a per-op bucket.
COMPUTE = "compute"
COLLECTIVE = "collective"
INFEED = "infeed"

# HLO collective opcodes.  Op instruction names default to their opcode with
# optional ``.N`` uniquifiers and async ``-start``/``-done`` halves, so a
# prefix match on the opcode covers ``all-gather``, ``all-gather-start`` and
# ``all-gather.3`` alike without catching fusions named after their root
# (e.g. ``broadcast_add_fusion`` uses underscores, not opcode prefixes).
_COLLECTIVE_RE = re.compile(
    r"^(all-reduce|all-gather|reduce-scatter|all-to-all|ragged-all-to-all|"
    r"collective-permute|collective-broadcast)"
)
_INFEED_RE = re.compile(r"^(infeed|outfeed)")


class TraceParseError(ValueError):
    """A trace file that cannot be understood: truncated gzip, invalid JSON,
    or JSON that is not a trace-event bundle."""


@dataclass
class TraceEvent:
    """One complete (``ph == "X"``) trace event, times in microseconds."""

    name: str
    ts: float
    dur: float
    pid: int
    tid: int
    hlo_op: Optional[str] = None
    hlo_module: Optional[str] = None

    @property
    def end(self) -> float:
        return self.ts + self.dur


@dataclass
class Timeline:
    """Parsed trace: device-op events plus the process/thread name maps and
    host-side step markers needed to attribute them."""

    events: list = field(default_factory=list)  # device-op TraceEvents
    host_events: list = field(default_factory=list)  # host-side TraceEvents
    process_names: dict = field(default_factory=dict)  # pid -> name
    thread_names: dict = field(default_factory=dict)  # (pid, tid) -> name
    n_raw_events: int = 0
    source: Optional[str] = None

    def device_scopes(self) -> dict:
        """Group device-op events by scope.

        On TPU each device is its own trace process (``/device:TPU:N``), so a
        scope is one chip.  On CPU every virtual device's executor thread
        shares the single host process, so the scope is the whole (single
        process) fleet — overlap is then judged fleet-wide, which is the
        honest granularity the CPU trace offers (documented limit)."""
        scopes: dict = {}
        for ev in self.events:
            scopes.setdefault(ev.pid, []).append(ev)
        return scopes

    def lanes(self) -> dict:
        """Device-op events grouped by (pid, tid) lane (used for self-time)."""
        lanes: dict = {}
        for ev in self.events:
            lanes.setdefault((ev.pid, ev.tid), []).append(ev)
        return lanes

    def tracks(self) -> dict:
        """Human labels per (pid, tid) lane: ``"process/thread"`` from the
        metadata events, falling back to the raw ids.  Covers every lane any
        event (device or host) landed on — the serving Chrome-trace export
        validates its slot/request tracks through this."""
        out: dict = {}
        for ev in self.events + self.host_events:
            key = (ev.pid, ev.tid)
            if key in out:
                continue
            proc = self.process_names.get(ev.pid, str(ev.pid))
            thread = self.thread_names.get(key, str(ev.tid))
            out[key] = f"{proc}/{thread}"
        return out


def classify_op(name: str) -> str:
    """Bucket one device op by its HLO name: collective / infeed / compute."""
    if _COLLECTIVE_RE.match(name):
        return COLLECTIVE
    if _INFEED_RE.match(name):
        return INFEED
    return COMPUTE


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------


def find_trace_files(path: str) -> list:
    """Locate trace-event files under ``path``.

    Accepts the profiler's output root (searches ``plugins/profile/<run>/``),
    a run directory, or a single ``*.trace.json[.gz]`` file.  Newest run wins
    when several captures share the root (a re-armed sentinel, repeated
    ``start_trace`` calls)."""
    if os.path.isfile(path):
        return [path]
    if not os.path.isdir(path):
        return []
    patterns = (
        os.path.join(path, "*.trace.json.gz"),
        os.path.join(path, "*.trace.json"),
        os.path.join(path, "plugins", "profile", "*", "*.trace.json.gz"),
        os.path.join(path, "plugins", "profile", "*", "*.trace.json"),
        os.path.join(path, "**", "*.trace.json.gz"),
    )
    for pattern in patterns:
        files = sorted(glob.glob(pattern, recursive=True))
        if files:
            # One run directory may hold one file per host; keep every file of
            # the newest run (same parent dir), not a mix of runs.
            newest_dir = os.path.dirname(max(files, key=os.path.getmtime))
            return [f for f in files if os.path.dirname(f) == newest_dir]
    return []


def load_trace_events(path: str) -> list:
    """Parse one trace file into its raw event dict list.

    Raises :class:`TraceParseError` for truncated gzip streams, invalid JSON,
    and JSON without a ``traceEvents`` list — a half-written capture (the
    process died mid-trace) must be rejected loudly, not half-analyzed."""
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt", encoding="utf-8") as f:
                data = json.load(f)
        else:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
    except (OSError, EOFError, UnicodeDecodeError, ValueError) as e:
        # gzip truncation surfaces as EOFError/OSError ("CRC check failed"),
        # torn JSON as ValueError (json.JSONDecodeError subclasses it).
        raise TraceParseError(f"cannot parse trace file {path}: {e}") from e
    if not isinstance(data, dict) or not isinstance(data.get("traceEvents"), list):
        raise TraceParseError(
            f"{path} is not a trace-event bundle (no traceEvents list)"
        )
    return data["traceEvents"]


def build_timeline(raw_events: list, source: Optional[str] = None) -> Timeline:
    """Split raw trace events into device ops vs host events + name maps."""
    tl = Timeline(n_raw_events=len(raw_events), source=source)
    for rec in raw_events:
        if not isinstance(rec, dict):
            continue
        ph = rec.get("ph")
        pid = rec.get("pid", 0)
        tid = rec.get("tid", 0)
        if ph == "M":
            args = rec.get("args") or {}
            if rec.get("name") == "process_name":
                tl.process_names[pid] = str(args.get("name", ""))
            elif rec.get("name") == "thread_name":
                tl.thread_names[(pid, tid)] = str(args.get("name", ""))
            continue
        if ph != "X":
            continue
        try:
            ts = float(rec.get("ts", 0.0))
            dur = float(rec.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        args = rec.get("args") or {}
        hlo_op = args.get("hlo_op") if isinstance(args, dict) else None
        ev = TraceEvent(
            name=str(rec.get("name", "?")),
            ts=ts,
            dur=dur,
            pid=pid,
            tid=tid,
            hlo_op=str(hlo_op) if hlo_op is not None else None,
            hlo_module=(args.get("hlo_module") if isinstance(args, dict) else None),
        )
        if _is_device_op(ev, tl):
            tl.events.append(ev)
        else:
            tl.host_events.append(ev)
    return tl


def _is_device_op(ev: TraceEvent, tl: Timeline) -> bool:
    """A device op either carries ``args.hlo_op`` (CPU/GPU traces) or lives on
    a device process's ``XLA Ops`` lane (TPU traces)."""
    if ev.hlo_op is not None:
        return True
    proc = tl.process_names.get(ev.pid, "")
    if proc.startswith("/device:"):
        thread = tl.thread_names.get((ev.pid, ev.tid), "")
        return thread.startswith("XLA Ops")
    return False


# ---------------------------------------------------------------------------
# Interval arithmetic (all inputs/outputs are [start, end) pairs in µs)
# ---------------------------------------------------------------------------


def merge_intervals(intervals: list) -> list:
    """Union of possibly-overlapping intervals, sorted and disjoint."""
    out: list = []
    for start, end in sorted(i for i in intervals if i[1] > i[0]):
        if out and start <= out[-1][1]:
            if end > out[-1][1]:
                out[-1] = (out[-1][0], end)
        else:
            out.append((start, end))
    return out


def intervals_total(intervals: list) -> float:
    """Total covered length of a DISJOINT (merged) interval list."""
    return sum(end - start for start, end in intervals)


def subtract_intervals(a: list, b: list) -> list:
    """``a − b`` for two merged interval lists: the parts of ``a`` not covered
    by ``b``.  This is the exposed-collective operator: collective-time minus
    concurrent-compute-time."""
    a = merge_intervals(a)
    b = merge_intervals(b)
    out = []
    j = 0
    for start, end in a:
        cur = start
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while k < len(b) and b[k][0] < end:
            bs, be = b[k]
            if bs > cur:
                out.append((cur, bs))
            cur = max(cur, be)
            if cur >= end:
                break
            k += 1
        if cur < end:
            out.append((cur, end))
    return out


def clip_intervals(intervals: list, start: float, end: float) -> list:
    """Restrict a merged interval list to a window."""
    out = []
    for s, e in intervals:
        s2, e2 = max(s, start), min(e, end)
        if e2 > s2:
            out.append((s2, e2))
    return out
