"""Flight-recorder smoke: SIGTERM a training run mid-step, read the black box.

Run via ``make flightrec-smoke`` (or ``python -m
accelerate_tpu.telemetry.flightrec_smoke``).  The parent launches one child:

1. **victim** — a CPU training run with the flight recorder enabled
   (``ACCELERATE_TPU_FLIGHTREC=1``, picked up by ``Accelerator()``) and
   preemption handling installed; ``ACCELERATE_TPU_FAULT_SIGTERM_STEP=K``
   delivers a real SIGTERM mid-run.  The PreemptionGuard's flags-only handler
   fires AND chains to the recorder's flush-on-signal handler (the
   composition under test), the guard writes its final verified checkpoint,
   and the child ``os._exit``\\ s — deliberately skipping atexit, so whatever
   is on disk got there from the signal-time flush alone.

The parent then asserts the postmortem story holds with the process gone:

- ``flightrec_p0.jsonl`` exists and parses;
- it contains the final step's ``step`` event (step K) and the ``signal``
  event — the crash-safe flush captured the timeline up to the kill;
- the guard's final checkpoint is manifest-complete — BOTH chained handlers
  did their jobs on one signal delivery;
- ``telemetry.report`` renders a postmortem block from the snapshot.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

STEPS = 8
KILL_STEP = 4


def _train(ckpt_root: str, losses_path: str) -> int:
    import torch
    from torch.utils.data import DataLoader

    from ..accelerator import Accelerator
    from ..telemetry.flightrec import get_flight_recorder
    from ..test_utils import RegressionDataset, RegressionModelWithLoss
    from ..test_utils.training import regression_collate
    from ..utils import set_seed

    set_seed(1234)
    accelerator = Accelerator()  # env enables telemetry + flight recorder
    rec = get_flight_recorder()
    assert rec.enabled, "ACCELERATE_TPU_FLIGHTREC=1 did not enable the recorder"
    model = RegressionModelWithLoss()
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    dl = DataLoader(
        list(RegressionDataset(length=64)), batch_size=4, collate_fn=regression_collate
    )
    model, opt, dl = accelerator.prepare(model, opt, dl)
    # Installed AFTER the recorder's handler: the guard must chain to it.
    accelerator.enable_preemption_handling(save_dir=os.path.join(ckpt_root, "preempt-ckpt"))

    global_step = 0
    losses: dict = {}
    preempted = False
    while global_step < STEPS and not preempted:
        for batch in dl:
            out = model(x=batch["x"], y=batch["y"])
            accelerator.backward(out.loss)
            opt.step()
            opt.zero_grad()
            global_step += 1
            losses[str(global_step)] = float(out.loss.detach())
            if accelerator.check_preemption(step=global_step):
                print(f"# preempted at step {global_step}", file=sys.stderr)
                preempted = True
                break
            if global_step >= STEPS:
                break
    with open(losses_path, "w") as f:
        json.dump({"losses": losses, "preempted": preempted, "last_step": global_step}, f)
    # Hard exit: atexit (and its recorder flush) must NOT run — the parent's
    # assertions then prove the signal-time flush alone wrote the black box.
    os._exit(0)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--role", choices=("train",), default=None)
    parser.add_argument("--ckpt-root", default=None)
    parser.add_argument("--losses", default=None)
    args = parser.parse_args()

    if args.role is not None:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return _train(args.ckpt_root, args.losses)

    # -- parent orchestration -------------------------------------------------
    work = tempfile.mkdtemp(prefix="atpu_flightrec_smoke_")
    rec_dir = os.path.join(work, "flightrec")
    ckpt_root = os.path.join(work, "ckpts")
    losses_path = os.path.join(work, "victim.json")
    os.makedirs(ckpt_root)

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(
        {
            "ACCELERATE_TPU_FLIGHTREC": "1",
            "ACCELERATE_TPU_FLIGHTREC_DIR": rec_dir,
            "ACCELERATE_TPU_TELEMETRY_DIR": os.path.join(work, "telemetry"),
            "ACCELERATE_TPU_SENTINEL_PROFILE": "0",
            "ACCELERATE_TPU_FAULT_SIGTERM_STEP": str(KILL_STEP),
        }
    )
    print(f"# flightrec-smoke: victim run (SIGTERM at step {KILL_STEP})", file=sys.stderr)
    proc = subprocess.run(
        [
            sys.executable, "-m", "accelerate_tpu.telemetry.flightrec_smoke",
            "--role", "train", "--ckpt-root", ckpt_root, "--losses", losses_path,
        ],
        env=env, capture_output=True, text=True, timeout=600,
    )
    if proc.returncode != 0:
        print(proc.stdout)
        print(proc.stderr, file=sys.stderr)
        raise RuntimeError(f"victim exited rc={proc.returncode}")
    sys.stderr.write(proc.stderr)
    with open(losses_path) as f:
        victim = json.load(f)
    assert victim["preempted"], f"victim was never preempted: {victim}"
    assert victim["last_step"] == KILL_STEP, victim

    # -- the black box survived the kill --------------------------------------
    snapshot_path = os.path.join(rec_dir, "flightrec_p0.jsonl")
    assert os.path.exists(snapshot_path), f"no flight-recorder snapshot at {snapshot_path}"
    records = [json.loads(line) for line in open(snapshot_path)]
    kinds = {r["kind"] for r in records}
    step_events = [r for r in records if r["kind"] == "step"]
    assert step_events, f"no step events in snapshot (kinds: {kinds})"
    last_steps = {r.get("step") for r in step_events}
    assert KILL_STEP in last_steps, (
        f"final step {KILL_STEP} missing from snapshot (steps: {sorted(last_steps)})"
    )
    signals = [r for r in records if r["kind"] == "signal"]
    assert signals and signals[-1].get("name") == "SIGTERM", (
        f"no SIGTERM signal event in snapshot (kinds: {kinds})"
    )

    # -- AND the chained PreemptionGuard still wrote its checkpoint -----------
    from ..resilience.manifest import find_latest_complete, verify_checkpoint

    ckpt = find_latest_complete(os.path.join(ckpt_root, "preempt-ckpt"))
    assert ckpt is not None, "guard's final checkpoint missing — chain broke"
    manifest = verify_checkpoint(ckpt)
    assert manifest["step"] == KILL_STEP, manifest

    # -- and the report CLI renders a postmortem from it ----------------------
    from .report import format_flight_report, load_flight_records, summarize_flight

    postmortem = format_flight_report(summarize_flight(load_flight_records(rec_dir)))
    assert "flight recorder" in postmortem and "SIGTERM" in postmortem, postmortem
    print(postmortem)
    print(
        f"flightrec-smoke OK — SIGTERM at step {KILL_STEP}: snapshot has the final "
        f"step + signal events, guard checkpoint {os.path.basename(ckpt)} is "
        "manifest-complete, postmortem renders"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
