"""Goodput-accounting smoke: ``python -m accelerate_tpu.telemetry.goodput_smoke``.

A short chaos-style CPU run with every badput source injected in one
process, then three proofs:

1. **conservation** — the ledger's categories sum to the elapsed wall-clock
   window within ε, every category is non-negative, and the attributed
   (non-background) time never exceeds the window;
2. **fault attribution** — each injected fault class lands in its correct
   badput category: the NaN-poisoned step (health gate skips it) →
   ``rewind_replay``, the torn checkpoint write (I/O retry) →
   ``checkpoint``, the synthetic OOM (retry-exhausted acquisition) →
   ``device_acquire``, the SIGTERM (preemption drain + final checkpoint) →
   ``preempt``; productive/compile/checkpoint wall time is attributed too;
3. **export** — the Prometheus endpoint scrapes once with valid text
   exposition (histogram ``_bucket``/``_sum``/``_count`` consistency
   included), the atomic snapshot file parses identically, and the offline
   ``telemetry.report`` path reproduces a ``goodput`` summary with the same
   markers from the JSONL stream alone.

Run via ``make goodput-smoke`` (wired into ``make test``).
"""

from __future__ import annotations

import os
import re
import sys
import tempfile
import urllib.request

NAN_STEP = 3
SIGTERM_STEP = 7
TOTAL_STEPS = 9
EPS_S = 1e-6


def _parse_exposition(text: str) -> dict:
    """Minimal exposition-format validator: every line is a comment or a
    ``name{labels} value`` sample; returns {sample_name_with_labels: value}.
    Raises on any malformed line."""
    samples = {}
    line_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})?\s+"
        r"([-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|[+-]Inf|NaN)$"
    )
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = line_re.match(line)
        assert m, f"malformed exposition line: {line!r}"
        samples[m.group(1) + (m.group(2) or "")] = float(m.group(3))
    assert samples, "exposition body carried no samples"
    return samples


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("ACCELERATE_TPU_CHECKPOINT_FSYNC", "0")
    # Hermetic compile accounting: a warm persistent cache would turn the
    # first-step compile into a cache hit and zero the compile category.
    os.environ["ACCELERATE_TPU_COMPILE_CACHE"] = ""
    os.environ["ACCELERATE_TPU_SENTINEL_PROFILE"] = "0"
    os.environ["ACCELERATE_TPU_IO_RETRIES"] = "3"
    os.environ["ACCELERATE_TPU_IO_RETRY_BASE_S"] = "0.02"
    # Arm the NaN poison and the SIGTERM before anything traces or installs.
    os.environ["ACCELERATE_TPU_FAULT_NAN_STEP"] = str(NAN_STEP)
    os.environ["ACCELERATE_TPU_FAULT_SIGTERM_STEP"] = str(SIGTERM_STEP)

    import numpy as np

    from .. import telemetry
    from ..resilience import faultinject
    from ..resilience.chaos import build_recipe, make_batch
    from . import export, goodput
    from .report import load_records, summarize

    faultinject.reload()
    work = tempfile.mkdtemp(prefix="atpu_goodput_smoke_")
    tel = telemetry.enable(dir=work)
    ledger = goodput.attach()
    snapshot_path = os.path.join(work, "metrics.prom")
    exporter = export.MetricsExporter()
    exporter.start(port=0, snapshot_path=snapshot_path, snapshot_every_s=30.0)

    acc, model, opt = build_recipe(os.path.join(work, "ckpts"))
    acc.enable_health_guard(optimizer=opt, max_skips=TOTAL_STEPS)
    step_fn = acc.make_train_step(model, opt, clip_norm=0.05)

    losses = []
    skipped = []
    preempted_at = None
    for i in range(TOTAL_STEPS):
        step = i + 1
        if step == 5:
            # Torn write: the NEXT checkpoint write fails once (transient),
            # the I/O retry policy absorbs it — checkpoint-category badput.
            os.environ["ACCELERATE_TPU_FAULT_WRITE_N"] = "1"
            faultinject.reload()
        losses.append(float(np.asarray(step_fn(make_batch(acc, i)))))
        if acc.check_health(step=step).skipped:
            skipped.append(step)
        if step in (2, 5):
            acc.save_state(step=step)
        if acc.check_preemption(step=step):
            preempted_at = step
            break
    os.environ.pop("ACCELERATE_TPU_FAULT_WRITE_N", None)

    # Synthetic OOM through the retry machinery (re-armed per attempt, so the
    # policy exhausts its tries): a device-acquisition fight, ledgered.
    oom_seen = False
    try:
        faultinject.synthetic_oom_acquire("smoke.device_acquire")
    except RuntimeError as e:
        assert "RESOURCE_EXHAUSTED" in str(e)
        oom_seen = True

    assert skipped == [NAN_STEP], f"health gate skipped {skipped}, expected [{NAN_STEP}]"
    assert preempted_at == SIGTERM_STEP, f"preempted at {preempted_at}, expected {SIGTERM_STEP}"
    assert oom_seen, "synthetic OOM never surfaced"

    # -- proof 1: conservation ------------------------------------------------
    summary = ledger.summary()
    seconds = summary["seconds"]
    assert abs(summary["conservation_error_s"]) < EPS_S, summary
    assert all(v >= 0.0 for v in seconds.values()), seconds
    assert summary["attributed_s"] <= summary["elapsed_s"] + EPS_S, summary
    assert seconds["productive"] > 0.0, seconds
    assert seconds["compile"] > 0.0, seconds
    assert seconds["checkpoint"] > 0.0, seconds
    assert seconds["rewind_replay"] > 0.0, seconds  # the skipped step's compute

    # -- proof 2: fault attribution ------------------------------------------
    markers = summary["markers"]
    for fault, category in (
        ("nan/health-skip", "rewind_replay"),
        ("torn-write retry", "checkpoint"),
        ("oom acquire", "device_acquire"),
        ("sigterm", "preempt"),
    ):
        assert markers.get(category, 0) >= 1, (
            f"{fault} left no {category!r} marker: {markers}"
        )

    # -- proof 3: export ------------------------------------------------------
    url = f"http://127.0.0.1:{exporter.port}/metrics"
    body = urllib.request.urlopen(url, timeout=10).read().decode()
    samples = _parse_exposition(body)
    assert "accelerate_tpu_goodput_fraction" in samples, sorted(samples)[:20]
    for name in goodput.CATEGORIES:
        assert f"accelerate_tpu_goodput_{name}_s" in samples, name
    # Histogram triplet consistency on the step-time family.
    stem = "accelerate_tpu_step_time_ms"
    assert samples[f'{stem}_bucket{{le="+Inf"}}'] == samples[f"{stem}_count"]
    assert f"{stem}_sum" in samples
    exporter.stop()  # writes the final snapshot
    with open(snapshot_path) as f:
        snap_samples = _parse_exposition(f.read())
    assert "accelerate_tpu_goodput_fraction" in snap_samples

    telemetry.disable()
    goodput.detach()

    # Offline replay: the report path recomputes the same ledger from JSONL.
    offline = summarize(load_records(work))["goodput"]
    assert offline is not None and abs(offline["conservation_error_s"]) < EPS_S
    for category in ("rewind_replay", "checkpoint", "device_acquire", "preempt"):
        assert offline["markers"].get(category, 0) >= 1, (category, offline["markers"])

    print(
        "goodput-smoke OK — "
        f"elapsed {summary['elapsed_s']:.2f}s, "
        f"productive {100 * summary['goodput_fraction']:.1f}%, "
        f"compile {seconds['compile']:.2f}s, checkpoint {seconds['checkpoint']:.2f}s, "
        f"rewind-replay {seconds['rewind_replay']:.2f}s, "
        f"conservation error {summary['conservation_error_s']:.2e}s; "
        f"faults attributed: nan->rewind_replay, torn-write->checkpoint, "
        f"oom->device_acquire, sigterm->preempt; "
        f"endpoint scraped {len(samples)} samples, snapshot parsed"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
