"""Optimized-HLO collective scanner: the text half of the compiled-program
inspector (``introspect.py`` drives it).

Under GSPMD sharding propagation (arXiv:2105.04663) XLA inserts
``all-gather``/``all-reduce``/``reduce-scatter``/``all-to-all``/
``collective-permute`` ops wherever the sharding annotations under-constrain
the program — none of them appear in user code, so the only place they can be
*counted* is the optimized HLO module of the compiled executable.  This module
parses that text (``compiled.as_text()``) into a structured **comms ledger**:

- one :class:`CollectiveOp` per HLO collective, with the byte volume of the
  LARGE side of the transfer per participating device (result bytes for most
  kinds; for ``reduce-scatter``, whose result is the scattered shard, the
  operand-side bytes — result x group size) and the mesh axis/axes the op
  communicates over, recovered from ``replica_groups`` /
  ``source_target_pairs`` against the mesh's device coordinates;
- a :class:`CommsLedger` aggregate: op counts and byte volumes per collective
  kind and per mesh axis.

Pure text + numpy — no XLA bindings beyond the HLO string, so the scan works
identically on CPU test meshes and real TPU slices.

By default the scan is *static* — each HLO instruction counts once, so a
collective inside a ``while`` body (e.g. the per-tick CollectivePermute of
the pipeline scan) under-reports executed bytes by the loop trip count.
``scan_hlo(..., unroll_loops=True)`` fixes that: while instructions carry
XLA's ``backend_config={"known_trip_count":{"n":...}}`` (or a constant-vs-
induction-variable ``compare`` in the condition computation), and each
collective's bytes are multiplied by the product of its enclosing loops'
trip counts — which is what makes the pp invariant checkable on the same
convention as the dp/fsdp ones: executed ``collective-permute`` bytes over
the ``pp`` axis == per-tick activation bytes x pipeline ticks, independent
of the interleaving degree v.  The static default keeps the existing
num_layers=1 invariant tests bit-stable.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import numpy as np

__all__ = [
    "CollectiveOp",
    "CommsLedger",
    "COLLECTIVE_KINDS",
    "parse_shape_bytes",
    "parse_collectives",
    "classify_groups",
    "scan_hlo",
]

COLLECTIVE_KINDS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

# f32[4,8]{1,0} / bf16[2,4,8] / s8[16] / pred[] / u32[3]{0} / f8e4m3fn[...]
_SHAPE_RE = re.compile(r"(?P<dtype>[a-z][a-z0-9]*)\[(?P<dims>[0-9,]*)\](?:\{[^}]*\})?")

_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "f8e4m3fnuz": 1, "f8e5m2fnuz": 1, "f8e3m4": 1, "f8e4m3": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "c64": 8,
    "s64": 8, "u64": 8, "f64": 8, "c128": 16,
}

# One HLO instruction line whose opcode is a collective.  The result shape is
# either a single array shape or a tuple "(f32[...], f32[...])" when XLA fused
# several tensors (e.g. many gradient leaves) into one collective.  Async pairs
# lower as <op>-start/<op>-done; counting only -start (plus the sync form)
# avoids double counting.
_COLLECTIVE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*"
    r"(?P<shape>\([^)]*\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")(?P<start>-start)?\(",
    re.M,
)

# Nested one level: {{0,1},{2,3}} — the inner-group alternation keeps the
# match from stopping at the first inner "},".
_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{(?P<groups>(?:\{[^{}]*\}\s*,?\s*)*)\}")
# Iota form (newer XLA): replica_groups=[4,2]<=[8] — 4 groups of 2 over 8 ids.
_IOTA_GROUPS_RE = re.compile(r"replica_groups=\[(?P<dims>[0-9,]+)\]<=\[")
_SOURCE_TARGET_RE = re.compile(r"source_target_pairs=\{(?P<pairs>(?:\{[^{}]*\}\s*,?\s*)*)\}")
_OP_NAME_RE = re.compile(r'op_name="(?P<name>[^"]*)"')

# Computation header: a non-indented "%name (args...) -> result {" line
# (ENTRY-prefixed for the entry computation).
_COMPUTATION_RE = re.compile(r"^(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*\(.*->.*\{\s*$")
# While instruction: condition/body computation refs + XLA's analyzed trip
# count (emitted for counted loops like lax.scan's).
_WHILE_RE = re.compile(
    r"=\s*(?:\([^)]*\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s+while\("
)
_WHILE_COND_RE = re.compile(r"condition=%?(?P<name>[\w.\-]+)")
_WHILE_BODY_RE = re.compile(r"body=%?(?P<name>[\w.\-]+)")
_TRIP_COUNT_RE = re.compile(r'"known_trip_count":\{"n":"(?P<n>\d+)"\}')
_COND_CONSTANT_RE = re.compile(r"=\s*s32\[\]\s+constant\((?P<n>-?\d+)\)")
_COND_COMPARE_RE = re.compile(r"compare\(.*direction=(?P<dir>LT|LE|GT|GE)")


@dataclasses.dataclass
class CollectiveOp:
    """One collective instruction from the optimized HLO."""

    kind: str  # one of COLLECTIVE_KINDS
    bytes: int  # large-side byte volume per device (operand-side for reduce-scatter)
    axes: Optional[tuple[str, ...]]  # mesh axes communicated over (None: unknown)
    group_size: int  # devices per replica group (0 = unknown, 1 = degenerate)
    op_name: str = ""  # jax op_name metadata (trace provenance), may be ""
    # Product of enclosing while-loop trip counts (1 = top level / unknown).
    # ``bytes`` stays the per-execution figure; ``executed_bytes`` is the
    # loop-unrolled volume the ``unroll_loops`` ledger aggregates.
    trip_count: int = 1

    @property
    def executed_bytes(self) -> int:
        return self.bytes * max(self.trip_count, 1)

    @property
    def is_degenerate(self) -> bool:
        """True when every replica group has exactly one member — the
        partitioner kept the op but it moves no data (e.g. a psum over a
        size-1 mesh axis).  Unknown group size (0 — no replica_groups
        attribute and no mesh to resolve against) is NOT degenerate: an
        absent/empty group list means ALL devices, the maximum traffic."""
        return self.group_size == 1


@dataclasses.dataclass
class CommsLedger:
    """Aggregate comms view of one compiled program."""

    ops: list  # list[CollectiveOp], degenerate ops excluded
    by_kind: dict  # kind -> {"count": int, "bytes": int}
    by_axis: dict  # "dp" / "fsdp" / "dp+fsdp" / "?" -> bytes
    total_bytes: int
    degenerate_ops: int  # collectives present in HLO but moving no data

    def to_dict(self) -> dict:
        return {
            "by_kind": self.by_kind,
            "by_axis": self.by_axis,
            "total_bytes": self.total_bytes,
            "n_ops": len(self.ops),
            "degenerate_ops": self.degenerate_ops,
        }


def parse_shape_bytes(shape: str) -> int:
    """Total byte volume of an HLO result shape (array or tuple of arrays)."""
    total = 0
    for m in _SHAPE_RE.finditer(shape):
        dims = m.group("dims")
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(m.group("dtype"), 4)
    return total


def _element_bytes(shape: str) -> list[tuple[str, str, int]]:
    """Per-element (dtype, dims, bytes) list of a (possibly tuple) HLO shape."""
    out = []
    for m in _SHAPE_RE.finditer(shape):
        n = 1
        for d in m.group("dims").split(","):
            if d:
                n *= int(d)
        out.append(
            (m.group("dtype"), m.group("dims"), n * _DTYPE_BYTES.get(m.group("dtype"), 4))
        )
    return out


def _async_start_bytes(shape: str) -> int:
    """Result bytes of an async ``<op>-start`` instruction.

    Async collectives lower with tuple shapes carrying the OPERAND buffer(s)
    alongside the result(s) (e.g. ``all-gather-start = (f32[S/N], f32[S])``,
    ``collective-permute-start = (f32[S], f32[S], u32[], u32[])``) — a plain
    tuple sum double-counts.  Context state is always SCALAR integer elements
    (``u32[]``); integer payloads (int8 weight shards, routing indices) keep
    their dims and stay counted.  Among the payload elements: equal
    front/back halves means (operands..., results...) of a combined
    same-shape collective — count the back half; otherwise the result is the
    final element (all-gather: the gathered buffer; reduce-scatter: the
    scattered shard)."""
    elems = [
        b
        for dtype, dims, b in _element_bytes(shape)
        if not (dims == "" and dtype.startswith(("u", "s")))
    ]
    if not elems:
        return parse_shape_bytes(shape)
    if len(elems) >= 2 and len(elems) % 2 == 0:
        half = len(elems) // 2
        if elems[:half] == elems[half:]:
            return sum(elems[half:])
    return elems[-1]


def _parse_groups(line: str) -> Optional[list[list[int]]]:
    """Extract replica groups as id lists: ``{{0,4},{1,5}}`` -> [[0,4],[1,5]].
    ``source_target_pairs`` (collective-permute) parse into 2-member groups so
    axis classification treats each hop as one communicating pair."""
    m = _REPLICA_GROUPS_RE.search(line)
    if m is not None:
        body = m.group("groups")
        groups = [
            [int(x) for x in g.split(",") if x.strip()]
            for g in re.findall(r"\{([^{}]*)\}", body)
        ]
        return [g for g in groups if g] or None
    m = _SOURCE_TARGET_RE.search(line)
    if m is not None:
        pairs = re.findall(r"\{(\d+),(\d+)\}", m.group("pairs"))
        return [[int(a), int(b)] for a, b in pairs] or None
    m = _IOTA_GROUPS_RE.search(line)
    if m is not None:
        dims = [int(d) for d in m.group("dims").split(",")]
        # Iota groups: the trailing dim is the per-group member count; expand
        # to consecutive-id groups (iota order, no transpose support — a
        # transposed iota loses axis attribution but keeps sizes right).
        n_groups, group_size = int(np.prod(dims[:-1], dtype=int)), dims[-1]
        return [
            list(range(g * group_size, (g + 1) * group_size)) for g in range(n_groups)
        ]
    return None


def _mesh_coords(mesh) -> dict:
    """Device id -> mesh coordinates, from the mesh's own device array
    (replica groups use global device ids when use_global_device_ids=true,
    which is how jax emits SPMD collectives)."""
    coords = {}
    for i, dev in enumerate(mesh.devices.reshape(-1)):
        coords[int(dev.id)] = np.unravel_index(i, mesh.devices.shape)
    return coords


def classify_groups(
    groups: Optional[list[list[int]]], mesh=None, coords: Optional[dict] = None
) -> tuple[Optional[tuple[str, ...]], int]:
    """Map replica groups onto mesh axis names.

    Returns ``(axes, group_size)`` where ``axes`` is the tuple of mesh axes
    whose coordinates vary within a group (mesh axis order), or ``None`` when
    no mesh was given / the ids don't match it.  ``group_size`` is the largest
    group's member count — 1 means degenerate (no traffic), 0 unknown.
    ``coords`` lets a scan over many collectives reuse one
    :func:`_mesh_coords` map instead of rebuilding it per instruction.
    """
    if not groups:
        # No replica_groups attribute: the collective spans every device.
        if mesh is None:
            return None, 0
        active = tuple(a for a in mesh.axis_names if mesh.shape[a] > 1)
        return active, int(np.prod([mesh.shape[a] for a in active], dtype=int)) if active else 1
    size = max(len(g) for g in groups)
    if mesh is None or size <= 1:
        return None, size
    if coords is None:
        coords = _mesh_coords(mesh)
    varying: set[int] = set()
    for g in groups:
        cs = [coords.get(d) for d in g]
        if any(c is None for c in cs):
            return None, size  # ids outside this mesh (e.g. a sub-mesh program)
        for dim in range(len(mesh.axis_names)):
            if len({c[dim] for c in cs}) > 1:
                varying.add(dim)
    if not varying:
        return None, size
    return tuple(mesh.axis_names[d] for d in sorted(varying)), size


def _computation_multipliers(hlo_text: str) -> dict:
    """Map computation name -> product of enclosing while-loop trip counts.

    XLA stamps counted loops (every ``lax.scan``) with
    ``backend_config={"known_trip_count":{"n":...}}``; when that is missing
    the trip count falls back to the condition computation's
    constant-vs-induction-variable ``compare`` (LT -> N, LE -> N+1), else 1
    (the static convention).  Multipliers compose through nested loops (the
    layer scan inside the pipeline tick scan) by walking while edges to a
    fixpoint."""
    # Pass 1: split into computations and find while edges.
    comp_lines: dict = {}
    current = None
    entry = None
    for line in hlo_text.splitlines():
        m = _COMPUTATION_RE.match(line)
        if m is not None:
            current = m.group("name")
            comp_lines[current] = []
            if m.group("entry"):
                entry = current
            continue
        if line.startswith("}"):
            current = None
            continue
        if current is not None:
            comp_lines[current].append(line)

    def _cond_trip(cond_name: str) -> int:
        direction = None
        constants = []
        for line in comp_lines.get(cond_name, ()):
            mc = _COND_COMPARE_RE.search(line)
            if mc is not None:
                direction = mc.group("dir")
            constants.extend(int(n) for n in _COND_CONSTANT_RE.findall(line))
        if direction in ("LT", "GT") and constants:
            return max(constants)
        if direction in ("LE", "GE") and constants:
            return max(constants) + 1
        return 1

    edges: dict = {}  # computation -> [(child computation, trip)]
    for name, lines in comp_lines.items():
        for line in lines:
            if _WHILE_RE.search(line) is None:
                continue
            body_m = _WHILE_BODY_RE.search(line)
            cond_m = _WHILE_COND_RE.search(line)
            trip_m = _TRIP_COUNT_RE.search(line)
            if trip_m is not None:
                trip = int(trip_m.group("n"))
            elif cond_m is not None:
                trip = _cond_trip(cond_m.group("name"))
            else:
                trip = 1
            for ref in (body_m, cond_m):
                if ref is not None:
                    edges.setdefault(name, []).append((ref.group("name"), trip))

    # Pass 2: propagate from the entry down the while nest to a fixpoint
    # (bounded by the computation count — while nests cannot be cyclic).
    mult = {name: 1 for name in comp_lines}
    if entry is not None:
        mult[entry] = 1
    for _ in range(len(comp_lines)):
        changed = False
        for parent, children in edges.items():
            for child, trip in children:
                new = mult.get(parent, 1) * max(trip, 1)
                if new > mult.get(child, 1):
                    mult[child] = new
                    changed = True
        if not changed:
            break
    return mult


def parse_collectives(
    hlo_text: str, mesh=None, trip_counts: bool = False
) -> list[CollectiveOp]:
    """Scan optimized HLO text for collective instructions.
    ``trip_counts=True`` additionally resolves each op's enclosing while-loop
    trip-count product (``CollectiveOp.trip_count``; defaults to 1 otherwise).

    Byte convention: the LARGE side of the transfer, per participating
    device.  For all-reduce/all-gather/all-to-all/collective-permute that is
    the result shape.  ``reduce-scatter`` is the one collective whose result
    is the SMALL side — each device receives ``operand/group_size`` — so its
    result bytes are scaled back up by the replica-group size (operand-shape
    accounting).  That keeps the cross-kind invariants comparable: a dp grad
    all-reduce, its ZeRO reduce-scatter replacement, and the matching param
    all-gather all ledger ≈ param bytes.
    """
    ops = []
    coords = _mesh_coords(mesh) if mesh is not None else None
    # The loop-multiplier pass is a second full-text scan — only pay for it
    # when the caller wants executed-bytes trip counts.
    multipliers = _computation_multipliers(hlo_text) if trip_counts else {}
    current_comp = None
    for line in hlo_text.splitlines():
        cm = _COMPUTATION_RE.match(line)
        if cm is not None:
            current_comp = cm.group("name")
            continue
        if line.startswith("}"):
            current_comp = None
            continue
        m = _COLLECTIVE_RE.match(line)
        if m is None:
            continue
        groups = _parse_groups(line)
        axes, group_size = classify_groups(groups, mesh, coords)
        name_m = _OP_NAME_RE.search(line)
        shape = m.group("shape")
        nbytes = _async_start_bytes(shape) if m.group("start") else parse_shape_bytes(shape)
        if m.group("kind") == "reduce-scatter" and group_size > 1:
            # Result is the scattered SHARD; the per-device transfer volume
            # is the full (operand-sized) reduction the shard came from.
            nbytes *= group_size
        ops.append(
            CollectiveOp(
                kind=m.group("kind"),
                bytes=nbytes,
                axes=axes,
                group_size=group_size,
                op_name=name_m.group("name") if name_m else "",
                trip_count=multipliers.get(current_comp, 1),
            )
        )
    return ops


def scan_hlo(hlo_text: str, mesh=None, unroll_loops: bool = False) -> CommsLedger:
    """Build the comms ledger for one compiled program's optimized HLO.

    Byte volumes are the collective's **large-side bytes on one participating
    device** (see :func:`parse_collectives`) — for an all-reduce of a
    replicated gradient this equals the gradient's full byte size, and for
    its ZeRO reduce-scatter replacement the operand-side accounting lands on
    the same figure, which is what makes the dp-grad-sync invariants
    (`all-reduce ≈ param bytes`, `reduce-scatter + all-gather ≈ param bytes
    each`) checkable.  Degenerate collectives (single-member groups — no
    traffic) are counted separately, not in the totals.

    ``unroll_loops=True`` aggregates EXECUTED bytes instead of static ones:
    each op's bytes x the product of its enclosing while trip counts — the
    convention the pp permute invariant (per-tick activation bytes x
    pipeline ticks) is checked on.
    """
    all_ops = parse_collectives(hlo_text, mesh, trip_counts=unroll_loops)
    ops = [op for op in all_ops if not op.is_degenerate]
    by_kind: dict = {}
    by_axis: dict = {}
    total = 0
    for op in ops:
        nbytes = op.executed_bytes if unroll_loops else op.bytes
        agg = by_kind.setdefault(op.kind, {"count": 0, "bytes": 0})
        agg["count"] += 1
        agg["bytes"] += nbytes
        axis_key = "+".join(op.axes) if op.axes else "?"
        by_axis[axis_key] = by_axis.get(axis_key, 0) + nbytes
        total += nbytes
    return CommsLedger(
        ops=ops,
        by_kind=by_kind,
        by_axis=by_axis,
        total_bytes=total,
        degenerate_ops=len(all_ops) - len(ops),
    )
