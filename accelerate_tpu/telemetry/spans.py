"""Trace spans: ``with span("name"):`` / ``@span("name")``.

Each span records wall-time, process index, and nesting (a thread-local name
stack) to the telemetry JSONL sink, and mirrors into
``jax.profiler.TraceAnnotation`` so the same names show up in Perfetto/XPlane
dumps captured with ``Accelerator.profile()``.

When telemetry is disabled, ``__enter__`` is a single attribute check — safe
to leave on every hot path.
"""

from __future__ import annotations

import functools
import threading
import time

from .core import get_telemetry

__all__ = ["span"]

_tls = threading.local()


class span:
    """Context manager AND decorator.

    >>> with span("checkpoint.save", path=out_dir):
    ...     ...
    >>> @span("train_step")
    ... def train_step(...): ...
    """

    __slots__ = ("name", "attrs", "_tel", "_t0", "_ann", "_path")

    def __init__(self, name: str, **attrs):
        self.name = name
        self.attrs = attrs
        self._tel = None
        self._t0 = None
        self._ann = None
        self._path = None

    def __enter__(self):
        tel = get_telemetry()
        if not tel.enabled:
            return self
        self._tel = tel
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self._path = "/".join(stack + [self.name])
        stack.append(self.name)
        try:
            import jax

            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._t0 is None:  # telemetry was off at __enter__
            return False
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        self._t0 = None
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
            self._ann = None
        stack = _tls.stack
        if stack and stack[-1] == self.name:
            stack.pop()
        tel = self._tel
        self._tel = None
        record = {
            "kind": "span",
            "name": self.name,
            "path": self._path,
            "depth": len(stack),
            "dur_ms": round(dur_ms, 3),
        }
        if exc_type is not None:
            record["error"] = exc_type.__name__
        if self.attrs:
            record["attrs"] = self.attrs
        tel.write(record)
        tel.registry.histogram(f"span.{self.name}_ms").observe(dur_ms)
        return False

    def __call__(self, fn):
        name, attrs = self.name, self.attrs

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            # A fresh span per call: enablement is re-checked at call time, so
            # decorating at import time costs nothing until telemetry turns on.
            with span(name, **attrs):
                return fn(*args, **kwargs)

        return wrapped
