"""Unified HBM ledger: per-subsystem memory attribution + OOM forensics.

Who owns device memory?  Before this module the answer was two raw gauges
sampled from one device (``collect_hbm``) and a static per-program
``memory_analysis()`` — enough to see *that* HBM filled up, useless to say
*why*.  The :class:`MemoryLedger` is the goodput-ledger discipline applied to
bytes instead of seconds: long-lived owners (model params, optimizer state,
the paged KV pool, prefix-cache residents, host-offload buffers, prefetch
staging) **register** reservations computed from their live pytree's actual
per-device sharded bytes, and every reconcile checks the result against
``device.memory_stats()`` on ALL local devices under a conservation
contract:

    attributed + program_estimate + unattributed == bytes_in_use   (per device)

The residual (``unattributed``) is exposed, never silently absorbed — a
growing residual is the "whose allocation is this?" alarm.  The
``program_estimate`` term is the XLA temp/scratch + generated-code bytes of
the inspected compiled programs (``introspect.py`` feeds it), i.e. memory a
*program* owns rather than a live array.

Registration stores **integers, never array references**: computing bytes at
register time keeps the ledger from extending donated-buffer lifetimes.
Sharded leaves contribute ``shard_shape`` bytes to each addressable device
they live on; leaves placed in a non-default memory space (host offload)
count under ``host_bytes`` instead of device HBM.

On top of the ledger:

- **OOM forensics** — :meth:`MemoryLedger.note_oom` snapshots the ranked
  ledger into a ``memory.oom_postmortem`` event (mirrored into the flight
  recorder when armed) naming the *blamed owner*: the largest per-chip
  reservation at the moment of death.  Wired into every
  ``RESOURCE_EXHAUSTED`` site: ``find_executable_batch_size`` halvings, the
  resilience retry fail-fast path, and serving admission
  (``scheduler.grow_to`` with nothing left to evict).
- **Gauges** — ``memory.attributed_bytes`` / ``memory.unattributed_bytes``
  (worst device), ``memory.headroom_bytes`` (fleet min of
  ``bytes_limit - bytes_in_use``; absent where the backend reports no
  stats), and per-owner ``memory.owner.{name}_bytes``.
- **Serving headroom** — the engine registers its pool + prefix cache and
  publishes ``serving.headroom_bytes`` (see ``serving/engine.py``).

CPU builds: ``device.memory_stats()`` returns ``None`` on the XLA host
platform, so per-device records carry ``stats_available: 0`` and no
conservation arithmetic is invented.  ``reconcile(stats_fn=...)`` takes an
injectable per-device stats provider so tests and the smoke can assert the
contract honestly without TPU hardware.

Process-wide singleton via :func:`get_memory_ledger`; the full JSON view
(:meth:`snapshot`) backs the ``/debug/memory`` endpoint and the report's
memory block.  See ``docs/package_reference/memledger.md``.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = [
    "MemoryLedger",
    "Reservation",
    "get_memory_ledger",
    "tree_device_bytes",
    "looks_like_oom",
]

# Substrings that mark an exception as an out-of-memory failure (the
# utils/memory.py should_reduce_batch_size list, duplicated here because
# utils imports telemetry — the reverse import would cycle).
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "out of memory",
    "OOM",
    "Attempting to allocate",
    "CUDA out of memory",
)


def looks_like_oom(exc: BaseException) -> bool:
    """Whether ``exc`` smells like a device OOM (RESOURCE_EXHAUSTED et al.)."""
    text = str(exc)
    return any(marker in text for marker in _OOM_MARKERS)


def _owner_slug(owner: str) -> str:
    """Owner name → gauge-safe slug (``memory.owner.{slug}_bytes``)."""
    return re.sub(r"[^0-9A-Za-z_]+", "_", owner).strip("_") or "owner"


def tree_device_bytes(tree) -> tuple[Dict[int, int], int, int]:
    """Per-device byte footprint of a pytree of jax Arrays.

    Returns ``(per_device, host_bytes, n_leaves)`` where ``per_device`` maps
    device id → bytes of the shards resident there (replicated leaves charge
    every device their full size — that is what the HBM actually holds), and
    ``host_bytes`` collects leaves placed in a non-default memory space
    (host offload): those shards occupy pinned host DRAM, not device HBM.
    Only integers escape — no references to ``tree``'s (possibly donated)
    buffers survive the call.
    """
    import numpy as np

    import jax

    per_device: Dict[int, int] = {}
    host_bytes = 0
    n_leaves = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if not isinstance(leaf, jax.Array):
            continue
        n_leaves += 1
        sharding = leaf.sharding
        shard_nbytes = int(np.prod(sharding.shard_shape(leaf.shape))) * leaf.dtype.itemsize
        devices = list(getattr(sharding, "_addressable_device_assignment", None) or [])
        if not devices:
            try:
                devices = [d for d in sharding.device_set if d.process_index == jax.process_index()]
            except Exception:
                devices = []
        on_host = False
        kind = getattr(sharding, "memory_kind", None)
        if kind is not None and devices:
            try:
                on_host = kind != devices[0].default_memory().kind
            except Exception:
                on_host = False
        if on_host:
            host_bytes += shard_nbytes * max(len(devices), 1)
        else:
            for d in devices:
                per_device[d.id] = per_device.get(d.id, 0) + shard_nbytes
    return per_device, host_bytes, n_leaves


class Reservation:
    """One owner's registered footprint — plain integers only."""

    __slots__ = ("owner", "per_device", "host_bytes", "n_leaves", "subset_of", "detail", "token", "t")

    def __init__(
        self,
        owner: str,
        per_device: Dict[int, int],
        host_bytes: int = 0,
        n_leaves: int = 0,
        subset_of: Optional[str] = None,
        detail: Optional[dict] = None,
        token: int = 0,
    ):
        self.owner = owner
        self.per_device = dict(per_device)
        self.host_bytes = int(host_bytes)
        self.n_leaves = int(n_leaves)
        # ``subset_of``: these bytes live INSIDE another owner's reservation
        # (prefix-cache residents inside the KV pool).  Ranked views show
        # them; conservation sums skip them — double counting would poison
        # the residual.
        self.subset_of = subset_of
        self.detail = dict(detail or {})
        self.token = token
        self.t = time.time()

    @property
    def device_bytes(self) -> int:
        """Worst single device — the per-chip footprint (the binding
        constraint under symmetric SPMD; replicated trees report their
        full size, sharded ones their shard)."""
        return max(self.per_device.values(), default=0)

    @property
    def total_device_bytes(self) -> int:
        return sum(self.per_device.values())

    def to_dict(self) -> dict:
        out = {
            "owner": self.owner,
            "bytes_per_device": {str(k): v for k, v in sorted(self.per_device.items())},
            "device_bytes": self.device_bytes,
            "host_bytes": self.host_bytes,
            "n_leaves": self.n_leaves,
        }
        if self.subset_of:
            out["subset_of"] = self.subset_of
        if self.detail:
            out["detail"] = self.detail
        return out


def _default_stats_fn(device) -> Optional[dict]:
    try:
        return device.memory_stats()
    except Exception:
        return None


class MemoryLedger:
    """Process-wide registry of long-lived HBM reservations, reconciled
    against live per-device memory stats under the conservation contract."""

    def __init__(self):
        self._lock = threading.Lock()
        self._owners: Dict[str, Reservation] = {}
        self._program_bytes: Dict[str, int] = {}
        self._tokens = 0
        # Last reconcile's per-device records (the watermark note_oom snapshots
        # even when reconcile cannot run at the crash site).
        self._last_devices: List[dict] = []
        self.oom_postmortems: List[dict] = []

    # -- registration --------------------------------------------------------

    def register(
        self,
        owner: str,
        tree=None,
        *,
        nbytes: Optional[int] = None,
        per_device: Optional[Dict[int, int]] = None,
        host_bytes: int = 0,
        subset_of: Optional[str] = None,
        detail: Optional[dict] = None,
    ) -> int:
        """Register (or replace) owner ``owner``'s reservation.

        Exactly one of ``tree`` (live pytree — bytes computed per device from
        its actual shardings), ``per_device`` (explicit mapping), or
        ``nbytes`` (flat bytes charged to every local device — the right
        shape for a replicated pool allocated outside a pytree) must be
        given.  Returns an ownership token for :meth:`unregister`.
        """
        n_leaves = 0
        if tree is not None:
            per_device, tree_host, n_leaves = tree_device_bytes(tree)
            host_bytes = host_bytes + tree_host
        elif per_device is not None:
            per_device = {int(k): int(v) for k, v in per_device.items()}
        elif nbytes is not None:
            per_device = {}
            try:
                import jax

                for d in jax.local_devices():
                    per_device[d.id] = int(nbytes)
            except Exception:
                per_device = {0: int(nbytes)}
        else:
            raise ValueError("register() needs one of tree=, per_device=, nbytes=")
        with self._lock:
            self._tokens += 1
            token = self._tokens
            self._owners[owner] = Reservation(
                owner, per_device, host_bytes, n_leaves, subset_of, detail, token
            )
        return token

    def update_bytes(self, owner: str, nbytes: int, token: Optional[int] = None) -> bool:
        """Refresh an existing reservation's bytes in place (token-guarded,
        registration identity kept) — the cheap per-tick path for owners
        whose footprint moves, like prefix-cache residents.  Every device the
        reservation was registered on takes the new per-device value."""
        with self._lock:
            res = self._owners.get(owner)
            if res is None or (token is not None and res.token != token):
                return False
            res.per_device = {k: int(nbytes) for k in (res.per_device or {0: 0})}
            return True

    def unregister(self, owner: str, token: Optional[int] = None) -> bool:
        """Drop ``owner``; with ``token``, only when it still owns the entry
        (a replaced registration keeps the replacement)."""
        with self._lock:
            res = self._owners.get(owner)
            if res is None or (token is not None and res.token != token):
                return False
            del self._owners[owner]
            return True

    def has_owners(self) -> bool:
        return bool(self._owners)

    def owners(self) -> List[Reservation]:
        """Reservations ranked by per-chip footprint, largest first."""
        with self._lock:
            items = list(self._owners.values())
        return sorted(items, key=lambda r: (-r.device_bytes, r.owner))

    def note_program_bytes(self, program: str, nbytes: int) -> None:
        """Record one compiled program's temp/scratch + generated-code bytes
        (the inspector calls this; latest capture per program wins).  Summed
        into the conservation contract's ``program_estimate`` term — memory a
        program owns rather than a live array."""
        with self._lock:
            self._program_bytes[program] = int(nbytes)

    def program_estimate(self) -> int:
        with self._lock:
            return sum(self._program_bytes.values())

    def reset(self) -> None:
        with self._lock:
            self._owners.clear()
            self._program_bytes.clear()
            self._last_devices = []
            self.oom_postmortems = []

    # -- reconciliation ------------------------------------------------------

    def attributed_per_device(self) -> Dict[int, int]:
        """Summed registered bytes per device (subset entries excluded)."""
        out: Dict[int, int] = {}
        for res in self.owners():
            if res.subset_of:
                continue
            for dev, b in res.per_device.items():
                out[dev] = out.get(dev, 0) + b
        return out

    def reconcile(self, stats_fn: Optional[Callable] = None) -> List[dict]:
        """One conservation pass over every local device.

        ``stats_fn(device)`` must return a ``memory_stats()``-shaped dict or
        ``None`` (the default asks the device; CPU builds return ``None`` and
        the record honestly carries ``stats_available: 0`` instead of invented
        arithmetic).  Where stats exist::

            attributed + program_estimate + unattributed == bytes_in_use

        holds per device **by construction** — ``unattributed`` is defined as
        the residual, including a *negative* one (attribution exceeding the
        allocator's count means a stale registration; that is a finding, not
        an error to clamp away).
        """
        stats_fn = stats_fn or _default_stats_fn
        try:
            import jax

            devices = list(jax.local_devices())
        except Exception:
            devices = []
        attributed = self.attributed_per_device()
        program = self.program_estimate()
        records = []
        for d in devices:
            stats = stats_fn(d) or None
            att = attributed.get(d.id, 0)
            rec = {
                "device": d.id,
                "platform": getattr(d, "platform", "?"),
                "attributed_bytes": att,
                "program_estimate_bytes": program,
                "stats_available": 1 if stats else 0,
            }
            if stats:
                in_use = int(stats.get("bytes_in_use", 0))
                rec["bytes_in_use"] = in_use
                rec["unattributed_bytes"] = in_use - att - program
                if "peak_bytes_in_use" in stats:
                    rec["peak_bytes_in_use"] = int(stats["peak_bytes_in_use"])
                limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
                if limit:
                    rec["bytes_limit"] = int(limit)
                    rec["headroom_bytes"] = int(limit) - in_use
            records.append(rec)
        with self._lock:
            self._last_devices = records
        return records

    def min_device_headroom(self) -> Optional[int]:
        """Fleet-min ``bytes_limit - bytes_in_use`` from the last reconcile
        (None when no device reported stats — CPU builds)."""
        with self._lock:
            rooms = [r["headroom_bytes"] for r in self._last_devices if "headroom_bytes" in r]
        return min(rooms) if rooms else None

    def snapshot(self) -> dict:
        """The full ledger view (the ``/debug/memory`` body and the report's
        memory block): ranked owners, per-device conservation records, and
        the program-estimate term."""
        owners = self.owners()
        with self._lock:
            devices = list(self._last_devices)
            programs = dict(self._program_bytes)
        attributed = self.attributed_per_device()
        return {
            "owners": [r.to_dict() for r in owners],
            "devices": devices,
            "attributed_bytes_per_device": {str(k): v for k, v in sorted(attributed.items())},
            "attributed_bytes": max(attributed.values(), default=0),
            "host_bytes": sum(r.host_bytes for r in owners),
            "program_estimate_bytes": sum(programs.values()),
            "programs": programs,
            "oom_postmortems": len(self.oom_postmortems),
        }

    # -- gauges --------------------------------------------------------------

    def publish(self, registry) -> None:
        """Land the ledger's fleet-level view as ``memory.*`` gauges."""
        attributed = self.attributed_per_device()
        registry.gauge("memory.attributed_bytes").set(max(attributed.values(), default=0))
        with self._lock:
            devices = list(self._last_devices)
        residuals = [r["unattributed_bytes"] for r in devices if "unattributed_bytes" in r]
        if residuals:
            # Worst device by magnitude: a large negative residual (stale
            # registration) is as alarming as a large positive one.
            registry.gauge("memory.unattributed_bytes").set(max(residuals, key=abs))
        headroom = self.min_device_headroom()
        if headroom is not None:
            registry.gauge("memory.headroom_bytes").set(headroom)
        for res in self.owners():
            slug = _owner_slug(res.owner)
            registry.gauge(f"memory.owner.{slug}_bytes").set(res.device_bytes)

    def reconcile_and_publish(self, registry, stats_fn: Optional[Callable] = None) -> List[dict]:
        records = self.reconcile(stats_fn=stats_fn)
        self.publish(registry)
        return records

    # -- OOM forensics -------------------------------------------------------

    def note_oom(self, source: str, error: Optional[BaseException] = None, **extra) -> dict:
        """Snapshot the ranked ledger at an OOM site into a
        ``memory.oom_postmortem`` event (flight-recorder mirrored when the
        ring is armed) and name the blamed owner: the largest per-chip
        reservation alive at the moment of death.  Never raises — a
        forensics hook must not mask the OOM it is narrating."""
        try:
            owners = self.owners()
            blamed = next((r for r in owners if not r.subset_of), None)
            # Refresh the watermark AT the crash site (best effort — a truly
            # wedged device keeps the last reconcile's numbers instead).
            try:
                self.reconcile()
            except Exception:
                pass
            with self._lock:
                devices = list(self._last_devices)
            peak = max(
                (r.get("peak_bytes_in_use") for r in devices if r.get("peak_bytes_in_use")),
                default=None,
            )
            in_use = max(
                (r.get("bytes_in_use") for r in devices if r.get("bytes_in_use")),
                default=None,
            )
            postmortem = {
                "source": source,
                "blame": blamed.owner if blamed is not None else None,
                "blame_bytes": blamed.device_bytes if blamed is not None else None,
                "attributed_bytes": sum(
                    r.device_bytes for r in owners if not r.subset_of
                ),
                "ranked": [
                    {"owner": r.owner, "device_bytes": r.device_bytes}
                    for r in owners[:8]
                ],
                "watermark_bytes_in_use": in_use,
                "watermark_peak_bytes": peak,
                "error": f"{type(error).__name__}: {error}"[:300] if error is not None else None,
                **extra,
            }
            self.oom_postmortems.append(postmortem)
            from .core import get_telemetry

            tel = get_telemetry()
            if tel.enabled:
                tel.registry.counter("memory.oom_postmortems").inc()
            # event() writes to the JSONL sink only when telemetry is on but
            # mirrors into the flight recorder whenever the ring is armed —
            # exactly the durability an OOM postmortem needs.
            tel.event("memory.oom_postmortem", **postmortem)
            return postmortem
        except Exception:
            return {"source": source, "blame": None, "error": "postmortem failed"}


_LEDGER = MemoryLedger()


def get_memory_ledger() -> MemoryLedger:
    return _LEDGER
