"""Telemetry runtime: enablement, per-process JSONL sink, singleton wiring.

Default-OFF.  Enable with ``ACCELERATE_TPU_TELEMETRY=1`` (honored by
``Accelerator.__init__``) or programmatically via ``telemetry.enable()``.
When disabled, the instrumented hot paths reduce to one attribute check — no
file handles, no listeners firing, no records.

JSONL schema (one record per line, ``telemetry_p<process>.jsonl``):

- ``{"kind": "span", "name", "path", "depth", "dur_ms", "t", "proc", ...}``
- ``{"kind": "compile", "dur_ms", ...}`` — one per XLA backend compile (cache miss)
- ``{"kind": "stall", "elapsed_s", "deadline_s", "threads", ...}``
- ``{"kind": "event", "name", ...}`` — ad-hoc markers
- ``{"kind": "metrics", "snapshot": {...}}`` — final registry dump on disable/exit
- ``{"kind": "meta", ...}`` — run bookkeeping (enable time, pid)
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Optional

from .flightrec import get_flight_recorder
from .memledger import get_memory_ledger
from .metrics import CACHE_HIT_EVENT, COMPILE_EVENT, MetricsRegistry, StepTimer, collect_hbm

__all__ = [
    "Telemetry",
    "get_telemetry",
    "enabled",
    "enable",
    "disable",
    "maybe_enable_from_env",
    "ENV_ENABLE",
    "ENV_DIR",
    "ENV_STALL_TIMEOUT",
]

ENV_ENABLE = "ACCELERATE_TPU_TELEMETRY"
ENV_DIR = "ACCELERATE_TPU_TELEMETRY_DIR"
ENV_STALL_TIMEOUT = "ACCELERATE_TPU_STALL_TIMEOUT_S"
DEFAULT_DIR = "telemetry"

_TRUTHY = {"1", "true", "yes", "on"}


def _env_flag(key: str) -> bool:
    return os.environ.get(key, "").strip().lower() in _TRUTHY


class Telemetry:
    """Process-wide telemetry hub: owns the metrics registry, the JSONL sink,
    the step timer, and (optionally) the stall watchdog."""

    def __init__(self):
        self.enabled = False
        self.dir: Optional[str] = None
        self.registry = MetricsRegistry()
        self.step_timer = StepTimer(self.registry)
        self.watchdog = None
        self._file = None
        self._lock = threading.Lock()
        self._proc: Optional[int] = None
        self._atexit_registered = False
        # pipeline.dispatches value at the last completed step — the delta
        # is the dispatches/step gauge.
        self._dispatch_mark = 0
        # Goodput ledger (goodput.py): when attached, every record written
        # through this hub is also classified into the wall-clock ledger.
        self.goodput = None
        self._goodput_steps = 0
        # Fleet aggregator (multi-host straggler/goodput gather); resolved
        # lazily on the first completed step so construction never touches
        # the backend.
        self._fleet = None
        self._fleet_resolved = False

    # -- lifecycle -----------------------------------------------------------

    def enable(self, dir: Optional[str] = None, stall_timeout_s: Optional[float] = None):
        """Turn telemetry on (idempotent).  ``dir`` defaults to
        ``$ACCELERATE_TPU_TELEMETRY_DIR`` then ``./telemetry``; a positive
        ``stall_timeout_s`` (or ``$ACCELERATE_TPU_STALL_TIMEOUT_S``) arms the
        stall watchdog."""
        if self.enabled:
            return self
        self.dir = dir or os.environ.get(ENV_DIR) or DEFAULT_DIR
        os.makedirs(self.dir, exist_ok=True)
        # Fresh-run semantics: a re-enable starts a new measurement window.
        self.registry.reset()
        self.step_timer.reset()
        self._dispatch_mark = 0
        self._file = None
        self.enabled = True
        _install_compile_listener()
        if stall_timeout_s is None:
            try:
                stall_timeout_s = float(os.environ.get(ENV_STALL_TIMEOUT, "0") or 0)
            except ValueError:
                stall_timeout_s = 0.0
        if stall_timeout_s and stall_timeout_s > 0:
            from .watchdog import StallWatchdog

            self.watchdog = StallWatchdog(stall_timeout_s, telemetry=self)
            self.watchdog.start()
        if not self._atexit_registered:
            self._atexit_registered = True
            atexit.register(self.disable)
        from . import export, goodput

        if goodput.enabled_from_env():
            goodput.attach()
        export.maybe_start_from_env()
        self.write({"kind": "meta", "event": "enabled", "pid": os.getpid()})
        return self

    def disable(self):
        """Flush the final metrics snapshot and turn everything off."""
        if not self.enabled:
            return
        if self.goodput is not None:
            # The ledger's last word lands in the final snapshot (and in the
            # exporter's final file write below).
            try:
                self.goodput.publish(self.registry)
            except Exception:
                pass
        self.write({"kind": "metrics", "snapshot": self.registry.snapshot()})
        self.enabled = False
        from . import export

        export.stop_if_running()
        self.goodput = None
        self._goodput_steps = 0
        self._fleet = None
        self._fleet_resolved = False
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    # -- sink ----------------------------------------------------------------

    def _process_index(self) -> int:
        if self._proc is None:
            try:
                import jax

                self._proc = int(jax.process_index())
            except Exception:
                self._proc = 0
        return self._proc

    @property
    def jsonl_path(self) -> Optional[str]:
        if self.dir is None:
            return None
        return os.path.join(self.dir, f"telemetry_p{self._process_index()}.jsonl")

    def write(self, record: dict):
        if not self.enabled:
            return
        record.setdefault("t", time.time())
        record.setdefault("proc", self._process_index())
        line = json.dumps(record, default=str)
        with self._lock:
            if self._file is None:
                # Line-buffered append: records are durable per line, so a
                # crashed run still leaves a parseable file.
                self._file = open(self.jsonl_path, "a", buffering=1)
            self._file.write(line + "\n")
        ledger = self.goodput
        if ledger is not None:
            # Classify outside the sink lock: the ledger has its own.
            try:
                ledger.observe_record(record)
            except Exception:
                pass
        if record.get("kind") == "stall":
            # Mirror watchdog stalls into the flight recorder as anomalies:
            # a stalled run is exactly the one about to be killed from
            # outside, so the durable timeline must carry it.
            rec = get_flight_recorder()
            if rec.enabled:
                rec.note_stall(
                    record.get("elapsed_s") or 0.0, record.get("deadline_s") or 0.0
                )

    def event(self, name: str, **fields):
        self.write({"kind": "event", "name": name, **fields})
        # Mirror ad-hoc markers into the flight recorder: preemption signals
        # and checkpoints, I/O retries, health rewinds — the resilience
        # subsystem already narrates itself through event(), so the durable
        # ring gets the same narration for free.
        rec = get_flight_recorder()
        if rec.enabled:
            rec.record("event", name=name, **fields)

    # -- hot-path hooks ------------------------------------------------------

    def heartbeat(self):
        """Liveness signal for the stall watchdog (batch fetched, step done)."""
        if self.watchdog is not None:
            self.watchdog.beat()

    def count_dispatch(self, n: int = 1):
        """Tally ``n`` Python→XLA dispatch sites on the training hot path
        (a jitted call, a host-side gradient scale/accumulate, an optimizer
        update).  ``record_step`` folds the tally into the
        ``pipeline.dispatches_per_step`` gauge — the eager loop lands at
        ``3 × accum_steps`` per optimizer step, the fused train step at 1."""
        if self.enabled:
            self.registry.counter("pipeline.dispatches").inc(n)

    def record_step(self):
        """Mark one COMPLETED optimizer step: step-time histogram, derived
        tokens/sec + MFU gauges, HBM gauges, dispatches/step gauge, watchdog
        heartbeat."""
        if not self.enabled:
            return
        dt = self.step_timer.step()
        collect_hbm(self.registry)
        ledger = get_memory_ledger()
        if ledger.has_owners():
            # Conservation pass: attributed + program + unattributed ==
            # bytes_in_use per device, residual exposed as a gauge.  Owners
            # register once (train-step build, engine construction), so the
            # per-step cost is one memory_stats() round per local device.
            try:
                ledger.reconcile_and_publish(self.registry)
            except Exception:
                pass
        dispatches = self.registry.counter("pipeline.dispatches").value
        per_step = None
        if dispatches:
            per_step = dispatches - self._dispatch_mark
            self.registry.gauge("pipeline.dispatches_per_step").set(per_step)
        self._dispatch_mark = dispatches
        rec = get_flight_recorder()
        if rec.enabled:
            blocked = self.registry.peek("pipeline.host_blocked_ms")
            rec.note_step(
                step=self.registry.counter("step.count").value,
                dur_ms=dt * 1e3 if dt is not None else None,
                dispatches=per_step,
                host_blocked_ms=blocked.last if blocked is not None else None,
            )
        if self.goodput is not None:
            # Cadence-gated: the gauge refresh runs a full interval sweep,
            # which has no business on every hot-path step — the exporter
            # re-publishes on each scrape and disable() lands the final
            # value; this keeps the in-registry gauges merely *fresh-ish*
            # (first step, then every 16th).
            self._goodput_steps += 1
            if self._goodput_steps % 16 == 1:
                try:
                    self.goodput.publish(self.registry)
                except Exception:
                    pass
        fleet = self._fleet
        if fleet is None and not self._fleet_resolved:
            # Multi-host runs get fleet straggler/goodput aggregation for
            # free; single-host runs never build the aggregator (tests
            # install one explicitly via install_fleet_aggregator).
            self._fleet_resolved = True
            try:
                import jax

                if jax.process_count() > 1:
                    from .goodput import FleetAggregator

                    fleet = self._fleet = FleetAggregator()
            except Exception:
                pass
        if fleet is not None and dt is not None:
            try:
                fleet.on_step(dt * 1e3, telemetry=self)
            except Exception:
                pass
        self.heartbeat()

    def install_fleet_aggregator(self, aggregator) -> None:
        """Install (or replace) the fleet aggregator ``record_step`` drives —
        the explicit entry point for custom cadence/gather wiring and tests."""
        self._fleet = aggregator
        self._fleet_resolved = True


_TELEMETRY = Telemetry()


def get_telemetry() -> Telemetry:
    return _TELEMETRY


def enabled() -> bool:
    return _TELEMETRY.enabled


def enable(dir: Optional[str] = None, stall_timeout_s: Optional[float] = None) -> Telemetry:
    return _TELEMETRY.enable(dir=dir, stall_timeout_s=stall_timeout_s)


def disable():
    _TELEMETRY.disable()


def maybe_enable_from_env() -> bool:
    """Enable iff ``$ACCELERATE_TPU_TELEMETRY`` is truthy (the Accelerator
    constructor calls this so env-only runs need no code changes).  Also
    honors ``$ACCELERATE_TPU_FLIGHTREC`` for the flight recorder (which
    enables telemetry as a side effect — the recorder feeds off its hooks)."""
    if not _TELEMETRY.enabled and _env_flag(ENV_ENABLE):
        _TELEMETRY.enable()
    from .flightrec import maybe_enable_from_env as _flightrec_from_env

    _flightrec_from_env()
    return _TELEMETRY.enabled


# ---------------------------------------------------------------------------
# Compile-event listener (module-level: jax.monitoring has no per-listener
# unregister, so exactly ONE is ever installed and it forwards to the
# singleton only while telemetry is enabled).
# ---------------------------------------------------------------------------

_compile_listener_installed = False


def _install_compile_listener():
    global _compile_listener_installed
    if _compile_listener_installed:
        return
    _compile_listener_installed = True
    from jax import monitoring

    def _on_duration(event, duration, **kwargs):
        tel = _TELEMETRY
        if not tel.enabled or event != COMPILE_EVENT:
            return
        dur_ms = duration * 1e3
        tel.registry.counter("jit.compiles").inc()
        tel.registry.histogram("jit.compile_ms").observe(dur_ms)
        tel.write({"kind": "compile", "dur_ms": round(dur_ms, 3)})
        rec = get_flight_recorder()
        if rec.enabled:
            # A mid-training compile is both a recorder-worthy event and a
            # recompile smell the postmortem should surface.
            rec.record("compile", dur_ms=round(dur_ms, 3))

    monitoring.register_event_duration_secs_listener(_on_duration)

    # Persistent-compilation-cache hits (pipeline/compile_cache.py): jax
    # records one event per executable loaded from the cache instead of
    # compiled.  Every backend compile (counted above) is by definition a
    # cache MISS, so jit.cache_hits/jit.compiles together are the cache's
    # hit/miss ledger.
    def _on_event(event, **kwargs):
        tel = _TELEMETRY
        if not tel.enabled or event != CACHE_HIT_EVENT:
            return
        tel.registry.counter("jit.cache_hits").inc()

    monitoring.register_event_listener(_on_event)
