"""Metrics export: a Prometheus text-exposition endpoint + atomic snapshots.

Everything the registry knows — counters, gauges, histograms, the
``goodput.*`` ledger gauges and the serving SLO burn rates — published in
the Prometheus text exposition format (version 0.0.4), two ways:

- **scrape endpoint** — a stdlib ``http.server`` on a background daemon
  thread serving ``GET /metrics`` (``ACCELERATE_TPU_METRICS_PORT=<port>``;
  ``0`` binds an ephemeral port, useful for tests).  Binds 127.0.0.1 only —
  exposing a trainer's metrics beyond the host is a proxy's job, not ours.
- **atomic file snapshot** — for scrape-less environments (batch jobs,
  airgapped pods with a sidecar that ships files):
  ``ACCELERATE_TPU_METRICS_SNAPSHOT=<path>`` rewrites the exposition text
  every ``ACCELERATE_TPU_METRICS_SNAPSHOT_EVERY_S`` seconds (default 15)
  via the flight recorder's write-temp + ``os.replace`` pattern, so a
  SIGTERM mid-write can never leave a torn file — the last complete
  snapshot survives.

Default-off: with neither env var set, ``maybe_start_from_env`` does
nothing.  The exporter starts when telemetry enables and stops (with one
final snapshot) when it disables.

Besides ``/metrics`` the endpoint serves:

- ``GET /healthz`` — liveness probe (``200 ok``), so an orchestrator can
  distinguish "exporter up" from "exporter gone" without paying for a full
  registry render;
- ``GET /debug/requests`` / ``GET /debug/blocks`` — live serving-engine
  introspection (JSON): in-flight request states with phase-so-far trace
  decomposition, and block-pool occupancy / refcounts / prefix-cache
  chains.  Engines self-register via :func:`register_debug_source`
  (weakly — a collected engine drops off the page); with no live engine
  the endpoints return an empty payload, not an error.
- ``GET /debug/memory`` — the process-wide HBM ledger
  (``telemetry/memledger.py``): ranked owner reservations and per-device
  conservation records (attributed + program + unattributed ==
  bytes_in_use), reconciled at request time.

Everything else still 404s.

Naming: registry names are dotted (``serving.ttft_ms``); Prometheus names
are ``accelerate_tpu_`` + the dotted name with ``.`` → ``_``
(``accelerate_tpu_serving_ttft_ms``).  Counters get the ``_total`` suffix;
histograms render exact ``_bucket``/``_sum``/``_count`` triplets from
:class:`~accelerate_tpu.telemetry.metrics.Histogram`'s native bucket counts.

Serving SLO burn rate: the fraction of the TTFT / inter-token error budget
currently being consumed, computed from the existing serving histograms'
recent window — ``burn = violation_rate / (1 - availability)``.  Burn 1.0
means latencies violate the target at exactly the budgeted rate; >1 burns
budget faster than the SLO allows.  Targets via ``ACCELERATE_TPU_SLO_TTFT_MS``
(default 500), ``ACCELERATE_TPU_SLO_INTER_TOKEN_MS`` (50), and
``ACCELERATE_TPU_SLO_AVAILABILITY`` (0.99).  Published as
``serving.slo.ttft_burn_rate`` / ``serving.slo.inter_token_burn_rate``
gauges, so the report and the snapshot carry them too.
"""

from __future__ import annotations

import json
import math
import os
import threading
import weakref
from typing import List, Optional

from .metrics import Counter, Gauge, Histogram, MetricsRegistry

__all__ = [
    "MetricsExporter",
    "render_prometheus",
    "sanitize_metric_name",
    "escape_label_value",
    "publish_slo_burn_rates",
    "get_exporter",
    "maybe_start_from_env",
    "stop_if_running",
    "register_debug_source",
    "debug_payload",
    "ENV_PORT",
    "ENV_SNAPSHOT",
    "ENV_SNAPSHOT_EVERY",
    "ENV_SLO_TTFT_MS",
    "ENV_SLO_INTER_TOKEN_MS",
    "ENV_SLO_AVAILABILITY",
]

ENV_PORT = "ACCELERATE_TPU_METRICS_PORT"
ENV_SNAPSHOT = "ACCELERATE_TPU_METRICS_SNAPSHOT"
ENV_SNAPSHOT_EVERY = "ACCELERATE_TPU_METRICS_SNAPSHOT_EVERY_S"
ENV_SLO_TTFT_MS = "ACCELERATE_TPU_SLO_TTFT_MS"
ENV_SLO_INTER_TOKEN_MS = "ACCELERATE_TPU_SLO_INTER_TOKEN_MS"
ENV_SLO_AVAILABILITY = "ACCELERATE_TPU_SLO_AVAILABILITY"

PREFIX = "accelerate_tpu_"

_OFF = {"0", "false", "no", "off"}


def _fsync_enabled() -> bool:
    return os.environ.get("ACCELERATE_TPU_CHECKPOINT_FSYNC", "1").strip().lower() not in _OFF


def _env_float(key: str, default: float) -> float:
    try:
        return float(os.environ.get(key, "") or default)
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# Live /debug sources (serving engines self-register, weakly)
# ---------------------------------------------------------------------------

_DEBUG_SOURCES: List["weakref.ref"] = []


def register_debug_source(engine) -> None:
    """Register an object exposing ``debug_requests()`` / ``debug_blocks()``
    for the ``/debug/*`` endpoints.  Held weakly: a garbage-collected engine
    silently drops out, so registration never extends an engine's life."""
    _DEBUG_SOURCES.append(weakref.ref(engine))


def _live_debug_sources() -> list:
    alive = []
    for ref in list(_DEBUG_SOURCES):
        obj = ref()
        if obj is None:
            _DEBUG_SOURCES.remove(ref)
        else:
            alive.append(obj)
    return alive


def debug_payload(kind: str) -> dict:
    """The JSON body for ``/debug/requests``, ``/debug/blocks`` or
    ``/debug/memory``.  The first two return one entry per live registered
    engine (keyed by position — multiple engines in one process are rare but
    legal); ``memory`` returns the process-wide :mod:`memledger` snapshot —
    ranked owners plus per-device conservation records — refreshed at
    request time so the residual is current, not last-step stale."""
    if kind == "memory":
        from .memledger import get_memory_ledger

        ledger = get_memory_ledger()
        try:
            ledger.reconcile()
        except Exception:
            pass
        return ledger.snapshot()
    method = {"requests": "debug_requests", "blocks": "debug_blocks"}[kind]
    engines = []
    for obj in _live_debug_sources():
        try:
            engines.append(getattr(obj, method)())
        except Exception as e:  # a torn snapshot must not kill the scrape
            engines.append({"error": str(e)[:200]})
    return {"engines": engines}


# ---------------------------------------------------------------------------
# Text exposition rendering
# ---------------------------------------------------------------------------


def sanitize_metric_name(name: str) -> str:
    """Dotted registry name → valid Prometheus metric name (prefixed)."""
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    sanitized = "".join(out)
    if sanitized and sanitized[0].isdigit():
        sanitized = "_" + sanitized
    return PREFIX + sanitized


def escape_label_value(value) -> str:
    """Escape a label value per the exposition spec: backslash, double
    quote, and newline."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(value: float) -> str:
    value = float(value)
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if math.isnan(value):
        return "NaN"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(value)


def render_prometheus(registry: MetricsRegistry) -> str:
    """The whole registry as exposition text (ends with a newline)."""
    with registry._lock:
        metrics = sorted(registry._metrics.values(), key=lambda m: m.name)
    lines = []
    for metric in metrics:
        pname = sanitize_metric_name(metric.name)
        if isinstance(metric, Counter):
            lines.append(f"# HELP {pname}_total registry counter {metric.name}")
            lines.append(f"# TYPE {pname}_total counter")
            lines.append(f"{pname}_total {_fmt(metric.value)}")
        elif isinstance(metric, Gauge):
            if metric.value is None:
                continue
            lines.append(f"# HELP {pname} registry gauge {metric.name}")
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_fmt(metric.value)}")
        elif isinstance(metric, Histogram):
            lines.append(f"# HELP {pname} registry histogram {metric.name}")
            lines.append(f"# TYPE {pname} histogram")
            # One consistent snapshot per histogram: a concurrent observe()
            # between two reads would otherwise emit +Inf != _count, breaking
            # the exposition invariant downstream quantile math relies on.
            buckets = list(metric.bucket_counts)
            count = metric.count
            total = metric.total
            cumulative = 0
            for bound, n in zip(metric.BOUNDS, buckets):
                cumulative += n
                le = escape_label_value(_fmt(bound))
                lines.append(f'{pname}_bucket{{le="{le}"}} {min(cumulative, count)}')
            lines.append(f'{pname}_bucket{{le="+Inf"}} {count}')
            lines.append(f"{pname}_sum {_fmt(total)}")
            lines.append(f"{pname}_count {count}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Serving SLO burn rate
# ---------------------------------------------------------------------------


def publish_slo_burn_rates(registry: MetricsRegistry) -> dict:
    """Compute the serving SLO burn rates from the existing latency
    histograms and land them as gauges.  No serving traffic → no gauges
    (the registry stays clean for pure-training runs)."""
    availability = min(max(_env_float(ENV_SLO_AVAILABILITY, 0.99), 0.0), 1.0 - 1e-9)
    budget = 1.0 - availability
    out = {}
    for stem, env_key, default_target in (
        ("serving.ttft_ms", ENV_SLO_TTFT_MS, 500.0),
        ("serving.inter_token_ms", ENV_SLO_INTER_TOKEN_MS, 50.0),
    ):
        hist = registry.peek(stem)
        if not isinstance(hist, Histogram):
            continue
        target = _env_float(env_key, default_target)
        violation = hist.over_threshold_fraction(target)
        if violation is None:
            continue
        burn = violation / budget
        short = stem.split(".", 1)[1].replace("_ms", "")
        registry.gauge(f"serving.slo.{short}_target_ms").set(target)
        registry.gauge(f"serving.slo.{short}_burn_rate").set(burn)
        out[f"serving.slo.{short}_burn_rate"] = burn
    return out


# ---------------------------------------------------------------------------
# The exporter: endpoint + snapshot writer
# ---------------------------------------------------------------------------


class MetricsExporter:
    """Background scrape endpoint and/or periodic atomic file snapshot over
    the live telemetry registry."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry
        self._server = None
        self._server_thread = None
        self._snapshot_path: Optional[str] = None
        self._snapshot_thread = None
        self._stop_event = threading.Event()
        self.port: Optional[int] = None
        self.running = False

    def registry(self) -> MetricsRegistry:
        if self._registry is not None:
            return self._registry
        from . import core

        return core.get_telemetry().registry

    def render(self) -> str:
        """One scrape: refresh the derived gauges (goodput ledger, SLO burn
        rates), then render the registry."""
        from . import core

        registry = self.registry()
        ledger = core.get_telemetry().goodput
        if ledger is not None:
            try:
                ledger.publish(registry)
            except Exception:
                pass
        try:
            publish_slo_burn_rates(registry)
        except Exception:
            pass
        from .memledger import get_memory_ledger

        ledger = get_memory_ledger()
        if ledger.has_owners():
            # Scrape-fresh memory.* family: the conservation residual and
            # per-owner gauges update here (like the goodput ledger), not
            # only on record_step — serving-only processes never step.
            try:
                ledger.reconcile_and_publish(registry)
            except Exception:
                pass
        return render_prometheus(registry)

    # -- endpoint ------------------------------------------------------------

    def _start_server(self, port: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, body: bytes, content_type: str):
                self.send_response(200)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    # Liveness, not readiness: answering at all is the signal,
                    # so no registry render on the probe path.
                    self._reply(b"ok\n", "text/plain; charset=utf-8")
                    return
                if path in ("/debug/requests", "/debug/blocks", "/debug/memory"):
                    try:
                        body = json.dumps(
                            debug_payload(path.rsplit("/", 1)[1])
                        ).encode()
                    except Exception as e:
                        self.send_error(500, str(e)[:100])
                        return
                    self._reply(body, "application/json; charset=utf-8")
                    return
                if path != "/metrics":
                    self.send_error(404)
                    return
                try:
                    body = exporter.render().encode()
                except Exception as e:  # a scrape must never crash the server
                    self.send_error(500, str(e)[:100])
                    return
                self._reply(
                    body, "text/plain; version=0.0.4; charset=utf-8"
                )

            def log_message(self, *args):  # silence per-scrape stderr spam
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._server_thread = threading.Thread(
            target=self._server.serve_forever,
            name="atpu-metrics-endpoint",
            daemon=True,
        )
        self._server_thread.start()

    # -- snapshot ------------------------------------------------------------

    def write_snapshot(self) -> Optional[str]:
        """Write the exposition text atomically (temp + ``os.replace``, the
        flight-recorder pattern): a kill mid-write leaves the previous
        complete snapshot, never a torn one."""
        path = self._snapshot_path
        if not path:
            return None
        tmp = f"{path}.tmp"
        try:
            body = self.render()
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            with open(tmp, "w") as f:
                f.write(body)
                f.flush()
                if _fsync_enabled():
                    try:
                        os.fsync(f.fileno())
                    except OSError:
                        pass
            os.replace(tmp, path)
            return path
        except OSError:
            return None

    def _snapshot_loop(self, every_s: float):
        while not self._stop_event.wait(every_s):
            self.write_snapshot()

    # -- lifecycle -----------------------------------------------------------

    def start(
        self,
        port: Optional[int] = None,
        snapshot_path: Optional[str] = None,
        snapshot_every_s: float = 15.0,
    ) -> "MetricsExporter":
        """Start whichever halves were configured (idempotent)."""
        if self.running:
            return self
        self._stop_event.clear()
        if port is not None:
            self._start_server(int(port))
        if snapshot_path:
            self._snapshot_path = snapshot_path
            self.write_snapshot()
            self._snapshot_thread = threading.Thread(
                target=self._snapshot_loop,
                args=(max(0.1, float(snapshot_every_s)),),
                name="atpu-metrics-snapshot",
                daemon=True,
            )
            self._snapshot_thread.start()
        self.running = True
        return self

    def stop(self, final_snapshot: bool = True):
        """Shut both halves down; by default writes one last snapshot so the
        file on disk reflects the final registry state."""
        if not self.running:
            return
        self.running = False
        self._stop_event.set()
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except Exception:
                pass
            self._server = None
            self._server_thread = None
        if self._snapshot_thread is not None:
            self._snapshot_thread.join(timeout=5.0)
            self._snapshot_thread = None
        if final_snapshot:
            self.write_snapshot()


_EXPORTER: Optional[MetricsExporter] = None


def get_exporter() -> Optional[MetricsExporter]:
    return _EXPORTER


def maybe_start_from_env() -> Optional[MetricsExporter]:
    """Start the exporter iff the env asks for it (called from
    ``Telemetry.enable``).  Disabled by default: no port, no snapshot path →
    nothing starts, nothing listens."""
    global _EXPORTER
    if _EXPORTER is not None and _EXPORTER.running:
        return _EXPORTER
    port_raw = os.environ.get(ENV_PORT, "").strip()
    snapshot = os.environ.get(ENV_SNAPSHOT, "").strip() or None
    port: Optional[int] = None
    if port_raw:
        try:
            port = int(port_raw)
        except ValueError:
            port = None
        if port is not None and port < 0:
            port = None
    if port is None and not snapshot:
        return None
    exporter = _EXPORTER or MetricsExporter()
    _EXPORTER = exporter
    exporter.start(
        port=port,
        snapshot_path=snapshot,
        snapshot_every_s=_env_float(ENV_SNAPSHOT_EVERY, 15.0),
    )
    return exporter


def stop_if_running():
    """Stop the env-started exporter (called from ``Telemetry.disable``);
    writes the final snapshot while the registry still holds the run."""
    if _EXPORTER is not None:
        _EXPORTER.stop(final_snapshot=True)
