"""Trace-driven performance attribution: the profile scanner.

``hlo_scan``/``introspect`` answer *static* questions about a compiled
program (how many collective bytes, on which mesh axis); this module answers
the *runtime* one: did those collectives actually hide behind compute, or did
the step pay for them?  It consumes a ``jax.profiler`` trace directory (the
sentinel's anomaly capture, ``Accelerator.profile``, ``bench.py``'s probe, or
any TensorBoard profile dump) and computes, by interval arithmetic over the
reconstructed device timeline:

- **device-busy ms** — union of device-op time per device scope;
- **exposed-collective ms** — collective time NOT covered by concurrent
  compute (``collective-union − compute-union`` per scope): the part of the
  comms bill the step actually paid;
- **realized overlap fraction** — ``1 − exposed/collective``;
- **top-k ops by self time** and a per-step waterfall
  (compute / hidden comms / exposed comms / infeed / idle).

Entry points: :func:`analyze_trace_dir` (offline or post-capture),
:func:`publish` (metrics registry + telemetry JSONL), :func:`digest` (the
compact dict the flight recorder attaches to anomaly postmortems), and
``python -m accelerate_tpu.telemetry.profile_scan <dir>`` for the CLI.
``telemetry.report --profile <dir>`` renders the same report.

No ``jax`` import anywhere on the analysis path: the parser that audits a
live TPU capture also runs on a committed fixture with no devices at all.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from dataclasses import asdict, dataclass, field
from typing import Optional

from .timeline import (
    COLLECTIVE,
    COMPUTE,
    INFEED,
    Timeline,
    TraceParseError,
    build_timeline,
    classify_op,
    clip_intervals,
    find_trace_files,
    intervals_total,
    load_trace_events,
    merge_intervals,
    subtract_intervals,
)

__all__ = [
    "ProfileReport",
    "analyze_trace_dir",
    "analyze_trace_file",
    "analyze_events",
    "report_from_dict",
    "publish",
    "digest",
    "format_profile_report",
    "main",
]

TOP_K_OPS = 5


@dataclass
class ProfileReport:
    """Headline attribution metrics for one captured trace window."""

    source: Optional[str] = None
    n_raw_events: int = 0
    n_device_events: int = 0
    n_device_lanes: int = 0
    n_scopes: int = 0
    window_ms: float = 0.0
    device_busy_ms: float = 0.0
    compute_ms: float = 0.0
    collective_ms: float = 0.0
    infeed_ms: float = 0.0
    exposed_collective_ms: float = 0.0
    # None when the window holds no collectives (single-device program).
    overlap_fraction: Optional[float] = None
    idle_ms: float = 0.0
    # Idle-gap share of the whole capture window across device scopes
    # (idle_ms / (window_ms x n_scopes)) — the realized pipeline-bubble
    # measurement the pp probes compare against the analytic
    # (S-1)/(v·M+S-1).  None until device events exist.
    bubble_fraction: Optional[float] = None
    step_marker: Optional[str] = None
    steps: list = field(default_factory=list)
    top_ops: list = field(default_factory=list)

    def to_dict(self) -> dict:
        return asdict(self)

    def step_bubble_fraction(self, skip_first: bool = True) -> Optional[float]:
        """Mean idle-gap share of the per-step windows (the realized bubble of
        the steady-state step).  ``skip_first`` drops step 0 when more than
        one step exists — its window absorbs warmup/compile idle that is not
        schedule bubble.  Each step row carries the scope count of the host
        it was built from (``n_scopes`` in the row) — on a merged multi-host
        report the report-level ``n_scopes`` sums ALL hosts while the step
        rows cover one, so the row value is the correct denominator."""
        steps = self.steps
        if skip_first and len(steps) > 1:
            steps = steps[1:]
        fracs = [
            s["idle_ms"] / (s["dur_ms"] * max(s.get("n_scopes") or self.n_scopes, 1))
            for s in steps
            if s.get("dur_ms")
        ]
        if not fracs:
            return None
        return round(sum(fracs) / len(fracs), 4)


# ---------------------------------------------------------------------------
# Self time
# ---------------------------------------------------------------------------


def _self_times(lane_events: list) -> list:
    """Per-event self time (dur minus direct children) for one (pid, tid)
    lane.  Trace events on a lane nest but never partially overlap, so a
    stack sweep in ts order reconstructs the tree."""
    order = sorted(lane_events, key=lambda e: (e.ts, -e.dur))
    stack: list = []  # [event, child_dur_accum]
    out = []

    def _finalize(entry):
        ev, child_dur = entry
        out.append((ev, max(0.0, ev.dur - child_dur)))

    for ev in order:
        while stack and stack[-1][0].end <= ev.ts + 1e-9:
            _finalize(stack.pop())
        if stack:
            stack[-1][1] += ev.dur
        stack.append([ev, 0.0])
    while stack:
        _finalize(stack.pop())
    return out


# ---------------------------------------------------------------------------
# Step segmentation
# ---------------------------------------------------------------------------


def _step_windows(tl: Timeline, step_marker_re: Optional[str] = None):
    """Per-step windows from host-side dispatch markers.

    The fused train step is one ``jax.jit`` dispatch per optimizer step, so
    its ``PjitFunction(<name>)`` host events are natural step boundaries.
    Among candidate marker names, the one whose windows cover the most wall
    time wins — a run's hot loop dominates its trace, while tiny helper
    dispatches (``device_put`` conversions and the like) may outnumber it but
    never outlast it.  Nested duplicates of the same marker (the profiler
    emits one per wrapper layer) collapse to the outermost.  Returns
    ``(marker_name, [(start, end), ...])`` — empty when no markers exist
    (the caller falls back to one whole-window step)."""
    import re as _re

    candidates: dict = {}
    match = _re.compile(step_marker_re) if step_marker_re else None
    for ev in tl.host_events:
        if match is not None:
            if not match.search(ev.name):
                continue
        elif not ev.name.startswith("PjitFunction("):
            continue
        candidates.setdefault(ev.name, []).append(ev)
    if not candidates:
        return None, []

    def _dedup(events: list) -> list:
        windows = []
        for ev in sorted(events, key=lambda e: (e.ts, -e.dur)):
            # Outermost wins: drop a marker fully inside the previous window.
            if windows and ev.ts >= windows[-1][0] and ev.end <= windows[-1][1] + 1e-9:
                continue
            windows.append((ev.ts, ev.end))
        return windows

    deduped = {name: _dedup(events) for name, events in candidates.items()}
    name = max(deduped, key=lambda n: sum(e - s for s, e in deduped[n]))
    return name, deduped[name]


# ---------------------------------------------------------------------------
# Analysis
# ---------------------------------------------------------------------------


def analyze_events(
    raw_events: list,
    source: Optional[str] = None,
    top_k: int = TOP_K_OPS,
    step_marker_re: Optional[str] = None,
    assume_no_overlap: bool = False,
) -> ProfileReport:
    """Classify + bucket one trace's events into a :class:`ProfileReport`.

    ``assume_no_overlap=True`` disables the concurrent-compute credit (every
    collective µs counts as exposed) — the perf gate's ``no-overlap`` degrade
    knob uses it to prove the overlap row actually judges the number."""
    tl = build_timeline(raw_events, source=source)
    report = ProfileReport(
        source=source,
        n_raw_events=tl.n_raw_events,
        n_device_events=len(tl.events),
        n_device_lanes=len(tl.lanes()),
    )
    if not tl.events:
        return report

    # Per-scope interval unions (scope = device pid on TPU, the whole
    # process on CPU — see Timeline.device_scopes).
    scopes = tl.device_scopes()
    report.n_scopes = len(scopes)
    t0 = min(ev.ts for ev in tl.events)
    t1 = max(ev.end for ev in tl.events)
    report.window_ms = round((t1 - t0) / 1e3, 3)
    per_scope = {}
    for pid, events in scopes.items():
        buckets: dict = {COMPUTE: [], COLLECTIVE: [], INFEED: []}
        for ev in events:
            buckets[classify_op(ev.hlo_op or ev.name)].append((ev.ts, ev.end))
        comp = merge_intervals(buckets[COMPUTE])
        coll = merge_intervals(buckets[COLLECTIVE])
        infeed = merge_intervals(buckets[INFEED])
        busy = merge_intervals(buckets[COMPUTE] + buckets[COLLECTIVE] + buckets[INFEED])
        exposed = coll if assume_no_overlap else subtract_intervals(coll, comp)
        per_scope[pid] = (comp, coll, infeed, busy, exposed)
        report.compute_ms += intervals_total(comp)
        report.collective_ms += intervals_total(coll)
        report.infeed_ms += intervals_total(infeed)
        report.device_busy_ms += intervals_total(busy)
        report.exposed_collective_ms += intervals_total(exposed)
        report.idle_ms += max(0.0, (t1 - t0) - intervals_total(busy))
    for key in (
        "compute_ms",
        "collective_ms",
        "infeed_ms",
        "device_busy_ms",
        "exposed_collective_ms",
        "idle_ms",
    ):
        setattr(report, key, round(getattr(report, key) / 1e3, 3))
    if report.collective_ms > 0:
        report.overlap_fraction = round(
            1.0 - report.exposed_collective_ms / report.collective_ms, 4
        )
    if report.window_ms > 0 and report.n_scopes:
        report.bubble_fraction = round(
            report.idle_ms / (report.window_ms * report.n_scopes), 4
        )

    # Top-k ops by self time (summed across lanes; uniquifier suffixes like
    # ``.3`` are kept — distinct HLO instructions are distinct rows).
    agg: dict = {}
    for lane_events in tl.lanes().values():
        for ev, self_us in _self_times(lane_events):
            name = ev.hlo_op or ev.name
            row = agg.setdefault(name, {"name": name, "bucket": classify_op(name), "count": 0, "self_ms": 0.0})
            row["count"] += 1
            row["self_ms"] += self_us
    top = sorted(agg.values(), key=lambda r: -r["self_ms"])[: max(0, top_k)]
    for row in top:
        row["self_ms"] = round(row["self_ms"] / 1e3, 3)
    report.top_ops = top

    # Per-step attribution from host dispatch markers (whole window as one
    # synthetic step when none exist — e.g. a trace of eager dispatches).
    marker, windows = _step_windows(tl, step_marker_re)
    report.step_marker = marker
    if not windows:
        windows = [(t0, t1)]
    else:
        # Device execution is async: the host dispatch returns long before
        # the device drains the step's ops.  Everything between one dispatch
        # and the next belongs to the earlier step, so each window extends to
        # the next marker's start (the last one to the end of device work).
        extended = []
        for i, (ws, we) in enumerate(windows):
            next_start = windows[i + 1][0] if i + 1 < len(windows) else max(t1, we)
            extended.append((ws, max(we, next_start)))
        windows = extended
    for index, (ws, we) in enumerate(windows):
        step = {
            "index": index,
            "n_scopes": report.n_scopes,
            "start_ms": round((ws - t0) / 1e3, 3),
            "dur_ms": round((we - ws) / 1e3, 3),
            "compute_ms": 0.0,
            "collective_ms": 0.0,
            "exposed_collective_ms": 0.0,
            "infeed_ms": 0.0,
            "busy_ms": 0.0,
            "idle_ms": 0.0,
            "overlap_fraction": None,
        }
        for comp, coll, infeed, busy, exposed in per_scope.values():
            step["compute_ms"] += intervals_total(clip_intervals(comp, ws, we))
            step["collective_ms"] += intervals_total(clip_intervals(coll, ws, we))
            step["exposed_collective_ms"] += intervals_total(clip_intervals(exposed, ws, we))
            step["infeed_ms"] += intervals_total(clip_intervals(infeed, ws, we))
            busy_us = intervals_total(clip_intervals(busy, ws, we))
            step["busy_ms"] += busy_us
            step["idle_ms"] += max(0.0, (we - ws) - busy_us)
        for key in (
            "compute_ms",
            "collective_ms",
            "exposed_collective_ms",
            "infeed_ms",
            "busy_ms",
            "idle_ms",
        ):
            step[key] = round(step[key] / 1e3, 3)
        if step["collective_ms"] > 0:
            step["overlap_fraction"] = round(
                1.0 - step["exposed_collective_ms"] / step["collective_ms"], 4
            )
        report.steps.append(step)
    return report


def report_from_dict(data: dict) -> ProfileReport:
    """Rebuild a :class:`ProfileReport` from its ``to_dict`` form (a
    ``profile`` telemetry record); unknown keys are ignored."""
    import dataclasses

    names = {f.name for f in dataclasses.fields(ProfileReport)}
    return ProfileReport(**{k: v for k, v in data.items() if k in names})


def analyze_trace_file(path: str, **kwargs) -> ProfileReport:
    """Analyze one ``*.trace.json[.gz]`` file."""
    return analyze_events(load_trace_events(path), source=path, **kwargs)


def analyze_trace_dir(path: str, **kwargs) -> ProfileReport:
    """Analyze a profiler output directory (or a single trace file).

    Multiple files in one run directory (one per host) are analyzed
    independently and summed — their clocks are per-host, so cross-host
    interval unions would be meaningless.  Raises :class:`TraceParseError`
    when no trace file exists or none parses."""
    files = find_trace_files(path)
    if not files:
        raise TraceParseError(f"no *.trace.json[.gz] under {path}")
    reports = []
    errors = []
    for file in files:
        try:
            reports.append(analyze_trace_file(file, **kwargs))
        except TraceParseError as e:
            errors.append(str(e))
    if not reports:
        raise TraceParseError("; ".join(errors))
    if len(reports) == 1:
        report = reports[0]
        report.source = path
        return report
    merged = ProfileReport(source=path)
    for rep in reports:
        merged.n_raw_events += rep.n_raw_events
        merged.n_device_events += rep.n_device_events
        merged.n_device_lanes += rep.n_device_lanes
        merged.n_scopes += rep.n_scopes
        merged.window_ms += rep.window_ms
        merged.device_busy_ms += rep.device_busy_ms
        merged.compute_ms += rep.compute_ms
        merged.collective_ms += rep.collective_ms
        merged.infeed_ms += rep.infeed_ms
        merged.exposed_collective_ms += rep.exposed_collective_ms
        merged.idle_ms += rep.idle_ms
    for key in (
        "window_ms", "device_busy_ms", "compute_ms", "collective_ms",
        "infeed_ms", "exposed_collective_ms", "idle_ms",
    ):
        setattr(merged, key, round(getattr(merged, key), 3))
    if merged.collective_ms > 0:
        merged.overlap_fraction = round(
            1.0 - merged.exposed_collective_ms / merged.collective_ms, 4
        )
    # Idle share over the summed per-host device capacity (windows are
    # per-host clocks, so capacity is the sum of window x scopes terms).
    capacity = sum(r.window_ms * r.n_scopes for r in reports)
    if capacity > 0:
        merged.bubble_fraction = round(merged.idle_ms / capacity, 4)
    host_with_steps = max(reports, key=lambda r: len(r.steps))
    merged.steps = host_with_steps.steps
    merged.step_marker = host_with_steps.step_marker
    agg: dict = {}
    for rep in reports:
        for row in rep.top_ops:
            cur = agg.setdefault(row["name"], dict(row))
            if cur is not row:
                cur["count"] += row["count"]
                cur["self_ms"] = round(cur["self_ms"] + row["self_ms"], 3)
    merged.top_ops = sorted(agg.values(), key=lambda r: -r["self_ms"])[:TOP_K_OPS]
    return merged


# ---------------------------------------------------------------------------
# Publication
# ---------------------------------------------------------------------------


def publish(report: ProfileReport, telemetry=None) -> None:
    """Publish the headline numbers into the metrics registry and the
    telemetry JSONL (kind ``profile``) so ``telemetry.report`` renders them."""
    if telemetry is None:
        from . import core

        telemetry = core.get_telemetry()
    if not telemetry.enabled:
        return
    reg = telemetry.registry
    reg.gauge("profile.device_busy_ms").set(report.device_busy_ms)
    reg.gauge("profile.collective_ms").set(report.collective_ms)
    reg.gauge("profile.exposed_collective_ms").set(report.exposed_collective_ms)
    if report.overlap_fraction is not None:
        reg.gauge("profile.overlap_fraction").set(report.overlap_fraction)
    telemetry.write({"kind": "profile", **report.to_dict()})


def digest(report: ProfileReport, top_k: int = 3) -> dict:
    """Compact attribution summary (the flight-recorder postmortem payload)."""
    return {
        "window_ms": report.window_ms,
        "device_busy_ms": report.device_busy_ms,
        "compute_ms": report.compute_ms,
        "collective_ms": report.collective_ms,
        "exposed_collective_ms": report.exposed_collective_ms,
        "overlap_fraction": report.overlap_fraction,
        "idle_ms": report.idle_ms,
        "bubble_fraction": report.bubble_fraction,
        "n_steps": len(report.steps),
        "top_ops": [
            {"name": r["name"], "bucket": r["bucket"], "self_ms": r["self_ms"]}
            for r in report.top_ops[:top_k]
        ],
    }


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def format_profile_report(report: ProfileReport, max_steps: int = 8) -> str:
    """Human rendering: headline, waterfall, top ops, per-step table."""
    lines = []
    lines.append(
        f"profile scan — {report.source or '?'}: "
        f"{report.n_device_events} device ops on {report.n_device_lanes} lanes "
        f"({report.n_scopes} device scope{'s' if report.n_scopes != 1 else ''}, "
        f"window {report.window_ms} ms)"
    )
    if not report.n_device_events:
        lines.append("  no device ops in trace (nothing executed during the window)")
        return "\n".join(lines)
    overlap = (
        f"{100.0 * report.overlap_fraction:.1f}%"
        if report.overlap_fraction is not None
        else "n/a (no collectives)"
    )
    lines.append(
        f"  device busy {report.device_busy_ms} ms | compute {report.compute_ms} ms | "
        f"collective {report.collective_ms} ms (exposed {report.exposed_collective_ms} ms) | "
        f"infeed {report.infeed_ms} ms | idle {report.idle_ms} ms"
    )
    lines.append(f"  realized collective overlap: {overlap}")
    waterfall = [
        ("compute", report.compute_ms),
        ("collective (hidden)", round(report.collective_ms - report.exposed_collective_ms, 3)),
        ("collective (exposed)", report.exposed_collective_ms),
        ("infeed", report.infeed_ms),
        ("idle", report.idle_ms),
    ]
    denom = sum(v for _, v in waterfall) or 1.0
    lines.append("  waterfall:")
    for name, value in waterfall:
        bar = "#" * int(round(24.0 * value / denom))
        lines.append(f"    {name:<22} {value:>10.3f} ms {bar}")
    if report.top_ops:
        lines.append("  top ops by self time:")
        for row in report.top_ops:
            lines.append(
                f"    {row['name']:<32} [{row['bucket']:<10}] x{row['count']:<5} "
                f"{row['self_ms']:>10.3f} ms"
            )
    if report.steps:
        shown = report.steps[:max_steps]
        marker = f" (marker {report.step_marker!r})" if report.step_marker else ""
        lines.append(f"  steps: {len(report.steps)}{marker}")
        lines.append(
            f"    {'step':>5} {'dur_ms':>10} {'compute':>10} {'coll':>10} "
            f"{'exposed':>10} {'overlap':>8}"
        )
        for step in shown:
            ov = (
                f"{100.0 * step['overlap_fraction']:.0f}%"
                if step["overlap_fraction"] is not None
                else "-"
            )
            lines.append(
                f"    {step['index']:>5} {step['dur_ms']:>10.3f} {step['compute_ms']:>10.3f} "
                f"{step['collective_ms']:>10.3f} {step['exposed_collective_ms']:>10.3f} {ov:>8}"
            )
        if len(report.steps) > len(shown):
            lines.append(f"    ... {len(report.steps) - len(shown)} more steps")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m accelerate_tpu.telemetry.profile_scan",
        description=(
            "Attribute a jax.profiler trace capture: compute/collective/"
            "infeed buckets, exposed-collective time, realized overlap."
        ),
    )
    parser.add_argument("path", help="profiler output dir or *.trace.json[.gz] file")
    parser.add_argument("--json", action="store_true", help="emit the report as JSON")
    parser.add_argument(
        "--step-marker",
        default=None,
        metavar="REGEX",
        help="host-event regex for step boundaries (default: PjitFunction markers)",
    )
    args = parser.parse_args(argv)
    if not os.path.exists(args.path):
        print(f"no such file or directory: {args.path}", file=sys.stderr)
        return 1
    try:
        report = analyze_trace_dir(args.path, step_marker_re=args.step_marker)
    except TraceParseError as e:
        print(f"profile scan failed: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(report.to_dict()))
    else:
        print(format_profile_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
