"""Telemetry run summarizer: ``python -m accelerate_tpu.telemetry.report <path>``.

``<path>`` is a telemetry or flight-recorder JSONL file, or a run directory
holding ``telemetry_p*.jsonl`` / ``flightrec_p*.jsonl`` files (one per
process).  Prints a per-span time breakdown, compile statistics, stall
events, the final metrics snapshot, and — when a flight-recorder snapshot is
present — a postmortem block: the last N steps, the anomaly list, the
sentinel's anomaly-capture digest, and the final event before the process
died.

``--profile <dir>`` additionally runs the trace scanner
(``profile_scan.py``) over any ``jax.profiler`` output directory offline and
appends the attribution block.  ``--json`` switches to machine-readable
output (stable ``telemetry``/``postmortem``/``profile`` top-level keys) so
bench/CI consume the same data without screen-scraping; the human renderer
is unchanged.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

__all__ = [
    "load_records",
    "load_flight_records",
    "load_fleet_records",
    "load_serving_trace_records",
    "summarize",
    "summarize_flight",
    "summarize_fleet",
    "format_report",
    "format_flight_report",
    "format_fleet_report",
    "format_memory_block",
    "main",
]


def _parse_jsonl(files: list) -> list[dict]:
    records = []
    for file in files:
        with open(file) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue
    return records


def load_records(path: str) -> list[dict]:
    """Parse every telemetry record from a JSONL file or a run directory.
    Unparseable lines (a crashed writer's torn tail) are skipped, not fatal.
    Flight-recorder snapshots are deliberately excluded — their step/anomaly
    kinds would double-count compiles/stalls; use :func:`load_flight_records`."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "telemetry_p*.jsonl")))
        if not files:
            files = [
                f
                for f in sorted(glob.glob(os.path.join(path, "*.jsonl")))
                if not os.path.basename(f).startswith("flightrec_")
            ]
    else:
        files = [path]
    return _parse_jsonl(files)


def load_flight_records(path: str) -> list[dict]:
    """Parse flight-recorder snapshots: ``flightrec_p*.jsonl`` under a run
    directory, or the given file directly."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "flightrec_p*.jsonl")))
    else:
        files = [path]
    return _parse_jsonl(files)


def load_fleet_records(path: str) -> dict:
    """Every rank's telemetry AND flight-recorder stream under a run
    directory, keyed by process index: ``{proc: [records]}`` with each record
    tagged ``source`` (``telemetry``/``flightrec``).  The raw material for the
    fleet postmortem view (:func:`summarize_fleet`)."""
    import re

    by_proc: dict = {}
    if not os.path.isdir(path):
        return by_proc
    for prefix, source in (("telemetry_p", "telemetry"), ("flightrec_p", "flightrec")):
        for file in sorted(glob.glob(os.path.join(path, f"{prefix}*.jsonl"))):
            match = re.search(r"_p(\d+)\.jsonl$", os.path.basename(file))
            name_proc = int(match.group(1)) if match else 0
            for rec in _parse_jsonl([file]):
                rec = dict(rec)
                rec["source"] = source
                proc = rec.get("proc")
                proc = name_proc if not isinstance(proc, int) else proc
                rec["proc"] = proc
                by_proc.setdefault(proc, []).append(rec)
    for records in by_proc.values():
        records.sort(key=lambda r: (r.get("t") or 0, r.get("seq") or 0))
    return by_proc


def _describe_record(rec: dict) -> str:
    kind = rec.get("kind")
    if kind == "step":
        return f"step {rec.get('step')} ({rec.get('dur_ms')}ms)"
    if kind == "event":
        skip = ("kind", "t", "proc", "seq", "name", "source")
        fields = ", ".join(f"{k}={rec[k]!r}" for k in rec if k not in skip)
        return f"event {rec.get('name')}" + (f" ({fields})" if fields else "")
    if kind == "span":
        return f"span {rec.get('name')} ({rec.get('dur_ms')}ms)"
    return _event_str(rec)


def summarize_fleet(by_proc: dict, timeline_n: int = 40) -> dict:
    """Merge every rank's streams into one rank-tagged postmortem: per-rank
    last-sign-of-life, the rank that went silent FIRST (the usual suspect for
    a dead/wedged member — everyone else's streams end later, wedged in the
    collective the dead rank abandoned), and a merged tail timeline placing
    the dead rank's final events adjacent to the survivors' last barrier."""
    ranks: dict = {}
    merged: list = []
    for proc in sorted(by_proc):
        records = by_proc[proc]
        if not records:
            continue
        last = records[-1]
        steps = [r for r in records if r.get("kind") == "step"]
        ranks[str(proc)] = {
            "n_records": len(records),
            "last_t": last.get("t"),
            "last_event": _describe_record(last),
            "last_step": steps[-1].get("step") if steps else None,
            "crashes": sum(1 for r in records if r.get("kind") == "crash"),
            "signals": sum(1 for r in records if r.get("kind") == "signal"),
        }
        merged.extend(records)
    merged.sort(key=lambda r: (r.get("t") or 0, r.get("seq") or 0))
    end_t = merged[-1].get("t") if merged else None
    first_silent = None
    if len(ranks) >= 2:
        first_silent = min(
            ranks, key=lambda p: (ranks[p]["last_t"] is None, ranks[p]["last_t"] or 0)
        )
    timeline = [
        {
            "t": r.get("t"),
            "behind_s": (
                round(end_t - r["t"], 3)
                if end_t is not None and isinstance(r.get("t"), (int, float))
                else None
            ),
            "proc": r.get("proc"),
            "source": r.get("source"),
            "desc": _describe_record(r),
        }
        for r in merged[-timeline_n:]
    ]
    return {
        "n_ranks": len(ranks),
        "n_records": len(merged),
        "ranks": ranks,
        "first_silent_rank": int(first_silent) if first_silent is not None else None,
        "timeline": timeline,
    }


def format_fleet_report(fsummary: dict, last_n: int = 20) -> str:
    """Render the rank-tagged fleet postmortem block."""
    lines = []
    lines.append(
        f"fleet postmortem — {fsummary['n_ranks']} ranks, "
        f"{fsummary['n_records']} records"
    )
    ranks = fsummary["ranks"]
    if ranks:
        end_t = max(
            (r["last_t"] for r in ranks.values() if r["last_t"] is not None),
            default=None,
        )
        lines.append("")
        lines.append(
            f"  {'rank':>5} {'records':>8} {'last step':>10} {'behind_s':>9}  last sign of life"
        )
        for proc in sorted(ranks, key=int):
            info = ranks[proc]
            behind = (
                f"{end_t - info['last_t']:9.3f}"
                if end_t is not None and info["last_t"] is not None
                else "        -"
            )
            lines.append(
                f"  {proc:>5} {info['n_records']:>8} "
                f"{info['last_step'] if info['last_step'] is not None else '-':>10} "
                f"{behind}  {info['last_event']}"
            )
    if fsummary.get("first_silent_rank") is not None:
        lines.append("")
        lines.append(
            f"first silent: rank {fsummary['first_silent_rank']} "
            "(earliest last record — likely the dead/wedged member)"
        )
    timeline = fsummary["timeline"][-last_n:]
    if timeline:
        lines.append("")
        lines.append(f"merged timeline (last {len(timeline)}):")
        for entry in timeline:
            behind = (
                f"-{entry['behind_s']:.3f}s" if entry["behind_s"] is not None else "?"
            )
            lines.append(
                f"  {behind:>10} p{entry['proc']} [{entry['source']}] {entry['desc']}"
            )
    return "\n".join(lines)


def load_serving_trace_records(path: str) -> list[dict]:
    """Per-request serving trace records (``serving_trace_*.jsonl``) under a
    run directory, or one such file directly.  The loader lives in
    ``serving/tracing.py`` (stdlib-only code, but inside the serving
    package); an unimportable serving package degrades to "no traces"
    rather than killing the rest of the report."""
    if not os.path.isdir(path) and not os.path.basename(path).startswith(
        "serving_trace_"
    ):
        return []
    try:
        from ..serving.tracing import load_serving_traces
    except Exception:
        return []
    return load_serving_traces(path)


def summarize(records: list[dict]) -> dict:
    """Aggregate records into the report's sections."""
    spans: dict = {}
    toplevel_ms = 0.0
    compiles = 0
    compile_ms = 0.0
    stalls = []
    snapshot = None
    introspect = {}
    profiles: dict = {}
    stragglers: dict = {}
    for rec in records:
        kind = rec.get("kind")
        if kind == "event" and rec.get("name") == "sentinel.straggler":
            # Latest verdict per host wins: the fleet aggregator emits an
            # explicit cleared=True event when a previously-named host
            # recovers, so stale verdicts genuinely age out of the report.
            stragglers[rec.get("host")] = rec
        if kind == "span":
            name = rec.get("name", "?")
            agg = spans.setdefault(
                name, {"count": 0, "total_ms": 0.0, "max_ms": 0.0, "depth": rec.get("depth", 0)}
            )
            dur = float(rec.get("dur_ms", 0.0))
            agg["count"] += 1
            agg["total_ms"] += dur
            agg["max_ms"] = max(agg["max_ms"], dur)
            agg["depth"] = min(agg["depth"], rec.get("depth", 0))
            if rec.get("depth", 0) == 0:
                toplevel_ms += dur
        elif kind == "compile":
            compiles += 1
            compile_ms += float(rec.get("dur_ms", 0.0))
        elif kind == "stall":
            stalls.append(
                {"elapsed_s": rec.get("elapsed_s"), "deadline_s": rec.get("deadline_s")}
            )
        elif kind == "metrics":
            snapshot = rec.get("snapshot")  # last one wins (written on disable)
        elif kind == "introspect":
            # Latest capture per program name wins (a recompile re-captures).
            introspect[rec.get("name", "?")] = rec
        elif kind == "profile":
            # Latest scan per trace source wins (a re-armed capture re-scans).
            profiles[rec.get("source") or "?"] = rec
    from .goodput import summary_from_records

    return {
        "spans": spans,
        "toplevel_ms": toplevel_ms,
        "compiles": compiles,
        "compile_ms": compile_ms,
        "stalls": stalls,
        "snapshot": snapshot,
        "introspect": introspect,
        "profiles": profiles,
        # Wall-clock attribution ledger, recomputed offline from the same
        # record stream (so a crashed run that never published its goodput
        # gauges still gets a ledger in the postmortem).
        "goodput": summary_from_records(records),
        "stragglers": [stragglers[h] for h in sorted(stragglers, key=lambda x: (x is None, x))],
        "n_records": len(records),
    }


def summarize_flight(records: list[dict]) -> dict:
    """Aggregate flight-recorder events into the postmortem's sections."""
    steps = []
    anomalies = []
    signals = []
    crashes = []
    compiles = 0
    events = 0
    profile_captures = []
    profile_digests = []
    oom_postmortems = []
    for rec in records:
        kind = rec.get("kind")
        if kind == "step":
            steps.append(rec)
        elif kind == "anomaly":
            anomalies.append(rec)
        elif kind == "signal":
            signals.append(rec)
        elif kind == "crash":
            crashes.append(rec)
        elif kind == "compile":
            compiles += 1
        elif kind == "event":
            events += 1
            name = rec.get("name")
            if name == "sentinel.profile_captured":
                profile_captures.append(rec)
            elif name in ("sentinel.profile_digest", "sentinel.profile_analysis_failed"):
                profile_digests.append(rec)
            elif name == "memory.oom_postmortem":
                oom_postmortems.append(rec)
    final = max(records, key=lambda r: (r.get("t") or 0, r.get("seq") or 0)) if records else None
    return {
        "n_events": len(records),
        "steps": steps,
        "anomalies": anomalies,
        "signals": signals,
        "crashes": crashes,
        "compiles": compiles,
        "events": events,
        "profile_captures": profile_captures,
        "profile_digests": profile_digests,
        # Ranked-ledger snapshots from RESOURCE_EXHAUSTED sites (the HBM
        # ledger's memory.oom_postmortem events) — a stable machine key for
        # --json consumers, rendered as the memory block below.
        "oom_postmortems": oom_postmortems,
        "final_event": final,
    }


def _event_str(rec: dict) -> str:
    skip = ("kind", "t", "proc", "seq")
    fields = ", ".join(f"{k}={rec[k]!r}" for k in rec if k not in skip)
    return f"{rec.get('kind')}" + (f" ({fields})" if fields else "")


def format_flight_report(fsummary: dict, last_n: int = 10) -> str:
    """Render the flight-recorder postmortem block."""
    lines = []
    lines.append(
        f"flight recorder — {fsummary['n_events']} events in snapshot "
        f"({len(fsummary['steps'])} steps, {fsummary['compiles']} compiles, "
        f"{fsummary['events']} markers)"
    )
    steps = fsummary["steps"][-last_n:]
    if steps:
        lines.append("")
        lines.append(f"last {len(steps)} steps:")
        lines.append(f"  {'step':>8} {'dur_ms':>10} {'dispatches':>11} {'host_blk_ms':>12}")
        for s in steps:

            def cell(value):
                return "-" if value is None else value

            lines.append(
                f"  {cell(s.get('step')):>8} "
                f"{cell(s.get('dur_ms')):>10} "
                f"{cell(s.get('dispatches')):>11} "
                f"{cell(s.get('host_blocked_ms')):>12}"
            )
    if fsummary["anomalies"]:
        lines.append("")
        lines.append(f"anomalies: {len(fsummary['anomalies'])}")
        for a in fsummary["anomalies"][-last_n:]:
            detail = {
                k: v for k, v in a.items() if k not in ("kind", "t", "proc", "seq")
            }
            lines.append(f"  - {detail.pop('reason', '?')}: {detail}")
    for pm in (fsummary.get("oom_postmortems") or [])[-last_n:]:
        lines.append("")
        lines.append(
            f"memory postmortem (OOM at {pm.get('source', '?')}): "
            f"blamed owner {pm.get('blame') or 'UNATTRIBUTED'}"
            + (
                f" holding {_human(pm.get('blame_bytes'))}B/chip"
                if pm.get("blame_bytes")
                else ""
            )
        )
        if pm.get("watermark_bytes_in_use") is not None:
            lines.append(
                f"  watermark: {_human(pm.get('watermark_bytes_in_use'))}B in use"
                + (
                    f" (peak {_human(pm.get('watermark_peak_bytes'))}B)"
                    if pm.get("watermark_peak_bytes") is not None
                    else ""
                )
            )
        ranked = pm.get("ranked") or []
        if ranked:
            lines.append(
                "  ranked owners: "
                + ", ".join(
                    f"{r.get('owner')} {_human(r.get('device_bytes'))}B"
                    for r in ranked
                )
            )
        if pm.get("error"):
            lines.append(f"  error: {pm['error']}")
    captures = fsummary.get("profile_captures") or []
    digests = {d.get("trigger_step"): d for d in fsummary.get("profile_digests") or []}
    for cap in captures:
        trigger = cap.get("trigger_step")
        lines.append("")
        lines.append(
            f"anomaly profile capture (trigger step {trigger}): {cap.get('dir')}"
        )
        dig = digests.get(trigger)
        if dig is None:
            lines.append("  no digest recorded (analysis still pending at flush time)")
        elif dig.get("name") == "sentinel.profile_analysis_failed":
            lines.append(f"  analysis FAILED: {dig.get('error')}")
        else:
            overlap = dig.get("overlap_fraction")
            overlap_str = f"{100.0 * overlap:.1f}%" if overlap is not None else "n/a"
            lines.append(
                f"  digest: device busy {dig.get('device_busy_ms')} ms, "
                f"compute {dig.get('compute_ms')} ms, "
                f"collective {dig.get('collective_ms')} ms "
                f"(exposed {dig.get('exposed_collective_ms')} ms, overlap {overlap_str}), "
                f"idle {dig.get('idle_ms')} ms over {dig.get('n_steps')} step(s)"
            )
            top = dig.get("top_ops") or []
            if top:
                lines.append(
                    "  top ops: "
                    + ", ".join(f"{r.get('name')} {r.get('self_ms')} ms" for r in top)
                )
    for sig in fsummary["signals"]:
        lines.append(
            f"signal: {sig.get('name', sig.get('signum'))} at t={sig.get('t')}"
        )
    for crash in fsummary["crashes"]:
        lines.append(f"crash: {crash.get('error')}: {crash.get('message')}")
    final = fsummary["final_event"]
    if final is not None:
        when = final.get("t")
        stamp = (
            time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(when))
            if isinstance(when, (int, float))
            else "?"
        )
        lines.append("")
        lines.append(f"final event before death: {_event_str(final)} at {stamp}")
    return "\n".join(lines)


def _human(n) -> str:
    """1234567 -> '1.2M' (unitless SI prefix; caller appends the unit)."""
    if n is None:
        return "?"
    n = float(n)
    for mag, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(n) >= mag:
            return f"{n / mag:.1f}{suffix} "
    return f"{n:.0f} "


def format_serving_block(snapshot) -> list:
    """Render the serving engine's SLO block from ``serving.*`` metric
    families (``serving/engine.py``); empty list when the run never served."""
    if not snapshot or not any(k.startswith("serving.") for k in snapshot):
        return []
    g = snapshot.get
    lines = ["serving engine (continuous batching):"]
    lines.append(
        f"  requests: {g('serving.requests', 0)} submitted, "
        f"{g('serving.completed', 0)} completed, "
        f"{g('serving.preempted', 0)} preempted; "
        f"{g('serving.tokens', 0)} tokens generated"
    )
    lines.append(
        f"  dispatches: {g('serving.decode_dispatches', 0)} decode "
        f"(fused, 1/step), {g('serving.prefill_dispatches', 0)} prefill chunks"
    )
    spec_rounds = g("serving.spec.rounds", 0)
    if spec_rounds:
        lines.append(
            f"  speculative: {g('serving.spec.accepted', 0)}/"
            f"{g('serving.spec.proposed', 0)} drafts accepted "
            f"(rate {g('serving.spec.acceptance_rate', 0.0):.1%}) over "
            f"{spec_rounds} verify rounds; "
            f"{g('serving.tokens_per_dispatch', 0.0):.2f} tokens/dispatch"
        )

    def hist(stem, label, unit="ms"):
        if g(f"{stem}.count"):
            lines.append(
                f"  {label}: p50 {g(f'{stem}.p50', 0):.2f} / "
                f"p95 {g(f'{stem}.p95', 0):.2f} / "
                f"mean {g(f'{stem}.mean', 0):.2f} {unit} "
                f"({g(f'{stem}.count')} samples)"
            )

    shed = g("serving.shed", 0)
    expired = g("serving.deadline_expired", 0)
    quarantined = g("serving.quarantined", 0)
    if shed or expired or quarantined:
        lines.append(
            f"  robustness: {shed} shed (queue bound), "
            f"{expired} deadline-expired, {quarantined} quarantined"
        )
    hist("serving.ttft_ms", "TTFT")
    hist("serving.inter_token_ms", "inter-token")
    hist("serving.queue_wait_ms", "queue wait")
    hist("serving.requeue_wait_ms", "re-queue wait (post-preemption)")
    hist("serving.tokens_per_s", "per-request throughput", unit="tok/s")
    occ = g("serving.block_occupancy")
    if occ is not None:
        lines.append(
            f"  kv blocks: {g('serving.blocks_used', 0)} in use "
            f"(occupancy {occ:.1%}), queue depth {g('serving.queue_depth', 0)}, "
            f"active slots {g('serving.active_slots', 0)}"
        )
    demotions = g("serving.tier.demotions", 0)
    promotions = g("serving.tier.promotions", 0)
    fallbacks = g("serving.tier.fallback_reprefills", 0)
    if demotions or promotions or fallbacks:
        line = (
            f"  kv tiering: {demotions} demotions / {promotions} promotions "
            f"({g('serving.tier.demoted_blocks', 0)} blocks to host, "
            f"{fallbacks} fallback re-prefills)"
        )
        host_bytes = g("serving.tier.host_bytes")
        if host_bytes is not None:
            line += (
                f"; host tier {_human(host_bytes)}B resident "
                f"({g('serving.tier.host_occupancy', 0.0):.1%} occupancy)"
            )
        lines.append(line)
    return lines


def format_memory_block(snapshot) -> list:
    """Render the HBM-ledger block from the ``memory.*``/``hbm.*`` gauge
    family (``telemetry/memledger.py``): ranked per-owner per-chip bytes,
    the conservation residual, and the fleet-min headroom.  Empty when the
    run registered no owners."""
    if not snapshot:
        return []
    owner_keys = [k for k in snapshot if k.startswith("memory.owner.")]
    if not owner_keys and "memory.attributed_bytes" not in snapshot:
        return []
    g = snapshot.get
    lines = ["memory ledger (per-chip HBM attribution):"]
    for key in sorted(owner_keys, key=lambda k: (-snapshot[k], k)):
        owner = key[len("memory.owner."):]
        if owner.endswith("_bytes"):
            owner = owner[: -len("_bytes")]
        lines.append(f"  {owner:<28} {_human(snapshot[key])}B/chip")
    att = g("memory.attributed_bytes")
    if att is not None:
        line = f"  attributed {_human(att)}B/chip"
        if g("memory.unattributed_bytes") is not None:
            line += f", unattributed residual {_human(g('memory.unattributed_bytes'))}B"
        if g("memory.headroom_bytes") is not None:
            line += f", fleet-min headroom {_human(g('memory.headroom_bytes'))}B"
        lines.append(line)
    if g("hbm.stats_available") == 0:
        lines.append(
            "  (backend reports no memory_stats — attribution only, "
            "no conservation residual)"
        )
    if g("serving.headroom_bytes") is not None:
        lines.append(f"  serving headroom: {_human(g('serving.headroom_bytes'))}B")
    if g("memory.oom_postmortems"):
        lines.append(
            f"  OOM postmortems recorded: {int(g('memory.oom_postmortems'))} "
            "(see the flight-recorder block)"
        )
    return lines


def format_goodput_block(summary: dict) -> list:
    """Render the wall-clock attribution ledger (goodput accounting);
    empty list when there is nothing attributed (no instrumented activity)."""
    gp = summary.get("goodput")
    if not gp or gp.get("attributed_s", 0.0) <= 0.0:
        return []
    from .goodput import CATEGORIES

    lines = [
        f"goodput ledger — elapsed {gp['elapsed_s']:.2f}s, "
        f"productive {100.0 * gp['goodput_fraction']:.1f}% "
        f"(conservation error {gp['conservation_error_s']:.6f}s)"
    ]
    markers = gp.get("markers") or {}
    for name in CATEGORIES:
        seconds = gp["seconds"].get(name, 0.0)
        frac = gp["fractions"].get(name, 0.0)
        if seconds <= 0.0 and name not in markers:
            continue
        mark = f"  [{markers[name]} marker(s)]" if name in markers else ""
        lines.append(f"  {name:<16} {seconds:>10.3f}s {100.0 * frac:>6.1f}%{mark}")
    snapshot = summary.get("snapshot") or {}
    fleet = snapshot.get("goodput.fleet_fraction")
    if fleet is not None:
        hosts = snapshot.get("goodput.fleet_hosts")
        lines.append(
            f"  fleet goodput (min over {int(hosts) if hosts else '?'} host(s)): "
            f"{100.0 * fleet:.1f}%"
        )
    for s in summary.get("stragglers") or []:
        if s.get("cleared"):
            continue  # the host recovered after its last straggler verdict
        lines.append(
            f"  STRAGGLER host {s.get('host')}: median {s.get('median_ms')} ms "
            f"vs fleet {s.get('fleet_median_ms')} ms ({s.get('ratio')}x)"
        )
    return lines


def format_report(summary: dict) -> str:
    lines = []
    spans = summary["spans"]
    lines.append(f"telemetry report — {summary['n_records']} records")
    lines.append("")
    if spans:
        lines.append(
            f"{'span':<36} {'count':>7} {'total_ms':>12} {'mean_ms':>10} {'max_ms':>10} {'%top':>6}"
        )
        top = summary["toplevel_ms"] or 1.0
        for name, agg in sorted(spans.items(), key=lambda kv: -kv[1]["total_ms"]):
            mean = agg["total_ms"] / agg["count"]
            pct = 100.0 * agg["total_ms"] / top if agg["depth"] == 0 else float("nan")
            pct_str = f"{pct:6.1f}" if pct == pct else "     -"
            lines.append(
                f"{name:<36} {agg['count']:>7} {agg['total_ms']:>12.1f} "
                f"{mean:>10.2f} {agg['max_ms']:>10.1f} {pct_str}"
            )
    else:
        lines.append("no spans recorded")
    lines.append("")
    lines.append(
        f"compiles: {summary['compiles']} ({summary['compile_ms']:.1f} ms total)"
    )
    if summary["stalls"]:
        lines.append(f"stalls: {len(summary['stalls'])}")
        for s in summary["stalls"]:
            lines.append(f"  - stalled {s['elapsed_s']}s (deadline {s['deadline_s']}s)")
    for name, rec in sorted(summary.get("introspect", {}).items()):
        lines.append("")
        lines.append(f"compiled program {name!r} (introspection):")
        lines.append(
            f"  cost: {_human(rec.get('flops'))}FLOPs, "
            f"{_human(rec.get('bytes_accessed'))}B accessed"
        )
        mem = rec.get("memory") or {}
        if mem:
            lines.append(
                "  memory: "
                + ", ".join(f"{k.replace('_bytes', '')} {_human(v)}B" for k, v in mem.items())
            )
        comms = rec.get("comms") or {}
        by_kind = comms.get("by_kind") or {}
        if by_kind:
            lines.append(
                f"  comms: {_human(comms.get('total_bytes'))}B total"
                + (
                    f" (est. comms/compute ratio {rec['comms_compute_ratio']:.3f})"
                    if rec.get("comms_compute_ratio") is not None
                    else ""
                )
            )
            for op_kind in sorted(by_kind):
                agg = by_kind[op_kind]
                lines.append(
                    f"    {op_kind:<20} x{agg['count']:<4} {_human(agg['bytes'])}B"
                )
            by_axis = comms.get("by_axis") or {}
            if by_axis:
                lines.append(
                    "    per mesh axis: "
                    + ", ".join(f"{ax}={_human(b)}B" for ax, b in sorted(by_axis.items()))
                )
        else:
            lines.append("  comms: no collectives (single-device program)")
        for finding in rec.get("lint") or []:
            lines.append(f"  LINT[{finding.get('kind')}]: {finding.get('message')}")
    for source in sorted(summary.get("profiles") or {}):
        from .profile_scan import format_profile_report, report_from_dict

        lines.append("")
        lines.append(format_profile_report(report_from_dict(summary["profiles"][source])))
    goodput = format_goodput_block(summary)
    if goodput:
        lines.append("")
        lines.extend(goodput)
    snapshot = summary["snapshot"]
    serving = format_serving_block(snapshot)
    if serving:
        lines.append("")
        lines.extend(serving)
    memory = format_memory_block(snapshot)
    if memory:
        lines.append("")
        lines.extend(memory)
    if snapshot:
        lines.append("")
        lines.append("final metrics snapshot:")
        for key in sorted(snapshot):
            value = snapshot[key]
            if isinstance(value, float):
                value = round(value, 4)
            lines.append(f"  {key} = {value}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m accelerate_tpu.telemetry.report",
        description=(
            "Summarize a telemetry/flight-recorder JSONL run: per-span time "
            "breakdown, compile stats, metrics snapshot, and (when a "
            "flight-recorder snapshot exists) a postmortem of the last steps."
        ),
    )
    parser.add_argument(
        "path",
        nargs="?",
        default=None,
        help="telemetry/flightrec JSONL file or run directory",
    )
    parser.add_argument(
        "--last",
        type=int,
        default=10,
        metavar="N",
        help="steps/anomalies to show in the flight-recorder block (default 10)",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help=(
            "machine-readable output: one JSON object with telemetry/"
            "postmortem/profile blocks instead of the human report"
        ),
    )
    parser.add_argument(
        "--profile",
        default=None,
        metavar="DIR",
        help=(
            "analyze a jax.profiler trace directory (or *.trace.json[.gz] "
            "file) offline and append the attribution block"
        ),
    )
    parser.add_argument(
        "--fleet",
        action="store_true",
        help=(
            "fleet postmortem view: merge every rank's telemetry_p*/"
            "flightrec_p* stream under the run directory into one rank-tagged "
            "timeline (last sign of life per rank, first-silent rank, merged "
            "tail)"
        ),
    )
    args = parser.parse_args(argv)
    if args.path is None and args.profile is None:
        parser.error("a run path and/or --profile <dir> is required")
    profile_report = None
    if args.profile is not None:
        from .profile_scan import TraceParseError, analyze_trace_dir

        if not os.path.exists(args.profile):
            print(f"no such file or directory: {args.profile}", file=sys.stderr)
            return 1
        try:
            profile_report = analyze_trace_dir(args.profile)
        except TraceParseError as e:
            print(f"profile scan failed: {e}", file=sys.stderr)
            return 1
    records: list = []
    flight: list = []
    serving_traces: list = []
    fleet: dict = {}
    if args.path is not None:
        if not os.path.exists(args.path):
            print(f"no such file or directory: {args.path}", file=sys.stderr)
            return 1
        is_flight_file = not os.path.isdir(args.path) and os.path.basename(
            args.path
        ).startswith("flightrec_")
        is_trace_file = not os.path.isdir(args.path) and os.path.basename(
            args.path
        ).startswith("serving_trace_")
        records = [] if (is_flight_file or is_trace_file) else load_records(args.path)
        flight = (
            load_flight_records(args.path)
            if (os.path.isdir(args.path) or is_flight_file)
            else []
        )
        serving_traces = load_serving_trace_records(args.path)
        if args.fleet:
            fleet = load_fleet_records(args.path)
            if not fleet:
                print(
                    f"--fleet: no telemetry_p*/flightrec_p* streams under {args.path}",
                    file=sys.stderr,
                )
                return 1
        if not records and not flight and not serving_traces:
            print(f"no telemetry records found under {args.path}", file=sys.stderr)
            # A successful --profile scan still renders: the run dir being
            # empty must not throw away the half that worked.
            if profile_report is None:
                return 1
    if args.json:
        # Machine contract (bench/CI): stable top-level keys, no screen
        # scraping.  Blocks are present only when their inputs are.
        out: dict = {}
        if records:
            summary = summarize(records)
            # The ledger is its own machine contract (bench/perf_gate/chaos
            # consume it): a stable top-level key, independent of where the
            # telemetry block's internals move.
            out["goodput"] = summary.pop("goodput", None)
            out["telemetry"] = summary
        if flight:
            out["postmortem"] = summarize_flight(flight)
        if serving_traces:
            # Offline blame decomposition, recomputed from the trace JSONL —
            # a dead engine gets the same block a live one would.
            from ..serving.tracing import summarize_traces

            out["serving_traces"] = summarize_traces(serving_traces)
        if fleet:
            out["fleet"] = summarize_fleet(fleet)
        if profile_report is not None:
            out["profile"] = profile_report.to_dict()
        print(json.dumps(out, default=str))
        return 0
    blocks = []
    if records:
        blocks.append(format_report(summarize(records)))
    if flight:
        blocks.append(format_flight_report(summarize_flight(flight), last_n=args.last))
    if fleet:
        blocks.append(format_fleet_report(summarize_fleet(fleet), last_n=args.last))
    if serving_traces:
        from ..serving.tracing import format_trace_block, summarize_traces

        trace_lines = format_trace_block(summarize_traces(serving_traces))
        if trace_lines:
            blocks.append("\n".join(trace_lines))
    if profile_report is not None:
        from .profile_scan import format_profile_report

        blocks.append(format_profile_report(profile_report))
    print("\n\n".join(blocks))
    return 0


if __name__ == "__main__":
    sys.exit(main())
