"""Telemetry run summarizer: ``python -m accelerate_tpu.telemetry.report <path>``.

``<path>`` is a telemetry JSONL file or a directory holding
``telemetry_p*.jsonl`` files (one per process).  Prints a per-span time
breakdown, compile statistics, stall events, and the final metrics snapshot.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

__all__ = ["load_records", "summarize", "format_report", "main"]


def load_records(path: str) -> list[dict]:
    """Parse every record from a JSONL file or a run directory.  Unparseable
    lines (a crashed writer's torn tail) are skipped, not fatal."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "telemetry_p*.jsonl")))
        if not files:
            files = sorted(glob.glob(os.path.join(path, "*.jsonl")))
    else:
        files = [path]
    records = []
    for file in files:
        with open(file) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue
    return records


def summarize(records: list[dict]) -> dict:
    """Aggregate records into the report's sections."""
    spans: dict = {}
    toplevel_ms = 0.0
    compiles = 0
    compile_ms = 0.0
    stalls = []
    snapshot = None
    introspect = {}
    for rec in records:
        kind = rec.get("kind")
        if kind == "span":
            name = rec.get("name", "?")
            agg = spans.setdefault(
                name, {"count": 0, "total_ms": 0.0, "max_ms": 0.0, "depth": rec.get("depth", 0)}
            )
            dur = float(rec.get("dur_ms", 0.0))
            agg["count"] += 1
            agg["total_ms"] += dur
            agg["max_ms"] = max(agg["max_ms"], dur)
            agg["depth"] = min(agg["depth"], rec.get("depth", 0))
            if rec.get("depth", 0) == 0:
                toplevel_ms += dur
        elif kind == "compile":
            compiles += 1
            compile_ms += float(rec.get("dur_ms", 0.0))
        elif kind == "stall":
            stalls.append(
                {"elapsed_s": rec.get("elapsed_s"), "deadline_s": rec.get("deadline_s")}
            )
        elif kind == "metrics":
            snapshot = rec.get("snapshot")  # last one wins (written on disable)
        elif kind == "introspect":
            # Latest capture per program name wins (a recompile re-captures).
            introspect[rec.get("name", "?")] = rec
    return {
        "spans": spans,
        "toplevel_ms": toplevel_ms,
        "compiles": compiles,
        "compile_ms": compile_ms,
        "stalls": stalls,
        "snapshot": snapshot,
        "introspect": introspect,
        "n_records": len(records),
    }


def _human(n) -> str:
    """1234567 -> '1.2M' (unitless SI prefix; caller appends the unit)."""
    if n is None:
        return "?"
    n = float(n)
    for mag, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(n) >= mag:
            return f"{n / mag:.1f}{suffix} "
    return f"{n:.0f} "


def format_report(summary: dict) -> str:
    lines = []
    spans = summary["spans"]
    lines.append(f"telemetry report — {summary['n_records']} records")
    lines.append("")
    if spans:
        lines.append(
            f"{'span':<36} {'count':>7} {'total_ms':>12} {'mean_ms':>10} {'max_ms':>10} {'%top':>6}"
        )
        top = summary["toplevel_ms"] or 1.0
        for name, agg in sorted(spans.items(), key=lambda kv: -kv[1]["total_ms"]):
            mean = agg["total_ms"] / agg["count"]
            pct = 100.0 * agg["total_ms"] / top if agg["depth"] == 0 else float("nan")
            pct_str = f"{pct:6.1f}" if pct == pct else "     -"
            lines.append(
                f"{name:<36} {agg['count']:>7} {agg['total_ms']:>12.1f} "
                f"{mean:>10.2f} {agg['max_ms']:>10.1f} {pct_str}"
            )
    else:
        lines.append("no spans recorded")
    lines.append("")
    lines.append(
        f"compiles: {summary['compiles']} ({summary['compile_ms']:.1f} ms total)"
    )
    if summary["stalls"]:
        lines.append(f"stalls: {len(summary['stalls'])}")
        for s in summary["stalls"]:
            lines.append(f"  - stalled {s['elapsed_s']}s (deadline {s['deadline_s']}s)")
    for name, rec in sorted(summary.get("introspect", {}).items()):
        lines.append("")
        lines.append(f"compiled program {name!r} (introspection):")
        lines.append(
            f"  cost: {_human(rec.get('flops'))}FLOPs, "
            f"{_human(rec.get('bytes_accessed'))}B accessed"
        )
        mem = rec.get("memory") or {}
        if mem:
            lines.append(
                "  memory: "
                + ", ".join(f"{k.replace('_bytes', '')} {_human(v)}B" for k, v in mem.items())
            )
        comms = rec.get("comms") or {}
        by_kind = comms.get("by_kind") or {}
        if by_kind:
            lines.append(
                f"  comms: {_human(comms.get('total_bytes'))}B total"
                + (
                    f" (est. comms/compute ratio {rec['comms_compute_ratio']:.3f})"
                    if rec.get("comms_compute_ratio") is not None
                    else ""
                )
            )
            for op_kind in sorted(by_kind):
                agg = by_kind[op_kind]
                lines.append(
                    f"    {op_kind:<20} x{agg['count']:<4} {_human(agg['bytes'])}B"
                )
            by_axis = comms.get("by_axis") or {}
            if by_axis:
                lines.append(
                    "    per mesh axis: "
                    + ", ".join(f"{ax}={_human(b)}B" for ax, b in sorted(by_axis.items()))
                )
        else:
            lines.append("  comms: no collectives (single-device program)")
        for finding in rec.get("lint") or []:
            lines.append(f"  LINT[{finding.get('kind')}]: {finding.get('message')}")
    snapshot = summary["snapshot"]
    if snapshot:
        lines.append("")
        lines.append("final metrics snapshot:")
        for key in sorted(snapshot):
            value = snapshot[key]
            if isinstance(value, float):
                value = round(value, 4)
            lines.append(f"  {key} = {value}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m accelerate_tpu.telemetry.report",
        description="Summarize a telemetry JSONL run into a per-span time breakdown.",
    )
    parser.add_argument("path", help="telemetry JSONL file or run directory")
    args = parser.parse_args(argv)
    if not os.path.exists(args.path):
        print(f"no such file or directory: {args.path}", file=sys.stderr)
        return 1
    records = load_records(args.path)
    if not records:
        print(f"no telemetry records found under {args.path}", file=sys.stderr)
        return 1
    print(format_report(summarize(records)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
