"""Goodput accounting: a wall-clock attribution ledger for the whole run.

Every other observability layer answers *how* the run is doing (telemetry),
*what* happened (flight recorder), or *why a step was slow* (profile scan).
None of them answers the first question a fleet operator asks: **what
fraction of wall-clock time actually advanced training, and which subsystem
burned the rest?**  The :class:`GoodputLedger` answers it by classifying
every second of the run into exactly ONE category:

- ``productive`` — fused-step compute that advanced training
  (``pipeline.train_step`` spans, minus everything below);
- ``compile`` — XLA backend compiles (the telemetry compile listener);
- ``checkpoint`` — save/restore/publish wall time plus checkpoint-I/O retry
  backoff waits (``checkpoint.*`` / ``resilience.final_checkpoint`` /
  ``health.rewind`` spans, ``resilience.retry`` waits on I/O labels);
- ``rewind_replay`` — steps that computed but did NOT advance training: the
  zero-delta steps the health gate skipped, and the steps re-run after a
  NaN rewind (badput even though the device was busy);
- ``input_wait`` — host/input-blocked time (``dataloader.next_batch`` spans:
  batch conversion, device placement, prefetch queue waits);
- ``device_acquire`` — device-acquisition retry backoff (retry waits whose
  label names a device/acquire path, or whose error is RESOURCE_EXHAUSTED)
  and OOM-driven batch-size halvings;
- ``preempt`` — drain downtime after a preemption signal (everything after
  ``resilience.preempt_signal`` not claimed by a category above);
- ``idle`` — the unattributed remainder (Python overhead, logging, eval,
  anything uninstrumented).

The ledger is **sourced from the existing instrumentation** — it subscribes
to the telemetry record stream (spans, compile records, ``event()`` markers)
via :meth:`observe_record`, so nothing on the hot path is re-instrumented.
Overlaps resolve by a fixed precedence sweep (a compile inside a train-step
span is ``compile``, not ``productive``), which is what makes the
**conservation invariant** hold by construction: the per-category seconds sum
to the elapsed wall-clock window within float ε, and no second is counted
twice.  ``summary()['conservation_error_s']`` exposes the residual; ``make
goodput-smoke`` asserts it.

**Fault markers** ride along: badput-narrating events (preempt signals,
checkpoint-I/O retries/give-ups, OOM, health skips/rewinds) are tallied per
category in ``summary()['markers']`` — the chaos campaign's acceptance
oracle checks each injected fault class lands in its correct category.

Offline mode: :func:`ledger_from_records` / :func:`summary_from_records`
replay a telemetry JSONL stream (the same one ``telemetry.report`` loads),
so a dead run's goodput is computable post-hoc and ``telemetry.report
--json`` carries a stable ``goodput`` top-level key.

Fleet aggregation: :class:`FleetAggregator` finally wires the sentinel's
``observe_host_step`` / ``straggler_report`` hooks into the train loop — at
a bounded, call-count-gated cadence (lockstep, like
``PreemptionGuard.should_stop``) it gathers per-host step durations and
local goodput fractions over the existing multi-host gather path, feeds the
sentinel, publishes fleet goodput = **min over hosts**, and names stragglers
as ``sentinel.straggler`` events that ``telemetry.report`` renders.

Enable live with ``ACCELERATE_TPU_GOODPUT=1`` (rides telemetry enablement)
or :func:`attach`.  Default-off, like every other telemetry layer.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from .sentinel import AnomalySentinel

__all__ = [
    "CATEGORIES",
    "BADPUT_CATEGORIES",
    "GoodputLedger",
    "FleetAggregator",
    "attach",
    "attached",
    "detach",
    "get_ledger",
    "ledger_from_records",
    "summary_from_records",
    "ENV_GOODPUT",
]

ENV_GOODPUT = "ACCELERATE_TPU_GOODPUT"

_TRUTHY = {"1", "true", "yes", "on"}

# Attribution precedence, highest first.  ``preempt`` and ``idle`` are
# background categories: they claim whatever the interval sweep left
# unattributed (after/before the preemption mark respectively), which is
# exactly why the categories always sum to the elapsed window.
CATEGORIES = (
    "compile",
    "checkpoint",
    "device_acquire",
    "input_wait",
    "rewind_replay",
    "productive",
    "preempt",
    "idle",
)
BADPUT_CATEGORIES = tuple(c for c in CATEGORIES if c != "productive")

_N_FOREGROUND = 6  # compile..productive carry explicit intervals

_CAT_INDEX = {name: i for i, name in enumerate(CATEGORIES)}

# Span name -> category.  Nested checkpoint spans (publish, write_manifest,
# verify) are deliberately absent: their parents already claim the window and
# same-category nesting would only bloat the sweep.  ``health.rewind`` wraps
# the checkpoint restore, so it is checkpoint time; the *replayed* steps after
# it are claimed by the rewind-replay budget instead.
_SPAN_CATEGORY = {
    "checkpoint.save_state": "checkpoint",
    "checkpoint.load_state": "checkpoint",
    "resilience.final_checkpoint": "checkpoint",
    "health.rewind": "checkpoint",
    "dataloader.next_batch": "input_wait",
}

_STEP_SPAN = "pipeline.train_step"

# Retry labels that mean "fighting for a device", not checkpoint I/O.
_ACQUIRE_MARKERS = ("device", "acquire", "oom")


def _retry_category(label: str, error: str) -> str:
    text = (label or "").lower()
    if any(m in text for m in _ACQUIRE_MARKERS) or "RESOURCE_EXHAUSTED" in (error or ""):
        return "device_acquire"
    return "checkpoint"


class GoodputLedger:
    """Interval-based wall-clock attribution with a precedence sweep.

    Thread-safe: records arrive from the main thread, the watchdog, and the
    prefetcher.  ``summary()`` may be called at any time; the window runs
    from construction (or ``start_t``) to ``now``.
    """

    # Fold fully-swept intervals into scalar totals once the tail grows past
    # this — keeps summary() O(bounded) on multi-day runs.  The compaction
    # boundary trails ``now`` by a margin so late-arriving intervals (a retry
    # wait recorded before its sleep) still land in the live tail.
    COMPACT_AT = 4096
    COMPACT_MARGIN_S = 60.0

    def __init__(self, start_t: Optional[float] = None):
        self.start_t = float(start_t if start_t is not None else time.time())
        self._lock = threading.Lock()
        # [category_index, t0, t1] — foreground attribution claims.  Lists,
        # not tuples: a health.skip reclassifies its step's interval IN PLACE
        # via a direct object reference, which stays valid across the
        # compaction rebuilds below (an index would go stale).
        self._intervals: List[list] = []
        self._compacted_upto = self.start_t
        self._compacted = {name: 0.0 for name in CATEGORIES}
        self._markers = {}
        # Steps re-run after a health rewind are badput: each rewind event
        # adds (step - resumed_step) to this budget and the next that-many
        # train-step spans classify as rewind_replay instead of productive.
        self._replay_budget = 0
        # The last productive step interval (object reference), so a
        # health.skip event (the zero-delta step that just "computed" for
        # nothing) can reclassify it.  Cleared when compaction folds it —
        # skips arrive milliseconds after their span, far inside the
        # COMPACT_MARGIN_S tail, so the degradation is theoretical.
        self._last_step_interval: Optional[list] = None
        self.preempt_from: Optional[float] = None

    # -- ingestion -----------------------------------------------------------

    def note_interval(self, category: str, t0: float, t1: float) -> None:
        """Claim ``[t0, t1]`` for ``category`` (foreground categories only)."""
        idx = _CAT_INDEX[category]
        if idx >= _N_FOREGROUND:
            raise ValueError(f"{category!r} is a background category — it is derived, not claimed")
        if t1 <= t0:
            return
        with self._lock:
            self._intervals.append([idx, float(t0), float(t1)])

    def note_marker(self, category: str, n: int = 1) -> None:
        with self._lock:
            self._markers[category] = self._markers.get(category, 0) + n

    def observe_record(self, record: dict) -> None:
        """Classify one telemetry record (called by ``Telemetry.write`` for
        every live record, and by :func:`ledger_from_records` offline)."""
        kind = record.get("kind")
        if kind == "span":
            self._observe_span(record)
        elif kind == "compile":
            t = record.get("t") or time.time()
            dur = float(record.get("dur_ms") or 0.0) / 1e3
            self.note_interval("compile", t - dur, t)
        elif kind == "event":
            self._observe_event(record)

    def _observe_span(self, record: dict) -> None:
        name = record.get("name")
        t = record.get("t") or time.time()
        dur = float(record.get("dur_ms") or 0.0) / 1e3
        if name == _STEP_SPAN:
            with self._lock:
                if self._replay_budget > 0:
                    self._replay_budget -= 1
                    cat = _CAT_INDEX["rewind_replay"]
                    self._last_step_interval = None
                else:
                    cat = _CAT_INDEX["productive"]
                    self._last_step_interval = None
                if dur > 0:
                    interval = [cat, t - dur, t]
                    self._intervals.append(interval)
                    if cat == _CAT_INDEX["productive"]:
                        self._last_step_interval = interval
            return
        cat = _SPAN_CATEGORY.get(name)
        if cat is not None:
            self.note_interval(cat, t - dur, t)

    def _observe_event(self, record: dict) -> None:
        name = record.get("name")
        t = record.get("t") or time.time()
        if name == "resilience.preempt_signal":
            if self.preempt_from is None or t < self.preempt_from:
                self.preempt_from = t
            self.note_marker("preempt")
        elif name == "resilience.preempt_checkpoint":
            self.note_marker("preempt")
        elif name == "resilience.retry":
            cat = _retry_category(record.get("label"), record.get("error"))
            wait = float(record.get("wait_s") or 0.0)
            # The event is emitted BEFORE the backoff sleep: the wait interval
            # extends forward from the record time.
            self.note_interval(cat, t, t + wait)
            self.note_marker(cat)
        elif name == "resilience.gave_up":
            self.note_marker(_retry_category(record.get("label"), record.get("error")))
        elif name == "memory.oom_halving":
            self.note_marker("device_acquire")
        elif name == "health.skip":
            # The step that just finished computed a zero delta: it burned
            # device time without advancing training — retroactively badput.
            with self._lock:
                interval = self._last_step_interval
                if interval is not None and interval[0] == _CAT_INDEX["productive"]:
                    interval[0] = _CAT_INDEX["rewind_replay"]
                self._last_step_interval = None
            self.note_marker("rewind_replay")
        elif name == "health.rewind":
            step = record.get("step")
            resumed = record.get("resumed_step")
            replays = 0
            try:
                replays = max(int(step) - int(resumed), 0)
            except (TypeError, ValueError):
                pass
            with self._lock:
                self._replay_budget += replays
            self.note_marker("rewind_replay")

    # -- the sweep -----------------------------------------------------------

    @staticmethod
    def _sweep(intervals: Sequence[Tuple[int, float, float]], lo: float, hi: float,
               preempt_from: Optional[float]) -> dict:
        """Attribute ``[lo, hi]`` exactly once: each elementary segment goes
        to the highest-precedence category covering it; uncovered segments go
        to ``preempt`` past the preemption mark, else ``idle``."""
        out = {name: 0.0 for name in CATEGORIES}
        if hi <= lo:
            return out
        events: List[Tuple[float, int, int]] = []
        for cat, t0, t1 in intervals:
            t0, t1 = max(t0, lo), min(t1, hi)
            if t1 > t0:
                events.append((t0, +1, cat))
                events.append((t1, -1, cat))
        events.sort(key=lambda e: e[0])

        def background(a: float, b: float):
            if b <= a:
                return
            if preempt_from is None or preempt_from >= b:
                out["idle"] += b - a
            elif preempt_from <= a:
                out["preempt"] += b - a
            else:
                out["idle"] += preempt_from - a
                out["preempt"] += b - preempt_from

        counts = [0] * _N_FOREGROUND
        cursor = lo
        i = 0
        n = len(events)
        while i < n:
            t = events[i][0]
            if t > cursor:
                active = next((c for c in range(_N_FOREGROUND) if counts[c]), None)
                if active is None:
                    background(cursor, t)
                else:
                    out[CATEGORIES[active]] += t - cursor
                cursor = t
            while i < n and events[i][0] == t:
                counts[events[i][2]] += events[i][1]
                i += 1
        if cursor < hi:
            active = next((c for c in range(_N_FOREGROUND) if counts[c]), None)
            if active is None:
                background(cursor, hi)
            else:
                out[CATEGORIES[active]] += hi - cursor
        return out

    def _compact_locked(self, upto: float) -> None:
        if upto <= self._compacted_upto:
            return
        keep: List[list] = []
        done: List[Tuple[int, float, float]] = []
        for interval in self._intervals:
            cat, t0, t1 = interval
            if t1 <= upto:
                done.append((cat, t0, upto if t1 > upto else t1))
                if interval is self._last_step_interval:
                    # The referenced step folded into scalar totals: a
                    # (pathologically late) health.skip can no longer
                    # reclassify it — degrade to the marker only.
                    self._last_step_interval = None
            elif t0 < upto:
                done.append((cat, t0, upto))
                # Clip IN PLACE so the _last_step_interval reference (and its
                # possible future reclassification) survives the split.
                interval[1] = upto
                keep.append(interval)
            else:
                keep.append(interval)
        swept = self._sweep(done, self._compacted_upto, upto, self.preempt_from)
        for name, s in swept.items():
            self._compacted[name] += s
        self._intervals = keep
        self._compacted_upto = upto

    # -- views ---------------------------------------------------------------

    def summary(self, now: Optional[float] = None) -> dict:
        """The ledger: per-category seconds/fractions over ``[start_t, now]``,
        the goodput fraction, fault markers, and the conservation residual."""
        now = float(now if now is not None else time.time())
        now = max(now, self.start_t)
        with self._lock:
            if len(self._intervals) > self.COMPACT_AT:
                self._compact_locked(
                    max(self._compacted_upto, now - self.COMPACT_MARGIN_S)
                )
            # Deep-copy the tail: intervals are mutable lists shared with
            # concurrent reclassification/compaction; the sweep below runs
            # outside the lock and must see a consistent snapshot.
            intervals = [tuple(iv) for iv in self._intervals]
            compacted = dict(self._compacted)
            markers = dict(self._markers)
            lo = self._compacted_upto
        seconds = self._sweep(intervals, lo, now, self.preempt_from)
        for name, s in compacted.items():
            seconds[name] += s
        elapsed = now - self.start_t
        total = sum(seconds.values())
        fractions = {
            name: (s / elapsed if elapsed > 0 else 0.0) for name, s in seconds.items()
        }
        return {
            "start_t": self.start_t,
            "end_t": now,
            "elapsed_s": elapsed,
            "seconds": {k: round(v, 6) for k, v in seconds.items()},
            "fractions": {k: round(v, 6) for k, v in fractions.items()},
            "goodput_fraction": round(fractions["productive"], 6),
            "attributed_s": round(total - seconds["idle"] - seconds["preempt"], 6),
            "conservation_error_s": round(elapsed - total, 9),
            "markers": markers,
        }

    def publish(self, registry, now: Optional[float] = None) -> dict:
        """Land the ledger in the metrics registry as ``goodput.*`` gauges
        (what the Prometheus exporter and the final snapshot serve)."""
        s = self.summary(now=now)
        registry.gauge("goodput.elapsed_s").set(s["elapsed_s"])
        registry.gauge("goodput.fraction").set(s["goodput_fraction"])
        registry.gauge("goodput.attributed_s").set(s["attributed_s"])
        for name in CATEGORIES:
            registry.gauge(f"goodput.{name}_s").set(s["seconds"][name])
        return s


# ---------------------------------------------------------------------------
# Singleton attachment (the live ledger rides the telemetry record stream)
# ---------------------------------------------------------------------------


def attach(start_t: Optional[float] = None) -> GoodputLedger:
    """Attach a fresh ledger to the telemetry singleton: every subsequent
    record (span/compile/event) is classified as it is written."""
    from . import core

    ledger = GoodputLedger(start_t=start_t)
    core.get_telemetry().goodput = ledger
    return ledger


def detach() -> None:
    from . import core

    core.get_telemetry().goodput = None


@contextlib.contextmanager
def attached(start_t: Optional[float] = None):
    """Scoped ledger: attach a fresh one for the block, then RESTORE whatever
    was attached before (a probe inside a goodput-enabled run must not
    destroy the host run's ledger)."""
    from . import core

    tel = core.get_telemetry()
    previous = tel.goodput
    ledger = GoodputLedger(start_t=start_t)
    tel.goodput = ledger
    try:
        yield ledger
    finally:
        tel.goodput = previous


def get_ledger() -> Optional[GoodputLedger]:
    from . import core

    return core.get_telemetry().goodput


def enabled_from_env() -> bool:
    return os.environ.get(ENV_GOODPUT, "").strip().lower() in _TRUTHY


# ---------------------------------------------------------------------------
# Offline replay (postmortems, the report CLI, the chaos oracle)
# ---------------------------------------------------------------------------


def ledger_from_records(records: Sequence[dict]) -> Optional[GoodputLedger]:
    """Rebuild a ledger from a parsed telemetry JSONL stream (the list
    ``telemetry.report.load_records`` returns).  The window spans the
    records' timestamps.  Returns None for an empty stream."""
    stamped = [r for r in records if isinstance(r.get("t"), (int, float))]
    if not stamped:
        return None
    stamped.sort(key=lambda r: r["t"])

    def _t0(rec):
        # Span/compile records are stamped at their END: the window must
        # open at the earliest interval START or the first span would be
        # clipped out of its own ledger.
        if rec.get("kind") in ("span", "compile"):
            return rec["t"] - float(rec.get("dur_ms") or 0.0) / 1e3
        return rec["t"]

    ledger = GoodputLedger(start_t=min(_t0(r) for r in stamped))
    for rec in stamped:
        ledger.observe_record(rec)
    return ledger


def summary_from_records(records: Sequence[dict]) -> Optional[dict]:
    """Offline goodput summary over a record stream (None when empty)."""
    stamped = [r.get("t") for r in records if isinstance(r.get("t"), (int, float))]
    ledger = ledger_from_records(records)
    if ledger is None:
        return None
    return ledger.summary(now=max(stamped))


# ---------------------------------------------------------------------------
# Fleet aggregation: per-host step durations + min-over-hosts goodput
# ---------------------------------------------------------------------------


class FleetAggregator:
    """Cadence-gated multi-host aggregation over the existing gather path.

    ``on_step()`` runs once per completed optimizer step on EVERY process (it
    is called from ``Telemetry.record_step``, which the fused train step runs
    in lockstep across hosts).  Every ``every``-th call — call-count gated,
    never wall-clock, for exactly the reason ``PreemptionGuard.should_stop``
    is — all hosts gather ``{host, step durations since last gather, local
    goodput fraction}``, feed the sentinel's per-host straggler hooks, and
    publish:

    - ``goodput.fleet_fraction`` — min over hosts of the local goodput
      fraction (the fleet only advances as fast as its slowest member);
    - ``goodput.fleet_hosts`` / ``goodput.straggler_count`` gauges;
    - one ``sentinel.straggler`` event per named straggler (host id, median
      step ms, fleet median, ratio) — rendered by ``telemetry.report``.

    ``gather_fn`` is injectable for tests (and defaults to
    ``utils.operations.gather_object``, which on a single process is the
    identity — so single-host runs pay one list append per step and never
    touch a collective).
    """

    MAX_DURS_PER_GATHER = 64

    def __init__(
        self,
        sentinel: Optional[AnomalySentinel] = None,
        every: Optional[int] = None,
        gather_fn: Optional[Callable] = None,
        host: Optional[int] = None,
    ):
        if every is None:
            # Env-tunable cadence so short-lived fleets (the multi-process
            # chaos campaign runs single-digit steps) still reach a gather.
            every = int(os.environ.get("ACCELERATE_TPU_FLEET_EVERY", "32"))
        self.every = max(1, int(every))
        self._calls = 0
        self._pending: List[float] = []
        self._sentinel = sentinel
        self._gather = gather_fn
        self._host = host
        # Hosts named straggler at the previous gather: a host that recovers
        # gets an explicit cleared=True event, so the report's latest-verdict-
        # per-host view actually ages out (recovery emits no straggler row).
        self._named: set = set()
        self.last_report: Optional[dict] = None

    def _resolve_host(self) -> int:
        if self._host is None:
            try:
                import jax

                self._host = int(jax.process_index())
            except Exception:
                self._host = 0
        return self._host

    def _resolve_sentinel(self) -> AnomalySentinel:
        if self._sentinel is None:
            # Share the flight recorder's sentinel when it is running, so the
            # straggler state and the anomaly stream live in one place.
            from .flightrec import get_flight_recorder

            rec = get_flight_recorder()
            if rec.enabled and rec.sentinel is not None:
                self._sentinel = rec.sentinel
            else:
                self._sentinel = AnomalySentinel()
        return self._sentinel

    def _gather_payloads(self, payload: dict) -> List[dict]:
        if self._gather is not None:
            return list(self._gather([payload]))
        from ..utils.operations import gather_object

        return list(gather_object([payload]))

    def on_step(self, dur_ms: float, telemetry=None) -> Optional[dict]:
        """Buffer one local step duration; on the cadence boundary, gather,
        feed the sentinel, publish.  Returns the fleet report dict on gather
        calls, else None."""
        self._pending.append(float(dur_ms))
        self._calls += 1
        if self._calls % self.every != 0:
            return None
        local_fraction = None
        ledger = get_ledger()
        if ledger is not None:
            local_fraction = ledger.summary()["goodput_fraction"]
        payload = {
            "host": self._resolve_host(),
            "durs": self._pending[-self.MAX_DURS_PER_GATHER:],
            "goodput_fraction": local_fraction,
        }
        self._pending = []
        gathered = self._gather_payloads(payload)
        sentinel = self._resolve_sentinel()
        for p in gathered:
            for dur in p.get("durs") or []:
                sentinel.observe_host_step(int(p.get("host", 0)), dur)
        stragglers = sentinel.straggler_report()
        fractions = [
            p["goodput_fraction"]
            for p in gathered
            if p.get("goodput_fraction") is not None
        ]
        fleet_fraction = min(fractions) if fractions else None
        report = {
            "hosts": len(gathered),
            "fleet_fraction": fleet_fraction,
            "stragglers": stragglers,
        }
        self.last_report = report
        named_now = {s["host"] for s in stragglers}
        if telemetry is not None and telemetry.enabled:
            registry = telemetry.registry
            registry.gauge("goodput.fleet_hosts").set(len(gathered))
            registry.gauge("goodput.straggler_count").set(len(stragglers))
            if fleet_fraction is not None:
                registry.gauge("goodput.fleet_fraction").set(fleet_fraction)
            for s in stragglers:
                telemetry.event("sentinel.straggler", **s)
            for host in sorted(self._named - named_now):
                telemetry.event("sentinel.straggler", host=host, cleared=True)
        self._named = named_now
        return report
