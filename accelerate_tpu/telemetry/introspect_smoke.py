"""Introspection smoke: a 2-step CPU training loop on a forced dp=2 mesh with
``ACCELERATE_TPU_INTROSPECT=1``.

Run via ``make introspect-smoke`` (or
``python -m accelerate_tpu.telemetry.introspect_smoke``).  Drives the
transparent PreparedModel hook end-to-end, then asserts the telemetry JSONL
contains a parseable ``introspect`` record whose comms ledger reports >= 1
collective (the dp gradient all-reduce) with nonzero byte volume, and prints
the report (including the comms/memory block).  Exit code 0 only when every
assertion holds.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def main() -> int:
    # Environment BEFORE the first jax import: CPU backend, 2 virtual devices
    # (the dp=2 mesh), introspection on.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()
    os.environ["ACCELERATE_TPU_INTROSPECT"] = "1"
    out_dir = tempfile.mkdtemp(prefix="atpu_introspect_smoke_")

    from accelerate_tpu import telemetry

    tel = telemetry.enable(dir=out_dir)

    import torch
    from torch.utils.data import DataLoader

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.test_utils import RegressionDataset, RegressionModelWithLoss
    from accelerate_tpu.utils.dataclasses import ParallelismConfig

    def _collate(samples):
        return {
            "x": torch.tensor([s["x"] for s in samples]),
            "y": torch.tensor([s["y"] for s in samples]),
        }

    accelerator = Accelerator(parallelism_config=ParallelismConfig(dp=2))
    assert dict(accelerator.mesh.shape)["dp"] == 2, dict(accelerator.mesh.shape)
    # The prepared loader feeds a GLOBAL batch of 4 x dp=2 = 8 samples per
    # step: 16 samples = exactly 2 steps.
    ds = RegressionDataset(length=16)
    dl = DataLoader(list(ds), batch_size=4, collate_fn=_collate)
    model = RegressionModelWithLoss()
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    model, opt, dl = accelerator.prepare(model, opt, dl)

    steps = 0
    for batch in dl:  # 8 samples / batch 4 = exactly 2 steps
        out = model(x=batch["x"], y=batch["y"])
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        steps += 1
    assert steps == 2, f"expected 2 steps, ran {steps}"

    path = tel.jsonl_path
    telemetry.disable()  # flush the final metrics snapshot

    assert path is not None and os.path.exists(path), f"telemetry JSONL missing: {path}"
    with open(path) as f:
        records = [json.loads(line) for line in f if line.strip()]  # must parse
    intro = [r for r in records if r.get("kind") == "introspect"]
    assert intro, f"no introspect record in {path} (the hook did not fire)"
    rec = intro[-1]
    ledger = rec.get("comms") or {}
    n_collectives = sum(v.get("count", 0) for v in (ledger.get("by_kind") or {}).values())
    assert n_collectives >= 1, (
        f"dp=2 mesh but the ledger has no collectives (no gradient sync?): {ledger}"
    )
    assert ledger.get("total_bytes", 0) > 0, f"collectives with zero bytes: {ledger}"
    assert rec.get("flops", 0) > 0, f"no analyzed FLOPs: {rec}"

    from .report import format_report, summarize

    print(format_report(summarize(records)))
    print(
        f"\nintrospect-smoke OK — {n_collectives} collective(s), "
        f"{ledger['total_bytes']} comms bytes, {rec['flops']:.0f} analyzed FLOPs "
        f"({path})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
