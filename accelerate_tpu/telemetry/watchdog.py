"""Stall watchdog: warn (with a thread dump) when no step completes in time.

A wedged device tunnel, a deadlocked collective, or a host-side data stall all
present the same way — the training loop simply stops making progress, inside
a C call no Python-level timeout can interrupt.  The watchdog runs on a
daemon thread, fed heartbeats by the instrumented hot paths
(``Telemetry.record_step`` on every completed optimizer step, the data-loader
placer on every batch); when the configured deadline passes without a beat it
logs a warning carrying every thread's current stack and writes a ``stall``
record to the telemetry JSONL.  One warning per stall episode — the next
heartbeat re-arms it.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
import traceback
from typing import Optional

__all__ = ["StallWatchdog", "thread_dump"]

logger = logging.getLogger(__name__)


def thread_dump() -> str:
    """Current stack of every live thread, watchdog threads excluded."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in frames.items():
        name = names.get(ident, "?")
        if name.startswith("atpu-watchdog"):
            continue
        stack = "".join(traceback.format_stack(frame))
        parts.append(f"--- thread {name} ({ident}) ---\n{stack}")
    return "\n".join(parts)


class StallWatchdog:
    """Deadline-based liveness monitor.

    ``beat()`` from any thread marks progress; the monitor thread checks every
    ``poll_s`` and fires once per stall episode when ``deadline_s`` elapses
    without a beat.
    """

    def __init__(self, deadline_s: float, telemetry=None, poll_s: Optional[float] = None):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self.telemetry = telemetry
        self.poll_s = poll_s if poll_s is not None else min(max(deadline_s / 4.0, 0.01), 5.0)
        self.stall_count = 0
        self._last_beat = time.monotonic()
        self._stalled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self):
        self._last_beat = time.monotonic()
        self._stalled = False

    def start(self):
        if self._thread is not None:
            return self
        self._last_beat = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="atpu-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(self.poll_s * 4, 1.0))
            self._thread = None

    def _run(self):
        while not self._stop.wait(self.poll_s):
            elapsed = time.monotonic() - self._last_beat
            if elapsed <= self.deadline_s or self._stalled:
                continue
            self._stalled = True
            self.stall_count += 1
            dump = thread_dump()
            logger.warning(
                "no training step completed in %.1fs (deadline %.1fs) — the run "
                "may be stalled.  Thread dump:\n%s",
                elapsed,
                self.deadline_s,
                dump,
            )
            if self.telemetry is not None:
                self.telemetry.registry.counter("stall.count").inc()
                self.telemetry.write(
                    {
                        "kind": "stall",
                        "elapsed_s": round(elapsed, 3),
                        "deadline_s": self.deadline_s,
                        "threads": dump,
                    }
                )
