"""Metrics registry: counters, gauges, histograms + built-in collectors.

Dependency-free by design (stdlib + jax only, and jax is touched lazily): the
registry must be constructible before any backend client exists, and a snapshot
must serialize straight into the JSONL sink or a tracker ``log()`` call.

Built-in collectors cover the signals the ROADMAP's perf work needs to prove
wins on ``bench.py``'s MFU metric:

- ``StepTimer`` — wall-time between completed optimizer steps, tokens/sec and
  an achieved-MFU estimate against the per-chip peak-FLOPs table (the same
  table ``bench.py`` uses).
- ``CompileWatcher`` — counts XLA backend compiles via ``jax.monitoring``
  duration events; every backend compile is a jit cache miss, so a moving
  count mid-training is the recompile signal GSPMD runs must not have.
- ``collect_hbm`` — live/peak device HBM bytes via ``device.memory_stats()``.
"""

from __future__ import annotations

import bisect
import collections
import threading
import time
from typing import Optional

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "StepTimer",
    "CompileWatcher",
    "collect_hbm",
    "peak_flops_per_chip",
]

# jax.monitoring key emitted once per XLA backend compile (cache hits skip it).
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# jax.monitoring event recorded once per persistent-compilation-cache hit
# (an executable deserialized instead of compiled).
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1):
        self.value += n


class Gauge:
    """Last-value-wins scalar."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = None

    def set(self, value):
        self.value = float(value)


class Histogram:
    """Streaming distribution: exact count/sum/min/max plus a bounded window of
    recent observations for percentile estimates, and exact per-bucket counts
    over fixed bounds so the Prometheus exporter (``export.py``) can render a
    true ``_bucket``/``_sum``/``_count`` triplet over ALL observations, not
    just the recent window."""

    __slots__ = ("name", "count", "total", "min", "max", "last", "_recent", "bucket_counts")

    WINDOW = 1024
    # Exposition bucket upper bounds.  The registry's histograms are
    # millisecond-scale latencies (step time, TTFT, compile ms), so the
    # bounds span sub-ms to a minute; an implicit +Inf bucket catches the
    # rest.  Unit-free values (tokens/s) still render correctly — bucket
    # placement is just coarser.
    BOUNDS = (
        1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
        1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
    )

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.last = None
        self._recent = collections.deque(maxlen=self.WINDOW)
        self.bucket_counts = [0] * (len(self.BOUNDS) + 1)

    def observe(self, value):
        value = float(value)
        self.count += 1
        self.total += value
        self.last = value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        self._recent.append(value)
        self.bucket_counts[bisect.bisect_left(self.BOUNDS, value)] += 1

    def over_threshold_fraction(self, threshold: float) -> Optional[float]:
        """Fraction of the RECENT window strictly above ``threshold`` (the
        SLO burn-rate input; None before any observation)."""
        if not self._recent:
            return None
        over = sum(1 for v in self._recent if v > threshold)
        return over / len(self._recent)

    def summary(self) -> dict:
        if self.count == 0:
            return {"count": 0}
        data = sorted(self._recent)

        def pct(q):
            return data[min(int(q * len(data)), len(data) - 1)]

        return {
            "count": self.count,
            "mean": self.total / self.count,
            "min": self.min,
            "max": self.max,
            "last": self.last,
            "p50": pct(0.50),
            "p95": pct(0.95),
        }


class MetricsRegistry:
    """Name → metric store with get-or-create accessors and a flat snapshot."""

    def __init__(self):
        self._metrics: dict = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, cls):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = cls(name)
            elif not isinstance(metric, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {type(metric).__name__}"
                )
            return metric

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get_or_create(name, Histogram)

    def peek(self, name: str):
        """Read a metric WITHOUT creating it (None when absent) — for readers
        like the flight recorder that must not materialize metrics the
        instrumented path never touched."""
        with self._lock:
            return self._metrics.get(name)

    def reset(self):
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> dict:
        """Flat ``{name: scalar}`` view: counters/gauges as-is, histograms
        exploded into ``name.count/.mean/.p50/.p95/.max/.last``."""
        out: dict = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for metric in metrics:
            if isinstance(metric, Histogram):
                for k, v in metric.summary().items():
                    if v is not None:
                        out[f"{metric.name}.{k}"] = v
            elif metric.value is not None:
                out[metric.name] = metric.value
        return out


# ---------------------------------------------------------------------------
# Built-in collectors
# ---------------------------------------------------------------------------

# Per-chip bf16 peak FLOP/s by device kind, checked in order (the table
# bench.py's MFU math uses — kept here so the live MFU gauge and the benchmark
# can never disagree).  "v5 lite"/"v5e" before "v5" so the lite chip does not
# match the v5p row.
_PEAK_FLOPS_TABLE = (
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v6", 918e12),
    ("trillium", 918e12),
)
_DEFAULT_PEAK_FLOPS = 197e12  # conservative default


def peak_flops_per_chip(device=None) -> float:
    """bf16 peak FLOP/s for one chip of ``device``'s kind (default: device 0)."""
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = device.device_kind.lower()
    for key, flops in _PEAK_FLOPS_TABLE:
        if key in kind:
            return flops
    return _DEFAULT_PEAK_FLOPS


def collect_hbm(registry: MetricsRegistry, device=None) -> dict:
    """Record device memory gauges across EVERY local device (or just
    ``device`` when given): worst-device live/peak bytes and the fleet-min
    headroom (``bytes_limit - bytes_in_use`` over all devices — the binding
    constraint, since the first chip to fill kills the whole SPMD program).

    ``hbm.stats_available`` is always published (1/0) so a dashboard can
    tell "no data" (CPU builds and tunnels return no ``memory_stats()``)
    from "zero bytes"; the byte gauges only exist where stats do.
    """
    try:
        if device is not None:
            devices = [device]
        else:
            import jax

            devices = list(jax.local_devices())
    except Exception:
        return {}
    in_use, peak, headroom = [], [], []
    for d in devices:
        try:
            stats = d.memory_stats() or None
        except Exception:
            stats = None
        if not stats:
            continue
        if "bytes_in_use" in stats:
            in_use.append(int(stats["bytes_in_use"]))
            limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
            if limit:
                headroom.append(int(limit) - int(stats["bytes_in_use"]))
        if "peak_bytes_in_use" in stats:
            peak.append(int(stats["peak_bytes_in_use"]))
    available = bool(in_use or peak)
    registry.gauge("hbm.stats_available").set(1 if available else 0)
    out = {"hbm.stats_available": 1 if available else 0}
    if not available:
        return {}
    if in_use:
        registry.gauge("hbm.bytes_in_use").set(max(in_use))
        out["hbm.bytes_in_use"] = max(in_use)
    if peak:
        registry.gauge("hbm.peak_bytes").set(max(peak))
        out["hbm.peak_bytes"] = max(peak)
    if headroom:
        registry.gauge("hbm.fleet_min_headroom_bytes").set(min(headroom))
        out["hbm.fleet_min_headroom_bytes"] = min(headroom)
    return out


class StepTimer:
    """Wall-time between completed optimizer steps → step-time histogram,
    tokens/sec and achieved-MFU gauges (when configured with the workload's
    per-step token/FLOP counts)."""

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.tokens_per_step: Optional[float] = None
        self.flops_per_step: Optional[float] = None
        # Per-program analyzed FLOPs from the compiled-program inspector
        # (introspect.py).  When the user never configured a static estimate,
        # their sum IS the per-step FLOP count — measured-cost MFU.
        self.measured_flops: dict = {}
        self._last: Optional[float] = None

    def configure(self, tokens_per_step=None, flops_per_step=None):
        if tokens_per_step is not None:
            self.tokens_per_step = float(tokens_per_step)
        if flops_per_step is not None:
            self.flops_per_step = float(flops_per_step)

    def record_measured_flops(self, program: str, flops: float):
        """Register the XLA-analyzed FLOPs of one compiled program in the step
        (called by the inspector; latest capture per program name wins).
        NOTE: ``cost_analysis`` FLOPs are PER DEVICE (the SPMD-partitioned
        module), unlike ``configure(flops_per_step=)``'s global estimate —
        the MFU math normalizes the two differently."""
        self.measured_flops[program] = float(flops)

    @property
    def effective_flops_per_step(self) -> Optional[float]:
        """Explicit static estimate if configured, else the summed analyzed
        cost of every inspected step program — measured beats assumed."""
        if self.flops_per_step:
            return self.flops_per_step
        if self.measured_flops:
            return sum(self.measured_flops.values())
        return None

    def reset(self):
        self._last = None
        self.measured_flops.clear()

    def step(self) -> Optional[float]:
        """Mark one completed step; returns the step duration in seconds (None
        for the first step — there is no prior boundary to measure from)."""
        now = time.perf_counter()
        self.registry.counter("step.count").inc()
        dt = None
        if self._last is not None:
            dt = now - self._last
            self.registry.histogram("step.time_ms").observe(dt * 1e3)
            if self.tokens_per_step:
                self.registry.gauge("step.tokens_per_sec").set(self.tokens_per_step / dt)
            try:
                if self.flops_per_step:
                    # Global static estimate: normalize by the whole fleet.
                    import jax

                    peak = peak_flops_per_chip() * jax.device_count()
                    self.registry.gauge("step.mfu").set(self.flops_per_step / dt / peak)
                elif self.measured_flops:
                    # Analyzed cost is per device (SPMD module): per-chip peak
                    # only — the same value as global MFU under symmetric SPMD.
                    flops = sum(self.measured_flops.values())
                    self.registry.gauge("step.mfu").set(
                        flops / dt / peak_flops_per_chip()
                    )
            except Exception:
                pass
        self._last = now
        return dt


class CompileWatcher:
    """Standalone compile counter: registers a ``jax.monitoring`` duration
    listener and tallies backend compiles between construction and ``stop()``.

    jax has no per-listener unregister, so the listener stays installed but
    goes inert after ``stop()`` — construct sparingly (one per process is the
    intended shape; the telemetry singleton uses its own listener)."""

    def __init__(self):
        self.count = 0
        self.total_ms = 0.0
        self._active = True
        from jax import monitoring

        def _on_duration(event, duration, **kwargs):
            if self._active and event == COMPILE_EVENT:
                self.count += 1
                self.total_ms += duration * 1e3

        monitoring.register_event_duration_secs_listener(_on_duration)

    def stop(self):
        self._active = False
