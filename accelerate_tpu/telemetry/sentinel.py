"""Anomaly sentinel: online slow-step / stall / straggler detection.

The flight recorder (``flightrec.py``) keeps a timeline of what happened;
the sentinel watches that stream *as it happens* and decides which moments
deserve attention — so the one-shot ``jax.profiler`` capture window fires on
the first anomalous step, not after a human greps the postmortem.

Detection is deliberately simple and dependency-free:

- **slow step** — a step whose duration exceeds ``factor ×`` the rolling
  median of the last ``window`` steps (with a ``min_excess_ms`` floor so
  microsecond-scale CPU noise cannot trip the multiplicative test).  The
  median is judged *before* the new sample joins the window, so a slow step
  cannot mask itself; after a genuine regime change (e.g. a new sequence
  length doubling step time) the window re-centers within ``window/2`` steps
  and the sentinel goes quiet again.
- **stall** — forwarded from the stall watchdog (no step completed within
  its deadline); always anomalous.
- **straggler** (multi-host hook) — per-host step durations fed through
  :meth:`observe_host_step` keep a rolling median per host;
  :meth:`straggler_report` names hosts whose median exceeds
  ``straggler_factor ×`` the fleet median.  Today's runtime is single-host,
  so nothing calls this on the hot path yet — the multi-host runtime
  (ROADMAP item 2) gets its per-host attribution for free.

No warmup, no verdicts: until ``warmup`` samples exist every step is judged
healthy, bounding false positives on short runs.
"""

from __future__ import annotations

import collections
import statistics
from typing import Optional

__all__ = ["AnomalySentinel"]


class AnomalySentinel:
    """Rolling-median anomaly judge over the per-step event stream.

    ``observe(dur_ms)`` returns ``None`` for a healthy step or a dict
    describing the anomaly (``reason``, the offending duration, the rolling
    median, and the ratio) — the flight recorder records it and triggers the
    one-shot profiler window.
    """

    def __init__(
        self,
        window: int = 64,
        warmup: int = 16,
        factor: float = 3.0,
        min_excess_ms: float = 10.0,
        straggler_factor: float = 1.5,
    ):
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        if warmup < 2:
            raise ValueError(f"warmup must be >= 2, got {warmup}")
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1.0, got {factor}")
        self.window = window
        self.warmup = min(warmup, window)
        self.factor = factor
        self.min_excess_ms = min_excess_ms
        self.straggler_factor = straggler_factor
        self.observed = 0
        self.anomaly_count = 0
        self._durs: collections.deque = collections.deque(maxlen=window)
        self._hosts: dict = {}

    # -- single-host stream ----------------------------------------------------

    def median_ms(self) -> Optional[float]:
        """Rolling median of the current window (None before any sample)."""
        if not self._durs:
            return None
        return float(statistics.median(self._durs))

    def observe(self, dur_ms: float) -> Optional[dict]:
        """Judge one completed step.  Returns an anomaly descriptor or None.

        The sample is judged against the window *before* joining it, then
        appended regardless of verdict — anomalous samples age into the
        median so a persistent slowdown stops alerting once it becomes the
        new normal (the recorder keeps the first ``window/2`` alerts; that is
        the signal a human wants)."""
        dur_ms = float(dur_ms)
        verdict = None
        if self.observed >= self.warmup:
            med = float(statistics.median(self._durs))
            if dur_ms > self.factor * med and dur_ms - med > self.min_excess_ms:
                verdict = {
                    "reason": "slow_step",
                    "dur_ms": round(dur_ms, 3),
                    "median_ms": round(med, 3),
                    "ratio": round(dur_ms / med, 2) if med > 0 else None,
                }
        self._durs.append(dur_ms)
        self.observed += 1
        if verdict is not None:
            self.anomaly_count += 1
        return verdict

    def stall(self, elapsed_s: float, deadline_s: float) -> dict:
        """A watchdog stall is always an anomaly (no median judgment — the
        deadline already encodes the operator's tolerance)."""
        self.anomaly_count += 1
        return {
            "reason": "stall",
            "elapsed_s": round(float(elapsed_s), 3),
            "deadline_s": float(deadline_s),
        }

    # -- multi-host straggler hooks -------------------------------------------

    def observe_host_step(self, host: int, dur_ms: float) -> None:
        """Feed one host's step duration (multi-host runtimes call this with
        gathered per-host timings; single-host runs never do)."""
        durs = self._hosts.get(host)
        if durs is None:
            durs = self._hosts[host] = collections.deque(maxlen=self.window)
        durs.append(float(dur_ms))

    def straggler_report(self) -> list:
        """Hosts whose rolling-median step time exceeds ``straggler_factor ×``
        the fleet median (median of per-host medians).  Hosts with fewer than
        ``warmup`` samples are not judged."""
        medians = {
            host: float(statistics.median(durs))
            for host, durs in self._hosts.items()
            if len(durs) >= self.warmup
        }
        if len(medians) < 2:
            return []
        fleet = statistics.median(medians.values())
        if fleet <= 0:
            return []
        return [
            {
                "host": host,
                "median_ms": round(med, 3),
                "fleet_median_ms": round(fleet, 3),
                "ratio": round(med / fleet, 2),
            }
            for host, med in sorted(medians.items())
            if med > self.straggler_factor * fleet
        ]
