"""Dependency-free observability for the training hot path.

Three pillars (see ``docs/usage_guides/telemetry.md``):

- **trace spans** — ``span("name")`` context-manager/decorator: wall-time,
  process index and nesting to a per-process JSONL file, mirrored into
  ``jax.profiler.TraceAnnotation`` for Perfetto/XPlane dumps;
- **metrics registry** — counters/gauges/histograms with built-in collectors
  for step time, jit compile count/time (cache-miss detection via
  ``jax.monitoring``), tokens/sec, achieved-MFU, and device HBM bytes;
- **stall watchdog** — warns with a full thread dump when no step completes
  within a configurable deadline;
- **compiled-program introspection** — XLA cost/memory analysis, the
  per-program collective-communication ledger, and the resharding lint
  (``ACCELERATE_TPU_INTROSPECT=1``; see ``introspect.py`` /
  ``docs/package_reference/introspect.md``);
- **flight recorder + anomaly sentinel** — a bounded ring of per-step events
  flushed crash-safe on SIGTERM/exit/crash, with online rolling-median
  anomaly detection and a one-shot profiler capture
  (``ACCELERATE_TPU_FLIGHTREC=1``; see ``flightrec.py`` / ``sentinel.py`` /
  ``docs/package_reference/flightrec.md``);
- **HBM ledger** — per-subsystem memory attribution with a per-device
  conservation contract, OOM forensics (ranked-ledger postmortems into the
  flight recorder) and serving-headroom gauges (``memledger.py`` /
  ``docs/package_reference/memledger.md``);
- **goodput accounting + metrics export** — the wall-clock attribution
  ledger (every second classified into exactly one category, with a
  conservation invariant; ``ACCELERATE_TPU_GOODPUT=1``), fleet straggler
  aggregation (min-over-hosts goodput), and a Prometheus text-exposition
  endpoint / atomic snapshot (``ACCELERATE_TPU_METRICS_PORT`` /
  ``..._SNAPSHOT``; see ``goodput.py`` / ``export.py`` /
  ``docs/package_reference/goodput.md``).

Default-off: enable with ``ACCELERATE_TPU_TELEMETRY=1`` or
``telemetry.enable()``.  Summarize a run with
``python -m accelerate_tpu.telemetry.report <dir>``.
"""

from .core import (
    ENV_DIR,
    ENV_ENABLE,
    ENV_STALL_TIMEOUT,
    Telemetry,
    disable,
    enable,
    enabled,
    get_telemetry,
    maybe_enable_from_env,
)
from .metrics import (
    CompileWatcher,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    StepTimer,
    collect_hbm,
    peak_flops_per_chip,
)
from .flightrec import FlightRecorder, get_flight_recorder
from .hlo_scan import CollectiveOp, CommsLedger, parse_collectives, scan_hlo
from .profile_scan import (
    ProfileReport as TraceProfileReport,
    analyze_trace_dir,
    analyze_trace_file,
)
from .export import MetricsExporter, render_prometheus
from .goodput import FleetAggregator, GoodputLedger
from .memledger import MemoryLedger, get_memory_ledger, tree_device_bytes
from .sentinel import AnomalySentinel
from .timeline import Timeline, TraceEvent, TraceParseError
from .introspect import (
    ENV_INTROSPECT,
    LintFinding,
    ProgramReport,
    capture,
    inspect_compiled,
    lint_reshardings,
)
from .spans import span
from .watchdog import StallWatchdog, thread_dump

__all__ = [
    "Telemetry",
    "get_telemetry",
    "enabled",
    "enable",
    "disable",
    "maybe_enable_from_env",
    "span",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "StepTimer",
    "CompileWatcher",
    "collect_hbm",
    "peak_flops_per_chip",
    "StallWatchdog",
    "thread_dump",
    # flight recorder + anomaly sentinel
    "FlightRecorder",
    "get_flight_recorder",
    "AnomalySentinel",
    "ENV_ENABLE",
    "ENV_DIR",
    "ENV_STALL_TIMEOUT",
    # compiled-program introspection
    "ENV_INTROSPECT",
    "ProgramReport",
    "LintFinding",
    "CollectiveOp",
    "CommsLedger",
    "inspect_compiled",
    "capture",
    "lint_reshardings",
    "parse_collectives",
    "scan_hlo",
    # HBM ledger (per-subsystem memory attribution + OOM forensics)
    "MemoryLedger",
    "get_memory_ledger",
    "tree_device_bytes",
    # goodput accounting + metrics export
    "GoodputLedger",
    "FleetAggregator",
    "MetricsExporter",
    "render_prometheus",
    # trace-driven performance attribution
    "TraceProfileReport",
    "analyze_trace_dir",
    "analyze_trace_file",
    "Timeline",
    "TraceEvent",
    "TraceParseError",
]
