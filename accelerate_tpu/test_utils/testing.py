"""Test harness utilities shipped with the package.

Parity target: reference ``test_utils/testing.py`` (841 LoC) — ~50 ``require_*``
skip decorators (148-556), ``get_backend`` (79), ``get_launch_command`` (107),
``execute_subprocess_async`` (724), ``get_torch_dist_unique_port`` (755),
``TempDirTestCase`` (577), ``AccelerateTestCase`` (610), ``assert_exception``,
``capture_call_output``.

TPU-native reading: the "backend matrix" is {tpu, cpu-mesh}; multi-device
means a multi-device jax platform (real chips or the virtual
``--xla_force_host_platform_device_count`` CPU mesh), and the launcher under
test is ``accelerate-tpu launch``.
"""

from __future__ import annotations

import asyncio
import contextlib
import io
import os
import shutil
import socket
import sys
import tempfile
import unittest
from typing import Callable, Optional

from ..utils import imports as _imports

__all__ = [
    "ensure_virtual_devices",
    "get_backend",
    "device_count",
    "require_cpu",
    "require_tpu",
    "require_non_cpu",
    "require_multi_device",
    "require_single_device",
    "require_torch",
    "require_transformers",
    "require_safetensors",
    "require_tensorboard",
    "require_wandb",
    "require_mlflow",
    "require_clearml",
    "require_comet_ml",
    "require_dvclive",
    "require_aim",
    "require_pandas",
    "require_cuda",
    "require_mps",
    "require_xpu",
    "require_npu",
    "require_mlu",
    "require_musa",
    "require_hpu",
    "require_bnb",
    "require_deepspeed",
    "require_megatron_lm",
    "require_msamp",
    "require_transformer_engine",
    "require_torchao",
    "require_peft",
    "require_timm",
    "require_torchvision",
    "require_torchdata_stateful_dataloader",
    "require_matplotlib",
    "require_schedulefree",
    "require_lomo",
    "require_bf16",
    "require_fp16",
    "require_fp8",
    "require_pippy",
    "require_import_timer",
    "require_multi_gpu",
    "require_huggingface_suite",
    "skip",
    "slow",
    "get_launch_command",
    "get_unique_port",
    "get_torch_dist_unique_port",
    "execute_subprocess_async",
    "run_command",
    "SubprocessCallException",
    "TempDirTestCase",
    "AccelerateTestCase",
    "MockingTestCase",
    "assert_exception",
    "capture_call_output",
]


# ---------------------------------------------------------------------------
# backend matrix
# ---------------------------------------------------------------------------


def ensure_virtual_devices(n_devices: int) -> None:
    """Guarantee ``XLA_FLAGS`` requests at least ``n_devices`` virtual CPU
    devices.  Must run BEFORE the first jax backend-client creation (the flag
    locks in then); an existing larger count is kept, a smaller one raised.
    Shared by the driver's multichip dryrun and the pp/sharding payload
    scripts."""
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            f"--xla_force_host_platform_device_count={n_devices}",
            flags,
        )


def get_backend() -> tuple[str, int, Callable[[], int]]:
    """(backend_name, device_count, memory_fn) — reference ``get_backend``
    (``testing.py:79``) returned (device, count, memory-allocated-fn)."""
    import jax

    backend = jax.default_backend()
    n = jax.device_count()

    def memory_allocated() -> int:
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
            return int(stats.get("bytes_in_use", 0))
        except Exception:
            return 0

    return backend, n, memory_allocated


def device_count() -> int:
    import jax

    return jax.device_count()


# ---------------------------------------------------------------------------
# require_* decorators (reference testing.py:148-556)
# ---------------------------------------------------------------------------


def skip(reason: str = "test skipped"):
    return unittest.skip(reason)


def slow(test_case):
    """Skip unless RUN_SLOW=1 (reference ``slow`` decorator)."""
    from ..utils.environment import parse_flag_from_env

    return unittest.skipUnless(parse_flag_from_env("RUN_SLOW"), "test is slow")(test_case)


def require_cpu(test_case):
    return unittest.skipUnless(get_backend()[0] == "cpu", "test requires the CPU backend")(test_case)


def require_non_cpu(test_case):
    return unittest.skipUnless(get_backend()[0] != "cpu", "test requires an accelerator")(test_case)


def require_tpu(test_case):
    import jax

    is_tpu = jax.default_backend() == "tpu" or any(
        "tpu" in d.platform.lower() for d in jax.devices()
    )
    return unittest.skipUnless(is_tpu, "test requires TPU")(test_case)


def require_multi_device(test_case):
    return unittest.skipUnless(device_count() > 1, "test requires multiple devices")(test_case)


def require_single_device(test_case):
    return unittest.skipUnless(device_count() == 1, "test requires a single device")(test_case)


def _require_import(flag_fn: Callable[[], bool], name: str):
    def decorator(test_case):
        return unittest.skipUnless(flag_fn(), f"test requires {name}")(test_case)

    return decorator


require_torch = _require_import(_imports.is_torch_available, "torch")
require_transformers = _require_import(_imports.is_transformers_available, "transformers")
require_safetensors = _require_import(_imports.is_safetensors_available, "safetensors")
require_tensorboard = _require_import(_imports.is_tensorboard_available, "tensorboard")
require_wandb = _require_import(_imports.is_wandb_available, "wandb")
require_mlflow = _require_import(_imports.is_mlflow_available, "mlflow")
require_clearml = _require_import(_imports.is_clearml_available, "clearml")
require_comet_ml = _require_import(_imports.is_comet_ml_available, "comet_ml")
require_dvclive = _require_import(_imports.is_dvclive_available, "dvclive")
require_aim = _require_import(_imports.is_aim_available, "aim")
require_pandas = _require_import(_imports.is_pandas_available, "pandas")

# Full reference decorator matrix (reference testing.py:148-556) over the
# detector matrix in utils/imports.py — accelerator-vendor gates honestly skip
# on a TPU host, library gates probe imports.
require_cuda = _require_import(_imports.is_cuda_available, "a CUDA device")
require_mps = _require_import(_imports.is_mps_available, "an MPS device")
require_xpu = _require_import(_imports.is_xpu_available, "an XPU device")
require_npu = _require_import(_imports.is_npu_available, "an NPU device")
require_mlu = _require_import(_imports.is_mlu_available, "an MLU device")
require_musa = _require_import(_imports.is_musa_available, "a MUSA device")
require_hpu = _require_import(_imports.is_hpu_available, "an HPU device")
require_bnb = _require_import(_imports.is_bnb_available, "bitsandbytes")
require_deepspeed = _require_import(_imports.is_deepspeed_available, "deepspeed")
require_megatron_lm = _require_import(_imports.is_megatron_lm_available, "megatron-lm")
require_msamp = _require_import(_imports.is_msamp_available, "ms-amp")
require_transformer_engine = _require_import(
    _imports.is_transformer_engine_available, "transformer-engine"
)
require_torchao = _require_import(_imports.is_torchao_available, "torchao")
require_peft = _require_import(_imports.is_peft_available, "peft")
require_timm = _require_import(_imports.is_timm_available, "timm")
require_torchvision = _require_import(_imports.is_torchvision_available, "torchvision")
require_torchdata_stateful_dataloader = _require_import(
    _imports.is_torchdata_stateful_dataloader_available, "torchdata StatefulDataLoader"
)
require_matplotlib = _require_import(_imports.is_matplotlib_available, "matplotlib")
require_schedulefree = _require_import(_imports.is_schedulefree_available, "schedulefree")
require_lomo = _require_import(_imports.is_lomo_available, "lomo-optim")
require_bf16 = _require_import(_imports.is_bf16_available, "bf16 support")
require_fp16 = _require_import(_imports.is_fp16_available, "hardware fp16")
require_fp8 = _require_import(_imports.is_fp8_available, "float8 dtypes")
require_pippy = _require_import(_imports.is_pippy_available, "pipeline inference")
require_import_timer = _require_import(_imports.is_import_timer_available, "import timer")


require_multi_gpu = _require_import(
    _imports.is_multi_gpu_available, "multiple CUDA devices"
)  # reference semantics: CUDA count — use require_multi_device for mesh tests


def require_huggingface_suite(test_case):
    ok = _imports.is_transformers_available() and _imports.is_datasets_available()
    return unittest.skipUnless(ok, "test requires transformers + datasets")(test_case)


# ---------------------------------------------------------------------------
# launcher plumbing (reference testing.py:107, 724, 755)
# ---------------------------------------------------------------------------


def get_unique_port() -> int:
    """A free TCP port, pytest-xdist safe (reference
    ``get_torch_dist_unique_port``)."""
    base = 29500
    worker = os.environ.get("PYTEST_XDIST_WORKER", "gw0")
    try:
        offset = int(worker.replace("gw", ""))
    except ValueError:
        offset = 0
    port = base + offset
    # Verify it's actually free; walk forward otherwise.
    for candidate in range(port, port + 100):
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            try:
                s.bind(("127.0.0.1", candidate))
                return candidate
            except OSError:
                continue
    raise RuntimeError(f"no free port in [{port}, {port + 100})")


get_torch_dist_unique_port = get_unique_port  # reference-name alias


def get_launch_command(num_processes: int = 1, num_machines: int = 1, **kwargs) -> list[str]:
    """Command prefix invoking the package launcher (reference
    ``get_launch_command``)."""
    cmd = [
        sys.executable,
        "-m",
        "accelerate_tpu.commands.accelerate_cli",
        "launch",
        f"--num_processes={num_processes}",
        f"--num_machines={num_machines}",
        f"--main_process_port={get_unique_port()}",
    ]
    for k, v in kwargs.items():
        if v is True:
            cmd.append(f"--{k}")
        elif v is not False and v is not None:
            cmd.append(f"--{k}={v}")
    return cmd


class SubprocessCallException(Exception):
    pass


class _RunOutput:
    def __init__(self, returncode, stdout, stderr):
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr


async def _stream_subprocess(cmd, env=None, timeout=None, echo=False) -> _RunOutput:
    p = await asyncio.create_subprocess_exec(
        cmd[0],
        *cmd[1:],
        env=env,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.PIPE,
    )
    out_lines: list[str] = []
    err_lines: list[str] = []

    async def tee(stream, sink, label):
        while True:
            line = await stream.readline()
            if not line:
                break
            text = line.decode(errors="replace")
            sink.append(text)
            if echo:
                print(f"[{label}] {text}", end="", file=sys.stderr)

    try:
        await asyncio.wait_for(
            asyncio.gather(
                tee(p.stdout, out_lines, "stdout"),
                tee(p.stderr, err_lines, "stderr"),
                p.wait(),
            ),
            timeout=timeout,
        )
    except asyncio.TimeoutError:
        p.kill()
        await p.wait()
        raise SubprocessCallException(
            f"command {' '.join(cmd)} timed out after {timeout}s\n"
            f"stdout: {''.join(out_lines)}\nstderr: {''.join(err_lines)}"
        )
    return _RunOutput(p.returncode, "".join(out_lines), "".join(err_lines))


def execute_subprocess_async(cmd: list[str], env=None, timeout: float = 300, echo: bool = False) -> _RunOutput:
    """Run a command with async stdout/stderr tee + timeout (reference
    ``execute_subprocess_async`` ``testing.py:724``); raises with full output
    on nonzero exit."""
    env = dict(os.environ if env is None else env)  # never mutate the caller's dict
    env.setdefault("PYTHONPATH", os.pathsep.join(p for p in sys.path if p))
    result = asyncio.run(_stream_subprocess(cmd, env=env, timeout=timeout, echo=echo))
    if result.returncode != 0:
        raise SubprocessCallException(
            f"command {' '.join(cmd)} failed with returncode {result.returncode}\n"
            f"stdout: {result.stdout}\nstderr: {result.stderr}"
        )
    return result


run_command = execute_subprocess_async  # reference-name alias


# ---------------------------------------------------------------------------
# test-case bases (reference testing.py:577, 610)
# ---------------------------------------------------------------------------


class TempDirTestCase(unittest.TestCase):
    """Per-class temp dir, wiped between tests (reference ``TempDirTestCase``);
    set ``clear_on_setup = False`` to keep files across tests in a class."""

    clear_on_setup = True

    @classmethod
    def setUpClass(cls):
        cls.tmpdir = tempfile.mkdtemp(prefix="atpu_test_")

    @classmethod
    def tearDownClass(cls):
        shutil.rmtree(cls.tmpdir, ignore_errors=True)

    def setUp(self):
        if self.clear_on_setup:
            for entry in os.listdir(self.tmpdir):
                path = os.path.join(self.tmpdir, entry)
                shutil.rmtree(path, ignore_errors=True) if os.path.isdir(path) else os.remove(path)


class AccelerateTestCase(unittest.TestCase):
    """Resets the three state singletons after each test so accelerators built
    in one test can't leak into the next (reference ``testing.py:610-621``)."""

    def tearDown(self):
        super().tearDown()
        from ..state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()


class MockingTestCase(unittest.TestCase):
    """Collects mock patchers and starts/stops them around each test
    (reference ``MockingTestCase``)."""

    def setUp(self):
        self._patchers = []

    def add_mocks(self, mocks):
        if not isinstance(mocks, (list, tuple)):
            mocks = [mocks]
        self._patchers.extend(mocks)
        for m in mocks:
            m.start()
            self.addCleanup(m.stop)


# ---------------------------------------------------------------------------
# assertion helpers
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def assert_exception(exception_class: type, msg: Optional[str] = None):
    """Assert the block raises ``exception_class`` (and optionally that ``msg``
    is in the text) — reference ``assert_exception``."""
    was_raised = False
    try:
        yield
    except Exception as e:
        was_raised = True
        if not isinstance(e, exception_class):
            raise AssertionError(f"Expected {exception_class.__name__}, got {type(e).__name__}: {e}")
        if msg is not None and msg not in str(e):
            raise AssertionError(f"Expected {msg!r} in {str(e)!r}")
    if not was_raised:
        raise AssertionError(f"{exception_class.__name__} was not raised")


def capture_call_output(func: Callable, *args, **kwargs) -> str:
    """Run ``func`` capturing stdout (reference ``capture_call_output``)."""
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        func(*args, **kwargs)
    return buf.getvalue()
