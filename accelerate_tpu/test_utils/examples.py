"""Example-sync checking helpers.

Parity target: reference ``test_utils/examples.py`` (145 LoC): keeps the
``by_feature`` scripts and the canonical/complete examples from drifting
apart.  The reference diffs extracted function bodies line-by-line; our
``by_feature`` scripts additionally import the canonical module through
``examples/by_feature/_base.py``, making most of the sync structural — these
helpers cover the remaining textual checks (and keep the reference's API for
migrated test suites).
"""

from __future__ import annotations

import ast

__all__ = [
    "get_function_contents_by_name",
    "clean_lines",
    "compare_against_test",
    "uses_base_loader",
]


def get_function_contents_by_name(lines: list, name: str) -> list:
    """Source lines of ``def name`` up to the next top-level marker (reference
    ``test_utils/examples.py:25``; accepts ``training_function`` or ``main``)."""
    if name not in ("training_function", "main"):
        raise ValueError(
            f"Incorrect function name passed: {name}, choose either 'main' or 'training_function'"
        )
    out, started = [], False
    for line in lines:
        if not started and f"def {name}" in line:
            started = True
            out.append(line)
            continue
        if started:
            if name == "training_function" and "def main" in line:
                return out
            if name == "main" and "if __name__" in line:
                return out
            out.append(line)
    if not out:
        # A missing function must FAIL the sync check, not diff as empty.
        raise ValueError(f"no `def {name}` found in the given source lines")
    return out


def clean_lines(lines: list) -> list:
    """Drop comments and blank lines (reference ``examples.py:51``)."""
    return [line for line in lines if not line.lstrip().startswith("#") and line != "\n"]


def compare_against_test(
    base_filename: str, feature_filename: str, parser_only: bool, secondary_filename: str = None
) -> list:
    """Lines the feature script ADDS relative to the base example (reference
    ``examples.py:62``): the diff of cleaned ``main``/``training_function``
    bodies.  ``secondary_filename`` removes lines already explained by a second
    base (e.g. the complete example)."""
    name = "main" if parser_only else "training_function"
    with open(base_filename) as f:
        base = clean_lines(get_function_contents_by_name(f.readlines(), name))
    with open(feature_filename) as f:
        feature = clean_lines(get_function_contents_by_name(f.readlines(), name))
    diff = [line for line in feature if line not in base]
    if secondary_filename is not None:
        with open(secondary_filename) as f:
            secondary = clean_lines(get_function_contents_by_name(f.readlines(), name))
        diff = [line for line in diff if line not in secondary]
    return diff


def uses_base_loader(feature_filename: str) -> bool:
    """True when a by_feature script routes through ``_base`` (our structural
    sync mechanism: the canonical example is imported, not copied)."""
    with open(feature_filename) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "_base":
            return True
        if isinstance(node, ast.Import) and any(a.name == "_base" for a in node.names):
            return True
    return False
