"""Tiny y=ax+b fixtures used by distributed correctness checks.

Parity: reference ``test_utils/training.py`` (RegressionModel/RegressionDataset) —
the oracle fixtures behind ``training_check`` (reference
``test_utils/scripts/test_script.py:454``).
"""

from __future__ import annotations

import numpy as np


class RegressionDataset:
    def __init__(self, a=2, b=3, length=64, seed=42):
        rng = np.random.default_rng(seed)
        self.length = length
        self.x = rng.normal(size=(length,)).astype(np.float32)
        self.y = (a * self.x + b + 0.1 * rng.normal(size=(length,))).astype(np.float32)

    def __len__(self):
        return self.length

    def __getitem__(self, i):
        return {"x": self.x[i], "y": self.y[i]}


def _torch():
    import torch

    return torch


class RegressionModel(_torch().nn.Module):
    """y = a*x + b with scalar parameters; loss computed externally (bridge-mode
    exercise)."""

    def __init__(self, a=0.0, b=0.0):
        torch = _torch()
        super().__init__()
        self.a = torch.nn.Parameter(torch.tensor(float(a)))
        self.b = torch.nn.Parameter(torch.tensor(float(b)))

    def forward(self, x):
        return x * self.a + self.b


class RegressionModelWithLoss(_torch().nn.Module):
    """Variant returning {'loss', 'logits'} like transformers models (fused-mode
    exercise)."""

    def __init__(self, a=0.0, b=0.0):
        torch = _torch()
        super().__init__()
        self.a = torch.nn.Parameter(torch.tensor(float(a)))
        self.b = torch.nn.Parameter(torch.tensor(float(b)))

    def forward(self, x, y):
        import torch.nn.functional as F

        pred = x * self.a + self.b
        return {"loss": F.mse_loss(pred, y), "logits": pred}


def regression_collate(samples):
    """Batch RegressionDataset samples into {'x','y'} float tensors — the one
    collate every distributed check shares."""
    import numpy as np

    torch = _torch()
    xs = np.stack([np.atleast_1d(s["x"]) for s in samples]).astype("float32")
    ys = np.stack([np.atleast_1d(s["y"]) for s in samples]).astype("float32")
    return {"x": torch.from_numpy(xs), "y": torch.from_numpy(ys)}
