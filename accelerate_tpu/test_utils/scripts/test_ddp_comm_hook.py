"""Launchable comm-hook check (reference
``test_utils/scripts/test_ddp_comm_hook.py``): train the regression fixture
under each gradient-communication hook ("no"/"fp16"/"bf16") and assert the
final weights agree — reduced-precision gradient STORAGE must not change
where training converges (bf16 holds ~3 decimal digits; the fixture's
gradients are O(1)).

Run standalone or through the launcher:
    accelerate-tpu launch -m accelerate_tpu.test_utils.scripts.test_ddp_comm_hook
"""

from __future__ import annotations

import numpy as np
import torch
from torch.utils.data import DataLoader


def _train(comm_hook: str) -> float:
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModelWithLoss
    from accelerate_tpu.utils import DistributedDataParallelKwargs, set_seed

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    accelerator = Accelerator(
        kwargs_handlers=[DistributedDataParallelKwargs(comm_hook=comm_hook)]
    )
    set_seed(42)

    def collate(items):
        return {
            "x": torch.stack([torch.as_tensor(i["x"], dtype=torch.float32) for i in items]),
            "y": torch.stack([torch.as_tensor(i["y"], dtype=torch.float32) for i in items]),
        }

    dl = DataLoader(list(RegressionDataset(length=64)), batch_size=16, collate_fn=collate)
    model = RegressionModelWithLoss()
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    model, opt, dl = accelerator.prepare(model, opt, dl)
    for _ in range(3):
        for batch in dl:
            out = model(x=batch["x"], y=batch["y"])
            accelerator.backward(out.loss)
            opt.step()
            opt.zero_grad()
    return float(np.asarray(model.params["a"]))


def main():
    results = {hook: _train(hook) for hook in ("no", "fp16", "bf16")}
    baseline = results["no"]
    for hook, value in results.items():
        assert abs(value - baseline) < 5e-2, (hook, value, baseline)
    from accelerate_tpu.state import PartialState

    PartialState().print(f"test_ddp_comm_hook: converged equally under {results}")


if __name__ == "__main__":
    main()
