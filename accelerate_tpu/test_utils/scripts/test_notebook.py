"""Launchable notebook_launcher check (reference
``test_utils/scripts/test_notebook.py``): the in-process launch path must run
the function with the env contract applied and restored (single-host direct
call).  The multi-process CPU form delegates to ``debug_launcher``, whose
real-cluster behavior is covered by ``tests/test_cli_launchers.py``.

Run:  python -m accelerate_tpu.test_utils.scripts.test_notebook
"""

from __future__ import annotations

import os


def _payload(expected_world: int):
    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == expected_world, (state.num_processes, expected_world)
    assert os.environ.get("ACCELERATE_MIXED_PRECISION") == "bf16"
    return state.process_index


def main():
    from accelerate_tpu.launchers import notebook_launcher

    prior = os.environ.get("ACCELERATE_MIXED_PRECISION")
    # Direct-call path (TPU host or num_processes<=1): env contract applied,
    # function runs in this process.
    result = notebook_launcher(_payload, args=(1,), num_processes=1, mixed_precision="bf16")
    assert result == 0, result
    # Env restored to whatever it was before the launch (may legitimately be
    # set when this script itself runs under the launcher).
    assert os.environ.get("ACCELERATE_MIXED_PRECISION") == prior
    print("test_notebook: direct-call path ok")


if __name__ == "__main__":
    main()
