"""Launchable collectives check (reference ``test_utils/scripts/test_ops.py``,
181 LoC): every pytree collective + the ACCELERATE_DEBUG_MODE shape verifier.

Run standalone or through the launcher:
    accelerate-tpu launch -m accelerate_tpu.test_utils.scripts.test_ops
"""

from __future__ import annotations

import numpy as np


def test_gather():
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import gather

    state = PartialState()
    x = np.full((2, 3), float(state.process_index))
    g = np.asarray(gather(x))
    assert g.shape == (2 * state.num_processes, 3), g.shape
    for rank in range(state.num_processes):
        assert (g[2 * rank : 2 * rank + 2] == rank).all()
    state.print("gather ok")


def test_gather_object():
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import gather_object

    state = PartialState()
    objs = gather_object([{"rank": state.process_index}])
    assert [o["rank"] for o in objs] == list(range(state.num_processes)), objs
    state.print("gather_object ok")


def test_broadcast():
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import broadcast

    state = PartialState()
    x = {"a": np.full(4, float(state.process_index)), "b": [np.arange(2) + state.process_index]}
    out = broadcast(x)
    assert (np.asarray(out["a"]) == 0).all()
    assert (np.asarray(out["b"][0]) == np.arange(2)).all()
    state.print("broadcast ok")


def test_reduce():
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import reduce

    state = PartialState()
    n = state.num_processes
    x = np.full(3, float(state.process_index + 1))
    total = np.asarray(reduce(x, reduction="sum"))
    assert (total == n * (n + 1) / 2).all(), total
    mean = np.asarray(reduce(x, reduction="mean"))
    assert np.allclose(mean, (n + 1) / 2), mean
    state.print("reduce ok")


def test_pad_across_processes():
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import pad_across_processes

    state = PartialState()
    x = np.ones((state.process_index + 1, 2))
    padded = np.asarray(pad_across_processes(x, dim=0))
    assert padded.shape == (state.num_processes, 2), padded.shape
    state.print("pad_across_processes ok")


def test_op_checker():
    """ACCELERATE_DEBUG_MODE shape verification (reference ``test_ops.py`` +
    ``utils/operations.py:350-411``)."""
    import os

    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import broadcast
    from accelerate_tpu.utils.operations import DistributedOperationException

    state = PartialState()
    if state.num_processes < 2:
        state.print("op checker skipped (single process)")
        return
    prior = os.environ.get("ACCELERATE_DEBUG_MODE")
    os.environ["ACCELERATE_DEBUG_MODE"] = "1"
    try:
        # Mismatched shapes across ranks must raise, not hang.
        bad = np.ones((1 + state.process_index,))
        raised = False
        try:
            broadcast(bad)
        except DistributedOperationException:
            raised = True
        assert raised, "debug mode did not catch the shape mismatch"
    finally:
        if prior is None:
            os.environ.pop("ACCELERATE_DEBUG_MODE", None)
        else:
            os.environ["ACCELERATE_DEBUG_MODE"] = prior
    state.print("op checker ok")


def main():
    from accelerate_tpu.state import PartialState

    state = PartialState()
    state.print(f"test_ops on {state.num_processes} process(es)")
    test_gather()
    test_gather_object()
    test_broadcast()
    test_reduce()
    test_pad_across_processes()
    test_op_checker()
    state.print("test_ops: success")


if __name__ == "__main__":
    main()
