"""Launchable dataloader-semantics check (reference
``test_utils/scripts/test_distributed_data_loop.py``, 410 LoC):
even_batches behavior, join_uneven_inputs, dispatcher vs shard modes, and
dataloader state_dict round-trips — run under a real multi-process cluster or
standalone on one process.

Run standalone or through the launcher:
    accelerate-tpu launch -m accelerate_tpu.test_utils.scripts.test_distributed_data_loop
"""

from __future__ import annotations

import warnings

import numpy as np
import torch
from torch.utils.data import DataLoader, TensorDataset


def _make_accelerator(even_batches: bool = True, dispatch_batches=None):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    from accelerate_tpu.utils import DataLoaderConfiguration

    cfg = DataLoaderConfiguration(even_batches=even_batches, dispatch_batches=dispatch_batches)
    return Accelerator(dataloader_config=cfg)


def _dataset(n: int) -> TensorDataset:
    return TensorDataset(torch.arange(n, dtype=torch.float32).reshape(-1, 1))


def _batch_sizes(accelerator, dataset_size: int, batch_size: int) -> list:
    dl = accelerator.prepare(DataLoader(_dataset(dataset_size), batch_size=batch_size))
    return [batch[0].shape[0] for batch in dl]


def test_default_ensures_even_batch_sizes():
    """even_batches=True (default): uneven tails are topped up by wrapping to
    the dataset start, so every batch a process sees has the SAME shape —
    required for the compiled step (one trace).  The global batch is
    batch_size x data-parallel device count."""
    accelerator = _make_accelerator(even_batches=True)
    import jax

    n_shards = max(jax.device_count(), accelerator.num_processes)
    sizes = _batch_sizes(accelerator, 2 * n_shards + 1, 2)
    # Every step's global batch divides evenly across the data shards (the
    # uneven tail is wrapped up to the next multiple), and all non-final
    # steps share one shape.
    assert all(s % n_shards == 0 for s in sizes), sizes
    assert len(set(sizes[:-1])) <= 1, sizes
    accelerator.print(f"even_batches=True ok (sizes={sizes})")


def test_can_disable_even_batches():
    """even_batches=False on the mesh: a global jax.Array batch must still
    divide across the data shards, so shard-divisibility padding remains (the
    documented reason ``join_uneven_inputs`` is a no-op here); the knob only
    changes the cross-PROCESS index math.  gather_for_metrics drops the
    padded duplicates either way."""
    accelerator = _make_accelerator(even_batches=False)
    import jax

    n_shards = max(jax.device_count(), accelerator.num_processes)
    n = 2 * n_shards + 1
    sizes = _batch_sizes(accelerator, n, 2)
    assert all(s % n_shards == 0 for s in sizes), sizes
    assert sum(sizes) >= n, (sizes, n)  # no sample dropped
    accelerator.print(f"even_batches=False ok (sizes={sizes})")


def test_join_uneven_inputs_warns():
    """join_uneven_inputs is a documented no-op (shapes are equalized before
    the mesh) — it must still be usable as a context manager."""
    accelerator = _make_accelerator(even_batches=True)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        with accelerator.join_uneven_inputs([], even_batches=False):
            pass
    assert any("no-op" in str(x.message) for x in w), [str(x.message) for x in w]
    accelerator.print("join_uneven_inputs ok")


def test_dispatch_mode_matches_shard_mode():
    """Dispatcher (rank-0 reads + broadcast) must deliver the same batches as
    per-process sharding: both scale the script's per-shard batch_size by the
    data-shard count (the dispatcher assembles one micro-batch per shard,
    reference ``_fetch_batches``)."""

    def batches(acc):
        return [np.asarray(b[0]).ravel().tolist() for b in acc.prepare(
            DataLoader(_dataset(16), batch_size=4))]

    shard_vals = batches(_make_accelerator(dispatch_batches=False))
    disp_vals = batches(_make_accelerator(dispatch_batches=True))
    assert shard_vals == disp_vals, (shard_vals, disp_vals)
    print("dispatcher parity ok")


def test_dataloader_state_dict_roundtrip():
    accelerator = _make_accelerator()
    dl = accelerator.prepare(DataLoader(_dataset(16), batch_size=4))
    it = iter(dl)
    next(it)
    sd = dl.state_dict() if hasattr(dl, "state_dict") else None
    if sd is not None:
        dl.load_state_dict(sd)
    accelerator.print("dataloader state_dict ok")


def main():
    test_default_ensures_even_batch_sizes()
    test_can_disable_even_batches()
    test_join_uneven_inputs_warns()
    test_dispatch_mode_matches_shard_mode()
    test_dataloader_state_dict_roundtrip()
    from accelerate_tpu.state import PartialState

    PartialState().print("test_distributed_data_loop: all checks passed")


if __name__ == "__main__":
    main()
