"""Launchable dataloader-semantics check (reference
``test_utils/scripts/test_distributed_data_loop.py``, 410 LoC):
even_batches behavior, join_uneven_inputs, dispatcher vs shard modes, and
dataloader state_dict round-trips — run under a real multi-process cluster or
standalone on one process.

Run standalone or through the launcher:
    accelerate-tpu launch -m accelerate_tpu.test_utils.scripts.test_distributed_data_loop
"""

from __future__ import annotations

import warnings

import numpy as np
import torch
from torch.utils.data import DataLoader, TensorDataset


def _make_accelerator(even_batches: bool = True, dispatch_batches=None):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    from accelerate_tpu.utils import DataLoaderConfiguration

    cfg = DataLoaderConfiguration(even_batches=even_batches, dispatch_batches=dispatch_batches)
    return Accelerator(dataloader_config=cfg)


def _dataset(n: int) -> TensorDataset:
    return TensorDataset(torch.arange(n, dtype=torch.float32).reshape(-1, 1))


def _batch_sizes(accelerator, dataset_size: int, batch_size: int) -> list:
    dl = accelerator.prepare(DataLoader(_dataset(dataset_size), batch_size=batch_size))
    return [batch[0].shape[0] for batch in dl]


def _verify_batch_sizes(accelerator, dataset_size, batch_size, expected_p0, expected_p1):
    """Reference :100 ``verify_dataloader_batch_sizes`` — per-process batch
    size lists must match exactly."""
    sizes = _batch_sizes(accelerator, dataset_size, batch_size)
    if accelerator.process_index == 0:
        assert sizes == expected_p0, (sizes, expected_p0)
    elif accelerator.process_index == 1:
        assert sizes == expected_p1, (sizes, expected_p1)


def test_default_ensures_even_batch_sizes():
    """even_batches=True (default): uneven tails are topped up by wrapping to
    the dataset start, so every batch a process sees has the SAME shape —
    required for the compiled step (one trace).  On a 2-process cluster the
    per-process size lists are reference-exact (reference :120)."""
    accelerator = _make_accelerator(even_batches=True)
    import jax

    if accelerator.num_processes == 2:
        _verify_batch_sizes(accelerator, 3, 1, [1, 1], [1, 1])
        _verify_batch_sizes(accelerator, 7, 2, [2, 2], [2, 2])
        accelerator.print("even_batches=True ok (reference-exact per-process sizes)")
        return
    n_shards = max(jax.device_count(), accelerator.num_processes)
    sizes = _batch_sizes(accelerator, 2 * n_shards + 1, 2)
    # Every step's global batch divides evenly across the data shards (the
    # uneven tail is wrapped up to the next multiple), and all non-final
    # steps share one shape.
    assert all(s % n_shards == 0 for s in sizes), sizes
    assert len(set(sizes[:-1])) <= 1, sizes
    accelerator.print(f"even_batches=True ok (sizes={sizes})")


def test_can_disable_even_batches():
    """even_batches=False: the cross-process index math stops topping up the
    tail — later ranks see genuinely smaller/fewer batches.  On a 2-process
    cluster the per-process size lists are reference-exact (reference :142:
    ds=3/bs=1 -> [1,1]/[1]; ds=7/bs=2 -> [2,2]/[2,1]).  Single-process,
    shard-divisibility padding remains (a global jax.Array must divide across
    local devices); gather_for_metrics drops pad duplicates either way."""
    accelerator = _make_accelerator(even_batches=False)
    import jax

    if accelerator.num_processes == 2:
        _verify_batch_sizes(accelerator, 3, 1, [1, 1], [1])
        _verify_batch_sizes(accelerator, 7, 2, [2, 2], [2, 1])
        accelerator.print("even_batches=False ok (reference-exact per-process sizes)")
        return
    n_shards = max(jax.device_count(), accelerator.num_processes)
    n = 2 * n_shards + 1
    sizes = _batch_sizes(accelerator, n, 2)
    assert all(s % n_shards == 0 for s in sizes), sizes
    assert sum(sizes) >= n, (sizes, n)  # no sample dropped
    accelerator.print(f"even_batches=False ok (sizes={sizes})")


def test_join_uneven_inputs_warns():
    """join_uneven_inputs is a documented no-op (shapes are equalized before
    the mesh) — it must still be usable as a context manager."""
    accelerator = _make_accelerator(even_batches=True)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        with accelerator.join_uneven_inputs([], even_batches=False):
            pass
    assert any("no-op" in str(x.message) for x in w), [str(x.message) for x in w]
    accelerator.print("join_uneven_inputs ok")


def test_dispatch_mode_matches_shard_mode():
    """Dispatcher (rank-0 reads + broadcast) must deliver the same batches as
    per-process sharding: both scale the script's per-shard batch_size by the
    data-shard count (the dispatcher assembles one micro-batch per shard,
    reference ``_fetch_batches``).  The dataset divides the global batch so no
    even_batches wraparound is in play (only shard mode wraps — see
    test_small_dataset_wraps_to_full_batch)."""
    import jax

    n = 8 * jax.device_count()  # two full global batches at batch_size 4

    def batches(acc):
        return [np.asarray(b[0]).ravel().tolist() for b in acc.prepare(
            DataLoader(_dataset(n), batch_size=4))]

    shard_vals = batches(_make_accelerator(dispatch_batches=False))
    disp_vals = batches(_make_accelerator(dispatch_batches=True))
    assert shard_vals == disp_vals, (shard_vals, disp_vals)
    print("dispatcher parity ok")


def test_small_dataset_wraps_to_full_batch():
    """Reference BatchSamplerShard semantics: a dataset smaller than one
    global batch wraps around so the compiled step still sees ONE static
    shape (reference test table: range(2) with batch 3 -> [[0,1,0]])."""
    import jax

    global_batch = 4 * jax.device_count()
    accelerator = _make_accelerator(even_batches=True)
    dl = accelerator.prepare(DataLoader(_dataset(global_batch // 2), batch_size=4))
    sizes = [np.asarray(b[0]).shape[0] for b in dl]
    if accelerator.num_processes == 1:
        # Single-process tail parity (reference 'No change if no multiprocess',
        # data_loader.py:1190): no wraparound duplication — the batch is only
        # padded up to device-divisibility (pad rows deduped by
        # gather_for_metrics).
        n_dev = jax.device_count()
        assert all(s % n_dev == 0 for s in sizes), sizes
        assert sum(sizes) >= global_batch // 2, sizes
    else:
        # Multi-process: the dataset (half a global batch) wraps to ONE full
        # global batch; each process sees its local slice of it.
        assert sizes == [global_batch // accelerator.num_processes], sizes
    print(f"small-dataset wraparound ok (sizes={sizes})")


def test_join_can_override_even_batches():
    """Reference :195 — even_batches temporarily overridden inside the join
    context for prepared map-style loaders, restored on exit.  At a single
    process the context is a nullcontext (reference accelerator.py:1251 —
    DistributedType.NO skips the override entirely; the plain torch
    BatchSampler has no even_batches knob)."""
    accelerator = _make_accelerator(even_batches=True)
    train_dl = accelerator.prepare(DataLoader(_dataset(8), batch_size=2))
    valid_dl = accelerator.prepare(DataLoader(_dataset(8), batch_size=2))
    if accelerator.num_processes == 1:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with accelerator.join_uneven_inputs([], even_batches=False):
                assert not hasattr(train_dl.batch_sampler, "even_batches")
        accelerator.print("join override skipped (single process: nullcontext parity)")
        return
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with accelerator.join_uneven_inputs([], even_batches=False):
            assert train_dl.batch_sampler.even_batches is False
            assert valid_dl.batch_sampler.even_batches is False
    assert train_dl.batch_sampler.even_batches is True
    assert valid_dl.batch_sampler.even_batches is True
    accelerator.print("join override ok")


def test_join_mixed_type_dataloaders():
    """Reference :214/:237 — iterable loaders skip the override without
    AttributeError and raise the map-style-only warning (multi-process only;
    single process is a nullcontext, see test_join_can_override_even_batches)."""

    class Stream(torch.utils.data.IterableDataset):
        def __iter__(self):
            yield from (torch.tensor([float(i)]) for i in range(4))

    accelerator = _make_accelerator(even_batches=True)
    accelerator.prepare(DataLoader(Stream(), batch_size=1))
    batch_dl = accelerator.prepare(DataLoader(_dataset(4), batch_size=1))
    if accelerator.num_processes == 1:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with accelerator.join_uneven_inputs([], even_batches=False):
                pass
        accelerator.print("join mixed-type skipped (single process: nullcontext parity)")
        return
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        with accelerator.join_uneven_inputs([], even_batches=False):
            assert batch_dl.batch_sampler.even_batches is False
    assert any("map-style" in str(x.message) for x in w), [str(x.message) for x in w]
    assert batch_dl.batch_sampler.even_batches is True
    accelerator.print("join mixed-type ok")


def test_pickle_accelerator():
    """Reference :250 — the accelerator round-trips through pickle.  Same
    process: the restore re-attaches to the live Borg state (identity).  The
    REAL contract is the fresh-process restore: device/mesh are rebuilt from
    the pickled config over the new process's backend."""
    import pickle
    import subprocess
    import sys
    import tempfile

    accelerator = _make_accelerator()
    accelerator.prepare(DataLoader(_dataset(16), batch_size=4))
    restored = pickle.loads(pickle.dumps(accelerator))
    assert restored.state.__dict__ == accelerator.state.__dict__

    with tempfile.NamedTemporaryFile(suffix=".pkl", delete=False) as f:
        pickle.dump(accelerator, f)
        path = f.name
    probe = (
        "import os, pickle, jax; "
        "jax.config.update('jax_platforms', 'cpu'); "
        "from jax.extend.backend import clear_backends; clear_backends(); "
        f"acc = pickle.load(open({path!r}, 'rb')); "
        "assert acc.state.mesh is not None; "
        "assert acc.state.device is not None; "
        "print('mesh axes', dict(acc.state.mesh.shape))"
    )
    env = dict(__import__('os').environ)
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run([sys.executable, "-c", probe], capture_output=True, text=True, env=env)
    assert res.returncode == 0, res.stderr[-500:]
    accelerator.print("pickle ok (same-process + fresh-process restore)")


def test_gather_for_metrics_epoch_completeness():
    """Reference :266 ``test_data_loader`` — after a full epoch over a
    non-divisible dataset, ``gather_for_metrics`` must return every element
    exactly once: the even_batches wraparound duplicates are dropped, nothing
    is lost across processes."""
    accelerator = _make_accelerator(even_batches=True)
    import jax

    n_shards = max(jax.device_count(), accelerator.num_processes)
    n = 4 * n_shards + 3  # forces a padded/wrapped tail batch
    dl = accelerator.prepare(DataLoader(_dataset(n), batch_size=2))
    seen = []
    for batch in dl:
        gathered = accelerator.gather_for_metrics(batch[0])
        seen.extend(np.asarray(gathered).ravel().tolist())
    assert sorted(set(seen)) == [float(i) for i in range(n)], (sorted(set(seen)), n)
    assert len(seen) == n, (len(seen), n)  # duplicates deduped, nothing dropped
    accelerator.print(f"gather_for_metrics epoch completeness ok (n={n})")


def test_stateful_dataloader_mid_epoch_resume():
    """Reference :283 ``test_stateful_dataloader`` — state_dict mid-epoch on a
    prepared stateful loader; a fresh prepared loader restored from it yields
    exactly the remaining batches, identical content, on every process."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import DataLoaderConfiguration

    def make():
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        from accelerate_tpu import Accelerator

        cfg = DataLoaderConfiguration(use_stateful_dataloader=True)
        return Accelerator(dataloader_config=cfg)

    import jax

    accelerator = make()
    n_shards = max(jax.device_count(), accelerator.num_processes)
    n = 16 * n_shards

    dl = accelerator.prepare(DataLoader(_dataset(n), batch_size=2))
    sd = None
    untrained = []
    for step, batch in enumerate(dl):
        if step == 1:
            sd = dl.state_dict()
        if step >= 2:
            untrained.append(np.asarray(batch[0]))
    assert sd is not None and sd["batches_yielded"] == 2, sd

    accelerator2 = make()
    dl2 = accelerator2.prepare(DataLoader(_dataset(n), batch_size=2))
    dl2.load_state_dict(sd)
    resumed = [np.asarray(b[0]) for b in dl2]
    assert len(resumed) == len(untrained), (len(resumed), len(untrained))
    for b1, b2 in zip(untrained, resumed):
        assert np.array_equal(b1, b2), (b1, b2)
    accelerator2.print(f"stateful mid-epoch resume ok ({len(resumed)} batches replayed)")


def test_dataloader_state_dict_roundtrip():
    accelerator = _make_accelerator()
    dl = accelerator.prepare(DataLoader(_dataset(16), batch_size=4))
    it = iter(dl)
    next(it)
    sd = dl.state_dict() if hasattr(dl, "state_dict") else None
    if sd is not None:
        dl.load_state_dict(sd)
    accelerator.print("dataloader state_dict ok")


# Single roster shared by main() and the multi-process cluster worker
# (debug_workers.run_data_loop_suite) so the two paths cannot drift.
# test_pickle_accelerator spawns a fresh-process restore probe, which is
# single-process-only (inside a cluster each rank would spawn its own).
ALL_TESTS = (
    test_default_ensures_even_batch_sizes,
    test_can_disable_even_batches,
    test_join_uneven_inputs_warns,
    test_join_can_override_even_batches,
    test_join_mixed_type_dataloaders,
    test_dispatch_mode_matches_shard_mode,
    test_small_dataset_wraps_to_full_batch,
    test_gather_for_metrics_epoch_completeness,
    test_stateful_dataloader_mid_epoch_resume,
    test_dataloader_state_dict_roundtrip,
)


def run_all(skip=()):
    for test in ALL_TESTS:
        if test.__name__ not in skip:
            test()


def main():
    run_all()
    test_pickle_accelerator()
    from accelerate_tpu.state import PartialState

    PartialState().print("test_distributed_data_loop: all checks passed")


if __name__ == "__main__":
    main()
