"""Performance lower-bound regression (reference
``external_deps/test_performance.py:298``: train, evaluate, assert best metric
>= ``--performance_lower_bound``).

The reference trains BERT on GLUE/MRPC; this image has no network egress, so
the task is a self-contained paraphrase classifier on synthetic pairs (same
shape as ``examples/nlp_example.py``) — learnable to ~1.0 accuracy in one
epoch, giving the bound real teeth.

Run:
    accelerate-tpu launch -m accelerate_tpu.test_utils.scripts.external_deps.test_performance \
        -- --performance_lower_bound 0.9
"""

from __future__ import annotations

import argparse

import numpy as np

VOCAB, SEQ = 512, 32


def _make_pairs(n: int, seed: int):
    """Positives are shuffled copies of sentence A; negatives independent."""
    rng = np.random.default_rng(seed)
    a = rng.integers(1, VOCAB, (n, SEQ))
    labels = rng.integers(0, 2, n)
    b = np.where(
        labels[:, None] == 1, rng.permuted(a, axis=1), rng.integers(1, VOCAB, (n, SEQ))
    )
    return a, b, labels


def get_dataloaders(batch_size: int):
    import torch
    from torch.utils.data import DataLoader

    def to_samples(a, b, labels):
        return [
            {
                "input_ids_a": torch.tensor(a[i]),
                "input_ids_b": torch.tensor(b[i]),
                "labels": int(labels[i]),
            }
            for i in range(len(labels))
        ]

    def collate(samples):
        return {
            "input_ids_a": torch.stack([s["input_ids_a"] for s in samples]),
            "input_ids_b": torch.stack([s["input_ids_b"] for s in samples]),
            "labels": torch.tensor([s["labels"] for s in samples]),
        }

    train = to_samples(*_make_pairs(512, seed=0))
    val = to_samples(*_make_pairs(128, seed=1))
    return (
        DataLoader(train, shuffle=True, collate_fn=collate, batch_size=batch_size),
        DataLoader(val, shuffle=False, collate_fn=collate, batch_size=32),
    )


def make_model():
    import torch

    class PairClassifier(torch.nn.Module):
        def __init__(self, vocab=VOCAB, dim=64):
            super().__init__()
            self.embed = torch.nn.Embedding(vocab, dim)
            self.head = torch.nn.Sequential(
                torch.nn.Linear(4 * dim, 128), torch.nn.GELU(), torch.nn.Linear(128, 2)
            )

        def forward(self, input_ids_a, input_ids_b):
            a = self.embed(input_ids_a).mean(dim=1)
            b = self.embed(input_ids_b).mean(dim=1)
            feats = torch.cat([a, b, torch.abs(a - b), a * b], dim=1)
            return self.head(feats)

    return PairClassifier()


def _data_shards(accelerator) -> int:
    """Data-parallel shard count — the factor AcceleratedScheduler advances
    the single-process schedule by per step (scheduler.py:69-82)."""
    from accelerate_tpu.parallel.mesh import data_axes

    shards = 1
    for a in data_axes(accelerator.state.mesh):
        shards *= accelerator.state.mesh.shape[a]
    return max(shards, 1)


def training_function(args) -> float:
    import torch

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed

    set_seed(args.seed)
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    train_dl, eval_dl = get_dataloaders(batch_size=args.batch_size)
    model = make_model()
    optimizer = torch.optim.AdamW(model.parameters(), lr=args.lr)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optimizer, train_dl, eval_dl
    )
    # Linear decay to exactly zero over the run (reference uses
    # get_linear_schedule_with_warmup with num_warmup_steps=0 and asserts the
    # lr after the FIRST optimizer step and lr == 0 at the end,
    # external_deps/test_performance.py:176-225).
    shards = _data_shards(accelerator)
    total_sched_steps = len(train_dl) * args.num_epochs * shards
    raw_sched = torch.optim.lr_scheduler.LambdaLR(
        optimizer.torch_optimizer, lambda step: max(0.0, 1.0 - step / total_sched_steps)
    )
    lr_scheduler = accelerator.prepare(raw_sched)

    best = 0.0
    first_step_checked = False
    for epoch in range(args.num_epochs):
        model.train()
        for batch in train_dl:
            labels = batch.pop("labels")
            logits = model(**batch)
            loss = torch.nn.functional.cross_entropy(logits, labels)
            accelerator.backward(loss)
            optimizer.step()
            lr_scheduler.step()
            optimizer.zero_grad()
            if not first_step_checked:
                first_step_checked = True
                expected = args.lr * max(0.0, 1.0 - shards / total_sched_steps)
                got = lr_scheduler.get_last_lr()[0]
                assert abs(got - expected) < 1e-12, (
                    f"Wrong lr after first optimizer step: got {got}, expected {expected} "
                    f"(shards={shards}, total={total_sched_steps})"
                )
        model.eval()
        correct = total = 0
        for batch in eval_dl:
            labels = batch.pop("labels")
            with torch.no_grad():
                logits = model(**batch)
            preds = logits.argmax(dim=-1)
            preds, labels = accelerator.gather_for_metrics((preds, labels))
            correct += int((preds == labels).sum())
            total += int(labels.numel())
        acc = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: accuracy {acc:.3f}")
        best = max(best, acc)

    # Reference :221 — the schedule decayed to exactly zero.
    assert lr_scheduler.get_last_lr()[0] == 0, (
        f"Wrong lr at end of training: got {lr_scheduler.get_last_lr()[0]}, expected 0"
    )

    if args.performance_lower_bound is not None:
        assert args.performance_lower_bound <= best, (
            f"Best performance metric {best} is lower than the lower bound "
            f"{args.performance_lower_bound}"
        )

    if args.output_dir is not None:
        # Reference :232-244 — wait_for_everyone + save; the safetensors
        # weights file must exist afterwards.
        import os

        accelerator.wait_for_everyone()
        accelerator.save_model(accelerator.unwrap_model(model), args.output_dir)
        assert os.path.exists(os.path.join(args.output_dir, "model.safetensors")), (
            f"model.safetensors missing from {args.output_dir}"
        )
    accelerator.end_training()
    return best


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--performance_lower_bound", type=float, default=None)
    parser.add_argument("--output_dir", type=str, default=None)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--mixed_precision", type=str, default="no")
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
