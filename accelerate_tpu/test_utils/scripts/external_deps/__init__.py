"""Bound-enforcing regression scripts (reference
``test_utils/scripts/external_deps/`` — there they need transformers/datasets;
here they are self-contained synthetic tasks, same oracles)."""
