"""Peak-memory ceiling regression (reference
``external_deps/test_peak_memory_usage.py:314``: per-epoch ``TorchTracemalloc``
tracking of begin/end/peak memory, asserting each epoch's train peak <=
``--peak_memory_upper_bound_mb``).

TPU-native measurement: ``device.memory_stats()`` — the XLA allocator's
``bytes_in_use`` / ``peak_bytes_in_use`` in HBM, the direct analog of the
reference's ``torch.cuda.memory_allocated`` / ``max_memory_allocated``.  Host
memory is tracked alongside via ``tracemalloc`` + RSS, like the reference's
cpu counters.  On backends without allocator stats (virtual CPU mesh) the
device numbers fall back to the RSS high-water mark so the script stays
launchable everywhere; the bound only has HBM meaning on a real chip.

Run:
    accelerate-tpu launch -m accelerate_tpu.test_utils.scripts.external_deps.test_peak_memory_usage \
        -- --peak_memory_upper_bound_mb 2000
"""

from __future__ import annotations

import argparse
import gc
import tracemalloc


def b2mb(x: float) -> float:
    """Bytes to megabytes (reference :42)."""
    return round(x / 2**20, 2)


def _device_bytes() -> tuple[float, float, str]:
    """(bytes_in_use, peak_bytes_in_use, source)."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "peak_bytes_in_use" in stats:
            return (
                float(stats.get("bytes_in_use", 0)),
                float(stats["peak_bytes_in_use"]),
                "device",
            )
    except Exception:
        pass
    import resource

    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024.0
    return rss, rss, "ru_maxrss"


class DeviceTracemalloc:
    """Reference ``TorchTracemalloc`` (:48-113) rebuilt on XLA allocator
    stats: records device begin/used/peaked and host begin/used/peaked for
    the enclosed block."""

    def __enter__(self):
        gc.collect()
        self.device_begin, self.device_peak_begin, self.source = _device_bytes()
        tracemalloc.start()
        self.cpu_begin = tracemalloc.get_traced_memory()[0]
        return self

    def __exit__(self, *exc):
        gc.collect()
        self.device_end, device_peak_end, _ = _device_bytes()
        self.used = b2mb(self.device_end - self.device_begin)
        # XLA's peak_bytes_in_use is a process-lifetime high-water mark with
        # no reset API (torch.cuda has reset_peak_memory_stats; XLA doesn't).
        # Attribute a peak to THIS block only if the mark moved inside it;
        # otherwise this block stayed under an earlier peak and contributes 0.
        if device_peak_end > self.device_peak_begin:
            self.peaked = b2mb(device_peak_end - self.device_begin)
        else:
            self.peaked = 0.0
        # Lifetime high-water mark — the ceiling assert uses this so a spike
        # BEFORE the first tracked block (e.g. during prepare/opt-state init)
        # can't slip under the bound.
        self.lifetime_peak = b2mb(device_peak_end)
        cpu_now, cpu_peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        self.cpu_used = b2mb(cpu_now - self.cpu_begin)
        self.cpu_peaked = b2mb(cpu_peak - self.cpu_begin)


def training_function(args) -> dict:
    import torch

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed

    from .test_performance import get_dataloaders, make_model

    set_seed(args.seed)
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    train_dl, _ = get_dataloaders(batch_size=args.batch_size)
    model = make_model()
    optimizer = torch.optim.AdamW(model.parameters(), lr=args.lr)
    model, optimizer, train_dl = accelerator.prepare(model, optimizer, train_dl)

    train_total_peak_memory = {}
    for epoch in range(args.num_epochs):
        model.train()
        with DeviceTracemalloc() as tracemalloc_ctx:
            for step, batch in enumerate(train_dl):
                if args.max_steps is not None and step >= args.max_steps:
                    break
                labels = batch.pop("labels")
                logits = model(**batch)
                loss = torch.nn.functional.cross_entropy(logits, labels)
                accelerator.backward(loss)
                optimizer.step()
                optimizer.zero_grad()
        # Reference :243-256 — print the full begin/used/peaked ledger.
        accelerator.print(f"epoch {epoch}: memory source {tracemalloc_ctx.source}")
        accelerator.print(f"Memory before entering the train : {b2mb(tracemalloc_ctx.device_begin)}")
        accelerator.print(f"Memory consumed at the end of the train (end-begin): {tracemalloc_ctx.used}")
        accelerator.print(f"Peak Memory consumed during the train (max-begin): {tracemalloc_ctx.peaked}")
        # The bound is enforced on the LIFETIME high-water mark (prepare-time
        # spikes count); the epoch-local 'peaked' above is attribution only.
        total = tracemalloc_ctx.lifetime_peak
        accelerator.print(f"Total Peak Memory consumed during the train (max): {total}")
        accelerator.print(
            f"CPU Memory consumed (end-begin): {tracemalloc_ctx.cpu_used}; "
            f"peak (max-begin): {tracemalloc_ctx.cpu_peaked}"
        )
        train_total_peak_memory[f"epoch-{epoch}"] = total
        if args.peak_memory_upper_bound_mb is not None:
            assert train_total_peak_memory[f"epoch-{epoch}"] <= args.peak_memory_upper_bound_mb, (
                f"Peak memory {train_total_peak_memory[f'epoch-{epoch}']:.1f} MB "
                f"({tracemalloc_ctx.source}) exceeds the ceiling "
                f"{args.peak_memory_upper_bound_mb} MB in epoch {epoch}"
            )
    accelerator.end_training()
    return train_total_peak_memory


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--peak_memory_upper_bound_mb", type=float, default=None)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--max_steps", type=int, default=16)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--mixed_precision", type=str, default="no")
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
