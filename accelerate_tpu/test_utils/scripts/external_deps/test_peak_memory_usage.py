"""Peak-memory ceiling regression (reference
``external_deps/test_peak_memory_usage.py:314``: train one epoch, assert peak
memory <= ``--peak_memory_upper_bound_mb``).

TPU-native measurement: ``device.memory_stats()['peak_bytes_in_use']`` — the
XLA allocator's high-water mark in HBM, the direct analog of the reference's
``torch.cuda.max_memory_allocated``.  On backends without allocator stats
(virtual CPU mesh) it falls back to the process RSS high-water mark
(``ru_maxrss``), so the script is launchable everywhere; the bound only has
HBM meaning on a real chip.

Run:
    accelerate-tpu launch -m accelerate_tpu.test_utils.scripts.external_deps.test_peak_memory_usage \
        -- --peak_memory_upper_bound_mb 2000
"""

from __future__ import annotations

import argparse


def measure_peak_mb() -> tuple[float, str]:
    """(peak_mb, source): device allocator high-water mark, else process RSS."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "peak_bytes_in_use" in stats:
            return stats["peak_bytes_in_use"] / 2**20, "device.peak_bytes_in_use"
    except Exception:
        pass
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2**10, "ru_maxrss"


def training_function(args) -> float:
    import torch

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed

    from .test_performance import get_dataloaders, make_model

    set_seed(args.seed)
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    train_dl, _ = get_dataloaders(batch_size=args.batch_size)
    model = make_model()
    optimizer = torch.optim.AdamW(model.parameters(), lr=args.lr)
    model, optimizer, train_dl = accelerator.prepare(model, optimizer, train_dl)

    model.train()
    for step, batch in enumerate(train_dl):
        if step >= args.max_steps:
            break
        labels = batch.pop("labels")
        logits = model(**batch)
        loss = torch.nn.functional.cross_entropy(logits, labels)
        accelerator.backward(loss)
        optimizer.step()
        optimizer.zero_grad()

    peak_mb, source = measure_peak_mb()
    accelerator.print(f"peak memory: {peak_mb:.1f} MB ({source})")
    if args.peak_memory_upper_bound_mb is not None:
        assert peak_mb <= args.peak_memory_upper_bound_mb, (
            f"Peak memory {peak_mb:.1f} MB ({source}) exceeds the ceiling "
            f"{args.peak_memory_upper_bound_mb} MB"
        )
    accelerator.end_training()
    return peak_mb


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--peak_memory_upper_bound_mb", type=float, default=None)
    parser.add_argument("--max_steps", type=int, default=16)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--mixed_precision", type=str, default="no")
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
