"""Pipelined-inference regression (reference
``external_deps/test_pippy.py:117``).

The reference splits BERT/GPT2 across PiPPy stages and only asserts that the
last process produced output.  The native equivalent is STRONGER: it builds
the flagship llama model, pipelines it with ``prepare_pippy`` over a pp mesh
(GPipe ``lax.scan`` schedule, ``inference.py``), and asserts the pipelined
logits MATCH the unpipelined forward — stage splitting, microbatch chunking,
and the activation hand-off cannot silently corrupt the forward.

Runs on a virtual device mesh when the host exposes fewer devices than the pp
degree (same mechanism as the driver's multichip dryrun).
"""

from __future__ import annotations

import argparse
import os


def run(args) -> None:
    from accelerate_tpu.test_utils import ensure_virtual_devices

    n_devices = args.pp * (args.dp or 1)
    ensure_virtual_devices(n_devices)
    import jax

    if jax.device_count() < n_devices:
        jax.config.update("jax_platforms", "cpu")
        from jax.extend.backend import clear_backends

        clear_backends()

    import numpy as np

    from accelerate_tpu import ParallelismConfig
    from accelerate_tpu.inference import prepare_pippy
    from accelerate_tpu.models import llama
    from accelerate_tpu.state import AcceleratorState
    from accelerate_tpu.utils import set_seed

    # The mesh must cover every visible device; with --dp unset, the dp axis
    # absorbs whatever the host exposes beyond the pp degree.
    dp = args.dp or max(jax.device_count() // args.pp, 1)

    set_seed(42)
    state = AcceleratorState(
        parallelism_config=ParallelismConfig(dp=dp, pp=args.pp)
    )
    cfg = llama.LlamaConfig.tiny(num_layers=4)
    params = llama.init_params(cfg, jax.random.key(0))

    rng = np.random.default_rng(0)
    input_ids = jax.numpy.asarray(
        rng.integers(0, cfg.vocab_size, (args.batch_size, args.seq_len)).astype(np.int32)
    )

    # Dense oracle: the unpipelined forward on the same params/batch.
    dense_logits = np.asarray(
        jax.jit(lambda p, ids: llama.apply(p, ids, cfg))(params, input_ids),
        np.float32,
    )

    pipelined = prepare_pippy(params, cfg, num_chunks=args.num_chunks)
    pipe_logits = np.asarray(pipelined(input_ids), np.float32)

    assert pipe_logits.shape == dense_logits.shape, (
        f"pipelined output shape {pipe_logits.shape} != dense {dense_logits.shape}"
    )
    max_delta = float(np.max(np.abs(pipe_logits - dense_logits)))
    # bf16 compute: stage boundaries reorder no math, only hand activations
    # across the pp axis — deltas are pure rounding, structural errors are O(1).
    assert max_delta < 5e-2, (
        f"pipelined logits diverge from the dense forward: max |Δ|={max_delta:.3e}"
    )
    print(
        f"pippy OK: mesh={dict(state.mesh.shape)}, chunks={args.num_chunks}, "
        f"logits {pipe_logits.shape}, max |Δ| vs dense={max_delta:.2e}"
    )


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--pp", type=int, default=2)
    parser.add_argument("--dp", type=int, default=None)
    parser.add_argument("--num_chunks", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=4)
    parser.add_argument("--seq_len", type=int, default=32)
    run(parser.parse_args())


if __name__ == "__main__":
    main()
