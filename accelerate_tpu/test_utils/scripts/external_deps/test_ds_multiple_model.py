"""Multiple models under one Accelerator with DeepSpeed-dialect configs
(reference ``external_deps/test_ds_multiple_model.py:332``).

Reference scenarios, same oracles, native engines:

1. **train + frozen inference model**: a trainable classifier plus a frozen
   "noise" model whose output scales the loss.  The noise model's parameter
   must be bit-identical after training (it has no optimizer), training must
   still clear an accuracy bound through the scaled loss, and the
   training/inference plugins must swap via ``select()`` /
   ``get_active_deepspeed_plugin`` exactly like the reference's
   zero2-train/zero3-inference pairing.
2. **two models training simultaneously**: two classifiers, two optimizers,
   one accelerator.  Both must train (params move, bound cleared) and
   stepping one optimizer must not touch the other model's params
   (no cross-contamination).

The zero2/zero3 configs use "auto" fields resolved by ``fill_auto`` at
prepare time, mirroring the reference's model_only ds_config jsons.
"""

from __future__ import annotations

import argparse

import numpy as np

from .test_performance import get_dataloaders, make_model


def _zero_config(stage: int) -> dict:
    return {
        "zero_optimization": {"stage": stage},
        "train_micro_batch_size_per_gpu": "auto",
        "gradient_accumulation_steps": "auto",
        "gradient_clipping": "auto",
    }


def _flat_params(model) -> np.ndarray:
    """Flatten a prepared model's parameters (jax arrays) or a torch module's
    tensors into one comparable vector."""
    import jax

    if hasattr(model, "params"):
        leaves = jax.tree.leaves(model.params)
        return np.concatenate([np.asarray(p, np.float32).ravel() for p in leaves])
    return np.concatenate(
        [p.detach().float().cpu().numpy().ravel() for p in model.parameters()]
    )


def _accuracy(accelerator, model, eval_dl) -> float:
    import torch

    model.eval()
    correct = total = 0
    for batch in eval_dl:
        labels = batch.pop("labels")
        with torch.no_grad():
            logits = model(**batch)
        preds = logits.argmax(dim=-1)
        preds, labels = accelerator.gather_for_metrics((preds, labels))
        correct += int((preds == labels).sum())
        total += int(labels.numel())
    return correct / max(total, 1)


def single_model_training(args) -> None:
    """Scenario 1: one model trains while a second, frozen model runs
    inference whose outputs shape the training loss (the reference's
    zero2-train / zero3-inference pairing, test_ds_multiple_model.py:107)."""
    import torch

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import set_seed
    from accelerate_tpu.utils.deepspeed import DeepSpeedPlugin, get_active_deepspeed_plugin

    set_seed(args.seed)
    train_plugin = DeepSpeedPlugin(hf_ds_config=_zero_config(2))
    inference_plugin = DeepSpeedPlugin(hf_ds_config=_zero_config(3))

    accelerator = Accelerator(deepspeed_plugin=train_plugin)
    assert get_active_deepspeed_plugin(accelerator.state) is train_plugin

    train_dl, eval_dl = get_dataloaders(batch_size=args.batch_size)
    student, teacher = make_model(), make_model()
    optimizer = torch.optim.AdamW(student.parameters(), lr=args.lr)
    student, optimizer, train_dl, eval_dl = accelerator.prepare(
        student, optimizer, train_dl, eval_dl
    )
    # The inference model is prepared WITHOUT an optimizer under the zero3
    # plugin (the reference swaps plugins per model via select()).
    inference_plugin.select()
    assert get_active_deepspeed_plugin() is inference_plugin
    teacher = accelerator.prepare(teacher)
    teacher_before = _flat_params(teacher)
    train_plugin.select()
    assert get_active_deepspeed_plugin() is train_plugin

    # Train the student on CE plus a small consistency term against the frozen
    # teacher's logits (computed under no_grad — pure inference).
    for _ in range(args.num_epochs):
        student.train()
        for batch in train_dl:
            labels = batch.pop("labels")
            with torch.no_grad():
                teacher_logits = teacher(**batch).detach()
            logits = student(**batch)
            loss = torch.nn.functional.cross_entropy(logits, labels)
            loss = loss + 0.05 * torch.nn.functional.mse_loss(logits, teacher_logits)
            accelerator.backward(loss)
            optimizer.step()
            optimizer.zero_grad()
    acc = _accuracy(accelerator, student, eval_dl)
    accelerator.print(f"scenario1 accuracy {acc:.3f}")
    assert acc >= args.performance_lower_bound, (
        f"scenario1: accuracy {acc} lower than the lower bound {args.performance_lower_bound}"
    )
    teacher_after = _flat_params(teacher)
    assert np.array_equal(teacher_before, teacher_after), (
        "scenario1: the frozen inference model's parameters changed during training"
    )
    accelerator.end_training()
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def multiple_model_training(args) -> None:
    """Scenario 2: two models, two optimizers, one accelerator."""
    import torch

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed
    from accelerate_tpu.utils.deepspeed import DeepSpeedPlugin

    set_seed(args.seed)
    accelerator = Accelerator(deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=_zero_config(2)))
    train_dl, eval_dl = get_dataloaders(batch_size=args.batch_size)
    model_a, model_b = make_model(), make_model()
    opt_a = torch.optim.AdamW(model_a.parameters(), lr=args.lr)
    opt_b = torch.optim.AdamW(model_b.parameters(), lr=args.lr)
    model_a, opt_a, model_b, opt_b, train_dl, eval_dl = accelerator.prepare(
        model_a, opt_a, model_b, opt_b, train_dl, eval_dl
    )

    a_start, b_start = _flat_params(model_a), _flat_params(model_b)

    # Step ONLY model A for one batch: B must be untouched (the reference's
    # independent-engine contract).
    batch = next(iter(train_dl))
    labels = batch.pop("labels")
    logits = model_a(**batch)
    accelerator.backward(torch.nn.functional.cross_entropy(logits, labels))
    opt_a.step()
    opt_a.zero_grad()
    assert not np.array_equal(a_start, _flat_params(model_a)), (
        "scenario2: stepping optimizer A did not update model A"
    )
    assert np.array_equal(b_start, _flat_params(model_b)), (
        "scenario2: stepping optimizer A leaked into model B"
    )

    # Now train both simultaneously; both must clear the bound.
    for _ in range(args.num_epochs):
        model_a.train(), model_b.train()
        for batch in train_dl:
            labels = batch.pop("labels")
            loss_a = torch.nn.functional.cross_entropy(model_a(**batch), labels)
            accelerator.backward(loss_a)
            opt_a.step()
            opt_a.zero_grad()
            loss_b = torch.nn.functional.cross_entropy(model_b(**batch), labels)
            accelerator.backward(loss_b)
            opt_b.step()
            opt_b.zero_grad()
    acc_a = _accuracy(accelerator, model_a, eval_dl)
    acc_b = _accuracy(accelerator, model_b, eval_dl)
    accelerator.print(f"scenario2 accuracies {acc_a:.3f} {acc_b:.3f}")
    for name, acc in (("A", acc_a), ("B", acc_b)):
        assert acc >= args.performance_lower_bound, (
            f"scenario2: model {name} accuracy {acc} lower than the lower bound "
            f"{args.performance_lower_bound}"
        )
    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--performance_lower_bound", type=float, default=0.9)
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument(
        "--scenario", choices=["single", "multiple", "both"], default="both"
    )
    args = parser.parse_args()
    if args.scenario in ("single", "both"):
        single_model_training(args)
    if args.scenario in ("multiple", "both"):
        multiple_model_training(args)


if __name__ == "__main__":
    main()
