"""ZeRO-3 init-integration regression (reference
``external_deps/test_zero3_integration.py:59``).

The reference proves that a user may bring up the distributed process group
THEMSELVES (``torch.distributed.init_process_group``) before handing control
to the framework with a ZeRO-3 config, and model construction still works.
Native equivalent: ``PartialState`` is created FIRST (owning the
``jax.distributed`` bring-up), then an ``Accelerator`` with a stage-3
DeepSpeed-dialect config must attach to that pre-existing state — not
re-initialize — and the dialect must land as the FULL_SHARD GSPMD mapping:

- zero_stage 3 -> sharding_strategy FULL_SHARD, zero3_init_flag on
  (``utils/deepspeed.py`` ``_ZERO_TO_STRATEGY``);
- "auto" config fields resolved by ``fill_auto`` at prepare time;
- prepared parameters ACTUALLY sharded over the mesh (device_set > 1 when
  devices allow), and one train step runs.
"""

from __future__ import annotations

import argparse


def run(args) -> None:
    import torch

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import set_seed
    from accelerate_tpu.utils.deepspeed import DeepSpeedPlugin, get_active_deepspeed_plugin

    # User-initialized distributed state, BEFORE the Accelerator exists
    # (reference init_torch_dist_then_launch_deepspeed, test_zero3_integration.py:29).
    state = PartialState()
    n_before = state.num_processes

    set_seed(42)
    ds_config = {
        "zero_optimization": {"stage": 3},
        "train_batch_size": "auto",
        "train_micro_batch_size_per_gpu": "auto",
        "gradient_accumulation_steps": "auto",
    }
    plugin = DeepSpeedPlugin(hf_ds_config=ds_config)
    accelerator = Accelerator(deepspeed_plugin=plugin)

    # Attached to the SAME process group, not a re-init.
    assert accelerator.num_processes == n_before, (
        f"Accelerator re-initialized the process group: {accelerator.num_processes} "
        f"!= {n_before}"
    )
    assert get_active_deepspeed_plugin(accelerator.state) is plugin
    assert plugin.zero_stage == 3
    assert plugin.zero3_init_flag, "stage 3 must enable zero3_init"
    assert plugin.sharding_strategy == "FULL_SHARD", (
        f"zero3 must map to FULL_SHARD, got {plugin.sharding_strategy}"
    )

    from .test_performance import get_dataloaders, make_model

    train_dl, _ = get_dataloaders(batch_size=args.batch_size)
    model = make_model()
    optimizer = torch.optim.AdamW(model.parameters(), lr=2e-3)
    model, optimizer, train_dl = accelerator.prepare(model, optimizer, train_dl)

    # fill_auto resolved the autos against the prepared loader.
    cfg = plugin.hf_ds_config
    micro = cfg.get_value("train_micro_batch_size_per_gpu")
    assert micro != "auto" and int(micro) > 0, f"auto micro-batch unresolved: {micro}"

    # Stage-3 semantics: parameters sharded over every device the mesh has.
    import jax

    n_dev = jax.device_count()
    embed = model.params["embed.weight"] if "embed.weight" in getattr(model, "params", {}) else None
    if embed is None:
        leaves = jax.tree.leaves(model.params)
        embed = max(leaves, key=lambda a: a.size)
    assert len(embed.sharding.device_set) == n_dev, (
        f"zero3/FULL_SHARD params must span all {n_dev} devices, got "
        f"{len(embed.sharding.device_set)}"
    )

    # One real step under the pre-initialized state.
    batch = next(iter(train_dl))
    labels = batch.pop("labels")
    loss = torch.nn.functional.cross_entropy(model(**batch), labels)
    accelerator.backward(loss)
    optimizer.step()
    optimizer.zero_grad()
    print(
        f"zero3 integration OK: processes={accelerator.num_processes}, "
        f"devices={n_dev}, strategy={plugin.sharding_strategy}, "
        f"micro_batch={micro}, loss={loss.item():.4f}"
    )


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--batch_size", type=int, default=16)
    run(parser.parse_args())


if __name__ == "__main__":
    main()
