"""``gather_for_metrics`` oracle vs single-process ground truth (reference
``external_deps/test_metrics.py:307``).

Contract under test: metrics computed from ``gather_for_metrics`` outputs on N
processes equal the single-process metric exactly — the dedup must drop the
even-batches padding (tail wraparound) and nothing else, for tensors, tensor
tuples, and non-tensor objects, on both map-style and dispatcher/iterable
paths.

Run:
    accelerate-tpu launch -m accelerate_tpu.test_utils.scripts.external_deps.test_metrics
"""

from __future__ import annotations


def _accuracy(preds, labels) -> float:
    import numpy as np

    return float((np.asarray(preds) == np.asarray(labels)).mean())


def test_metric_parity_uneven_tail(accelerator):
    """Dataset length not divisible by (batch x processes): gathered sample
    count equals the dataset length and the metric matches exactly."""
    import torch
    from torch.utils.data import DataLoader

    n = 77  # deliberately awkward vs batch 8 x N processes
    torch.manual_seed(0)
    labels = torch.randint(0, 2, (n,))
    # "Model": predicts label correctly except every 7th sample.
    preds = labels.clone()
    preds[::7] ^= 1
    baseline = _accuracy(preds, labels)

    ds = [{"pred": preds[i], "label": labels[i]} for i in range(n)]
    dl = accelerator.prepare(DataLoader(ds, batch_size=8))
    got_preds, got_labels = [], []
    for batch in dl:
        p, l = accelerator.gather_for_metrics((batch["pred"], batch["label"]))
        got_preds.append(p)
        got_labels.append(l)
    got_preds = torch.cat(got_preds)
    got_labels = torch.cat(got_labels)
    assert got_preds.shape[0] == n, (got_preds.shape, n)
    distributed = _accuracy(got_preds, got_labels)
    assert abs(distributed - baseline) < 1e-9, (distributed, baseline)
    accelerator.print(f"uneven-tail parity OK: accuracy {distributed:.4f} over {n}")


def test_metric_parity_iterable(accelerator):
    """Dispatcher path (iterable dataset): same count + parity contract."""
    import torch
    from torch.utils.data import DataLoader, IterableDataset

    n = 30

    class Stream(IterableDataset):
        def __iter__(self):
            for i in range(n):
                yield {"x": torch.tensor([float(i)])}

    dl = accelerator.prepare(DataLoader(Stream(), batch_size=4))
    seen = []
    for batch in dl:
        seen.append(accelerator.gather_for_metrics(batch["x"]))
    total = torch.cat(seen)
    assert total.shape[0] == n, (total.shape, n)
    expected = sum(range(n))
    assert float(total.sum()) == expected, (float(total.sum()), expected)
    accelerator.print(f"iterable parity OK: {n} samples, checksum {expected}")


def test_gather_non_tensor_objects(accelerator):
    """use_gather_object path: python objects survive the dedup."""
    from torch.utils.data import DataLoader

    n = 21
    ds = [{"tag": f"s{i}"} for i in range(n)]
    dl = accelerator.prepare(DataLoader(ds, batch_size=4, collate_fn=lambda b: [s["tag"] for s in b]))
    got = []
    for batch in dl:
        got.extend(accelerator.gather_for_metrics(batch, use_gather_object=True))
    assert len(got) == n, (len(got), n)
    assert sorted(got) == sorted(f"s{i}" for i in range(n)), got[:5]
    accelerator.print(f"object-gather parity OK: {n} objects")


def _f1(preds, labels) -> float:
    import numpy as np

    p, l = np.asarray(preds), np.asarray(labels)
    tp = float(((p == 1) & (l == 1)).sum())
    fp = float(((p == 1) & (l == 0)).sum())
    fn = float(((p == 0) & (l == 1)).sum())
    denom = 2 * tp + fp + fn
    return (2 * tp / denom) if denom else 1.0


def test_model_prediction_parity(dispatch_batches: bool, split_batches: bool):
    """Reference ``test_mrpc`` (:121-148): a real model evaluated through the
    prepared (dispatcher/split) pipeline must produce EXACTLY the
    single-process baseline metrics (accuracy and F1), for every
    (dispatch_batches, split_batches) combination."""
    import math

    import numpy as np
    import torch

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import DataLoaderConfiguration, set_seed

    from .test_performance import get_dataloaders, make_model

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    cfg = DataLoaderConfiguration(dispatch_batches=dispatch_batches, split_batches=split_batches)
    accelerator = Accelerator(dataloader_config=cfg)
    set_seed(7)
    train_dl_raw, eval_dl = get_dataloaders(batch_size=16)
    model = make_model()
    # Short plain-torch pretrain so predictions span both classes and F1 has
    # teeth (the reference evaluates a Hub-finetuned checkpoint).
    opt = torch.optim.AdamW(model.parameters(), lr=2e-3)
    model.train()
    for i, batch in enumerate(train_dl_raw):
        if i >= 3:
            break
        labels = batch.pop("labels")
        loss = torch.nn.functional.cross_entropy(model(**batch), labels)
        loss.backward()
        opt.step()
        opt.zero_grad()

    # Prepare the model ONCE; both the baseline and the distributed pass run
    # through it, so the comparison isolates the data-pipeline contract
    # (dispatcher/split/dedup) from backend numerics — a near-tie logit that
    # argmaxes differently between eager torch and XLA must not flake the
    # exact-parity assert.  (The reference compares two torch runs, where the
    # backends already match.)
    model.eval()
    _, eval_dl2 = get_dataloaders(batch_size=16)
    ddp_model, prepared_dl = accelerator.prepare(model, eval_dl2)

    # Baseline: the prepared model over the RAW (unprepared) dataloader.
    base_preds, base_labels = [], []
    for batch in eval_dl:
        labels = batch.pop("labels")
        with torch.no_grad():
            logits = ddp_model(**batch)
        base_preds.append(torch.as_tensor(np.asarray(logits)).argmax(dim=-1))
        base_labels.append(labels)
    baseline = {
        "accuracy": _accuracy(torch.cat(base_preds), torch.cat(base_labels)),
        "f1": _f1(torch.cat(base_preds), torch.cat(base_labels)),
    }
    # Both classes must appear or the F1 parity check is vacuous.
    assert len(torch.cat(base_preds).unique()) == 2, "degenerate predictions"

    # Distributed: same model through the prepared pipeline + gather_for_metrics.
    got_preds, got_labels = [], []
    for batch in prepared_dl:
        labels = batch.pop("labels")
        with torch.no_grad():
            logits = ddp_model(**batch)
        preds = torch.as_tensor(np.asarray(logits)).argmax(dim=-1)
        preds, labels = accelerator.gather_for_metrics((preds, labels))
        got_preds.append(torch.as_tensor(np.asarray(preds)))
        got_labels.append(torch.as_tensor(np.asarray(labels)))
    distributed = {
        "accuracy": _accuracy(torch.cat(got_preds), torch.cat(got_labels)),
        "f1": _f1(torch.cat(got_preds), torch.cat(got_labels)),
    }

    for key in ("accuracy", "f1"):
        assert math.isclose(baseline[key], distributed[key]), (
            f"Baseline and Distributed are not the same for key {key}:\n"
            f"\tBaseline: {baseline[key]}\n\tDistributed: {distributed[key]}\n"
            f"\t(dispatch_batches={dispatch_batches}, split_batches={split_batches})"
        )
    accelerator.print(
        f"prediction parity OK (dispatch={dispatch_batches}, split={split_batches}): "
        f"acc {distributed['accuracy']:.4f}, f1 {distributed['f1']:.4f}"
    )


def main():
    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    if accelerator.is_main_process:
        print("**Testing gather_for_metrics parity**")
    test_metric_parity_uneven_tail(accelerator)
    test_metric_parity_iterable(accelerator)
    test_gather_non_tensor_objects(accelerator)
    # Reference main() sweeps the (dispatch, split) matrix (:196-207).
    for dispatch_batches in (False, True):
        for split_batches in (False, True):
            test_model_prediction_parity(dispatch_batches, split_batches)
    accelerator.end_training()


if __name__ == "__main__":
    main()
