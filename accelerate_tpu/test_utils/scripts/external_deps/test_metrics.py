"""``gather_for_metrics`` oracle vs single-process ground truth (reference
``external_deps/test_metrics.py:307``).

Contract under test: metrics computed from ``gather_for_metrics`` outputs on N
processes equal the single-process metric exactly — the dedup must drop the
even-batches padding (tail wraparound) and nothing else, for tensors, tensor
tuples, and non-tensor objects, on both map-style and dispatcher/iterable
paths.

Run:
    accelerate-tpu launch -m accelerate_tpu.test_utils.scripts.external_deps.test_metrics
"""

from __future__ import annotations


def _accuracy(preds, labels) -> float:
    import numpy as np

    return float((np.asarray(preds) == np.asarray(labels)).mean())


def test_metric_parity_uneven_tail(accelerator):
    """Dataset length not divisible by (batch x processes): gathered sample
    count equals the dataset length and the metric matches exactly."""
    import torch
    from torch.utils.data import DataLoader

    n = 77  # deliberately awkward vs batch 8 x N processes
    torch.manual_seed(0)
    labels = torch.randint(0, 2, (n,))
    # "Model": predicts label correctly except every 7th sample.
    preds = labels.clone()
    preds[::7] ^= 1
    baseline = _accuracy(preds, labels)

    ds = [{"pred": preds[i], "label": labels[i]} for i in range(n)]
    dl = accelerator.prepare(DataLoader(ds, batch_size=8))
    got_preds, got_labels = [], []
    for batch in dl:
        p, l = accelerator.gather_for_metrics((batch["pred"], batch["label"]))
        got_preds.append(p)
        got_labels.append(l)
    got_preds = torch.cat(got_preds)
    got_labels = torch.cat(got_labels)
    assert got_preds.shape[0] == n, (got_preds.shape, n)
    distributed = _accuracy(got_preds, got_labels)
    assert abs(distributed - baseline) < 1e-9, (distributed, baseline)
    accelerator.print(f"uneven-tail parity OK: accuracy {distributed:.4f} over {n}")


def test_metric_parity_iterable(accelerator):
    """Dispatcher path (iterable dataset): same count + parity contract."""
    import torch
    from torch.utils.data import DataLoader, IterableDataset

    n = 30

    class Stream(IterableDataset):
        def __iter__(self):
            for i in range(n):
                yield {"x": torch.tensor([float(i)])}

    dl = accelerator.prepare(DataLoader(Stream(), batch_size=4))
    seen = []
    for batch in dl:
        seen.append(accelerator.gather_for_metrics(batch["x"]))
    total = torch.cat(seen)
    assert total.shape[0] == n, (total.shape, n)
    expected = sum(range(n))
    assert float(total.sum()) == expected, (float(total.sum()), expected)
    accelerator.print(f"iterable parity OK: {n} samples, checksum {expected}")


def test_gather_non_tensor_objects(accelerator):
    """use_gather_object path: python objects survive the dedup."""
    from torch.utils.data import DataLoader

    n = 21
    ds = [{"tag": f"s{i}"} for i in range(n)]
    dl = accelerator.prepare(DataLoader(ds, batch_size=4, collate_fn=lambda b: [s["tag"] for s in b]))
    got = []
    for batch in dl:
        got.extend(accelerator.gather_for_metrics(batch, use_gather_object=True))
    assert len(got) == n, (len(got), n)
    assert sorted(got) == sorted(f"s{i}" for i in range(n)), got[:5]
    accelerator.print(f"object-gather parity OK: {n} objects")


def main():
    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    if accelerator.is_main_process:
        print("**Testing gather_for_metrics parity**")
    test_metric_parity_uneven_tail(accelerator)
    test_metric_parity_iterable(accelerator)
    test_gather_non_tensor_objects(accelerator)
    accelerator.end_training()


if __name__ == "__main__":
    main()
