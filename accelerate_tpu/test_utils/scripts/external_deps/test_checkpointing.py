"""Launchable checkpoint-resume regression (reference
``external_deps/test_checkpointing.py:269``).

Reference flow: train epochs, ``save_state`` per epoch alongside a
``state_{epoch}.json`` recording (accuracy, scheduler lr, optimizer lr,
epoch); a second launch with ``--resume_from_checkpoint epoch_N`` must
``load_state``, re-evaluate, and ASSERT all four recorded values match —
a wrong optimizer/scheduler restore or a stale param tree fails loudly.

The reference trains BERT on GLUE/MRPC; with no network egress the task is
the same self-contained paraphrase classifier as ``test_performance``
(learnable to ~1.0, so resumed accuracy is a sharp oracle, not noise).

Run (two launches):
    accelerate-tpu launch -m ...external_deps.test_checkpointing -- \
        --output_dir /tmp/ckpt --partial_train_epoch 1
    accelerate-tpu launch -m ...external_deps.test_checkpointing -- \
        --output_dir /tmp/ckpt --resume_from_checkpoint /tmp/ckpt/epoch_0
"""

from __future__ import annotations

import argparse
import json
import os

from .test_performance import get_dataloaders, make_model


def evaluation_loop(accelerator, model, eval_dl) -> float:
    import torch

    model.eval()
    correct = total = 0
    for batch in eval_dl:
        labels = batch.pop("labels")
        with torch.no_grad():
            logits = model(**batch)
        preds = logits.argmax(dim=-1)
        preds, labels = accelerator.gather_for_metrics((preds, labels))
        correct += int((preds == labels).sum())
        total += int(labels.numel())
    return correct / max(total, 1)


def training_function(args) -> None:
    import torch

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed

    set_seed(args.seed)
    accelerator = Accelerator()
    train_dl, eval_dl = get_dataloaders(batch_size=args.batch_size)
    model = make_model()
    optimizer = torch.optim.AdamW(model.parameters(), lr=args.lr)
    # Linear decay: the lr CHANGES every epoch, so a resume that fails to
    # restore the scheduler/optimizer is caught by the lr asserts below.
    max_steps = len(train_dl) * args.num_epochs
    lr_scheduler = torch.optim.lr_scheduler.LambdaLR(
        optimizer, lambda step: max(0.1, 1.0 - step / max_steps)
    )
    model, optimizer, train_dl, eval_dl, lr_scheduler = accelerator.prepare(
        model, optimizer, train_dl, eval_dl, lr_scheduler
    )

    starting_epoch = 0
    ending_epoch = args.num_epochs
    if args.partial_train_epoch is not None:
        ending_epoch = args.partial_train_epoch

    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        epoch_string = args.resume_from_checkpoint.split("epoch_")[1]
        state_epoch_num = ""
        for char in epoch_string:
            if char.isdigit():
                state_epoch_num += char
            else:
                break
        starting_epoch = int(state_epoch_num) + 1
        accuracy = evaluation_loop(accelerator, model, eval_dl)
        accelerator.print("resumed checkpoint performance:", accuracy)
        accelerator.print("resumed checkpoint's scheduler's lr:", lr_scheduler.get_last_lr()[0])
        accelerator.print("resumed optimizer's lr:", optimizer.param_groups[0]["lr"])
        with open(os.path.join(args.output_dir, f"state_{starting_epoch - 1}.json")) as f:
            resumed = json.load(f)
        # Reference asserts (test_checkpointing.py:186-193), same oracles:
        assert resumed["accuracy"] == accuracy, (
            f"Accuracy mismatch, loading from checkpoint failed: "
            f"{resumed['accuracy']} != {accuracy}"
        )
        assert resumed["lr"] == lr_scheduler.get_last_lr()[0], (
            "Scheduler learning rate mismatch, loading from checkpoint failed"
        )
        assert resumed["optimizer_lr"] == optimizer.param_groups[0]["lr"], (
            "Optimizer learning rate mismatch, loading from checkpoint failed"
        )
        assert resumed["epoch"] == starting_epoch - 1, (
            "Epoch mismatch, loading from checkpoint failed"
        )
        accelerator.print("resume OK")
        return

    state = {}
    for epoch in range(starting_epoch, ending_epoch):
        model.train()
        for batch in train_dl:
            labels = batch.pop("labels")
            logits = model(**batch)
            loss = torch.nn.functional.cross_entropy(logits, labels)
            accelerator.backward(loss)
            optimizer.step()
            lr_scheduler.step()
            optimizer.zero_grad()
        output_dir = os.path.join(args.output_dir, f"epoch_{epoch}")
        accelerator.save_state(output_dir)
        state["accuracy"] = evaluation_loop(accelerator, model, eval_dl)
        state["lr"] = lr_scheduler.get_last_lr()[0]
        state["optimizer_lr"] = optimizer.param_groups[0]["lr"]
        state["epoch"] = epoch
        accelerator.print(f"epoch {epoch}:", state)
        accelerator.wait_for_everyone()
        if accelerator.is_main_process:
            with open(os.path.join(args.output_dir, f"state_{epoch}.json"), "w") as f:
                json.dump(state, f)
    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--output_dir", type=str, default=".")
    parser.add_argument("--resume_from_checkpoint", type=str, default=None)
    parser.add_argument("--partial_train_epoch", type=int, default=None)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--seed", type=int, default=42)
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
