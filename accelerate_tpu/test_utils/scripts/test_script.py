"""The ``accelerate-tpu test`` payload — end-to-end sanity of the core stack.

Parity target: reference ``test_utils/scripts/test_script.py`` (901 LoC; main at
819): RNG sync, dataloader preparation, ``training_check`` (distributed final
weights == single-process baseline), ``split_between_processes``, trigger flags.
"""

from __future__ import annotations

import numpy as np


def rng_sync_check():
    from accelerate_tpu.state import AcceleratorState
    from accelerate_tpu.utils import broadcast, set_seed
    from accelerate_tpu.utils.random import rng_registry, synchronize_rng_states

    state = AcceleratorState()
    set_seed(42 + state.process_index)
    synchronize_rng_states(["jax"])
    seeds = broadcast(np.array([rng_registry.initial_seed]))
    assert int(np.asarray(seeds)[0]) == 42, "RNG sync failed"
    if state.is_main_process:
        print("All rng are properly synched.")


def dl_preparation_check():
    import torch
    from torch.utils.data import DataLoader

    from accelerate_tpu.data_loader import prepare_data_loader
    from accelerate_tpu.state import AcceleratorState
    from accelerate_tpu.utils import gather

    state = AcceleratorState()
    length = 32 * state.num_devices
    dl = DataLoader(range(length), batch_size=8)
    dl = prepare_data_loader(dl, output_type="jax")
    result = []
    for batch in dl:
        result.append(gather(batch))
    result = np.concatenate([np.asarray(r).reshape(-1) for r in result])
    assert np.array_equal(np.sort(result), np.arange(length)), "Wrong dataloader sharding"
    if state.is_main_process:
        print("Non-shuffled dataloader passing.")


def training_check(use_seedable_sampler: bool = False):
    """Reference ``training_check`` (test_script.py:454-818) as a full matrix:
    a single-process torch-SGD baseline's final weights must be reproduced by
    EVERY dataloader configuration — {no-split, split_batches} x
    {dispatch_batches off, on} in fp32 (tight tolerance), then the
    mixed-precision rungs (bf16, fp8) within loose tolerance — and the whole
    sweep runs for both the sequential loader and the seedable-sampler
    shuffle (the caller invokes it twice, like the reference's main)."""
    import torch
    import torch.nn.functional as F
    from torch.utils.data import DataLoader

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.data_loader import SeedableRandomSampler
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.test_utils import RegressionDataset, RegressionModel
    from accelerate_tpu.utils.dataclasses import DataLoaderConfiguration

    batch_size = 16
    # Reference geometry: the baseline consumes the GLOBAL batch and the
    # dataset scales with the parallel degree (test_script.py:457-459).  In
    # the reference that degree is the process count; here the mesh's data
    # shards play that role — a non-split prepared loader feeds batch_size
    # PER SHARD, so the global batch is batch_size x shards.
    from accelerate_tpu.parallel.mesh import data_axes

    state0 = AcceleratorState()
    data_shards = 1
    for axis in data_axes(state0.mesh):
        data_shards *= state0.mesh.shape[axis]
    AcceleratorState._reset_state()
    length = batch_size * 4 * data_shards
    ds = RegressionDataset(length=length)
    samples = list(ds)

    def collate(items):
        return {
            "x": torch.tensor([s["x"] for s in items]),
            "y": torch.tensor([s["y"] for s in items]),
        }

    def epoch_orders(n_epochs):
        """Baseline iteration order per epoch: sequential, or the exact
        permutations the prepared loader's SeedableRandomSampler will draw
        (numpy rng seeded data_seed + epoch)."""
        if not use_seedable_sampler:
            return [list(range(length)) for _ in range(n_epochs)]
        sampler = SeedableRandomSampler(samples, initial_seed=42)
        return [list(iter(sampler)) for _ in range(n_epochs)]

    # Single-process torch baseline on the global batch.
    torch.manual_seed(0)
    model = RegressionModel()
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    global_bs = batch_size * data_shards
    for order in epoch_orders(3):
        for i in range(0, length, global_bs):
            batch = collate([samples[j] for j in order[i : i + global_bs]])
            opt.zero_grad()
            loss = F.mse_loss(model(batch["x"]), batch["y"])
            loss.backward()
            opt.step()
    base_a, base_b = model.a.detach().item(), model.b.detach().item()

    def make_dl(bs):
        if use_seedable_sampler:
            return DataLoader(samples, batch_size=bs, shuffle=True, collate_fn=collate)
        return DataLoader(samples, batch_size=bs, collate_fn=collate)

    def run_prepared(accelerator, bs, tol, label):
        model = RegressionModel()
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        model, opt, dl = accelerator.prepare(model, opt, make_dl(bs))
        for _ in range(3):
            for batch in dl:
                pred = model(batch["x"])
                loss = F.mse_loss(pred, batch["y"])
                accelerator.backward(loss)
                opt.step()
                opt.zero_grad()
        def scalar(x):
            # Multi-process clusters hold params as global arrays; the value
            # is replicated, so any addressable shard carries it (fetching
            # the global array cross-process is not possible).
            if hasattr(x, "addressable_shards"):
                return float(np.asarray(x.addressable_shards[0].data).reshape(-1)[0])
            return float(np.asarray(x).reshape(-1)[0])

        sd = model.state_dict()
        a, b = scalar(sd["a"]), scalar(sd["b"])
        assert abs(a - base_a) < tol and abs(b - base_b) < tol, (
            f"{label}: final weights ({a:.6f}, {b:.6f}) diverge from the "
            f"baseline ({base_a:.6f}, {base_b:.6f})"
        )
        if accelerator.is_main_process:
            print(f"Training matched the baseline: {label}.")

    def fresh():
        AcceleratorState._reset_state()
        GradientState._reset_state()

    import os

    sampler_tag = "seedable" if use_seedable_sampler else "sequential"
    # ACCELERATE_TEST_QUICK=1 trims to the two corner combos and skips the
    # precision rungs — the multi-process launcher smoke uses it so the
    # cluster run stays bounded (each prepared config recompiles per process).
    quick = os.environ.get("ACCELERATE_TEST_QUICK") == "1"
    combos = (
        ((False, False), (True, True))
        if quick
        else ((False, False), (False, True), (True, False), (True, True))
    )
    # fp32 matrix: split_batches x dispatch_batches, identical weights.
    for split, dispatch in combos:
        fresh()
        acc = Accelerator(
            dataloader_config=DataLoaderConfiguration(
                split_batches=split,
                dispatch_batches=dispatch,
                use_seedable_sampler=use_seedable_sampler,
                data_seed=42,
            )
        )
        # split mode consumes the loader at the global batch size
        # (reference test_script.py:498-501).
        run_prepared(
            acc,
            global_bs if split else batch_size,
            1e-3,
            f"{sampler_tag}/split={split}/dispatch={dispatch}",
        )

    # Precision rungs: bf16 compute and the native fp8 path must converge to
    # the same weights within mixed-precision rounding (reference's BF16/FP16
    # training checks; fp8 replaces the CUDA-only TE/MSAMP engines).
    for mp in () if quick else ("bf16", "fp8"):
        fresh()
        acc = Accelerator(
            mixed_precision=mp,
            dataloader_config=DataLoaderConfiguration(
                use_seedable_sampler=use_seedable_sampler, data_seed=42
            ),
        )
        run_prepared(acc, batch_size, 5e-2, f"{sampler_tag}/{mp}")
    fresh()


def split_between_processes_check():
    from accelerate_tpu.state import PartialState

    state = PartialState()
    data = list(range(10))
    with state.split_between_processes(data) as chunk:
        gathered_len = len(chunk) * state.num_processes
    if state.is_main_process:
        print("split_between_processes ok.")


def trigger_check():
    from accelerate_tpu.accelerator import Accelerator

    accelerator = Accelerator()
    assert not accelerator.check_trigger()
    accelerator.set_trigger()
    assert accelerator.check_trigger()
    if accelerator.is_main_process:
        print("Trigger flags ok.")


def main():
    from accelerate_tpu.accelerator import Accelerator

    accelerator = Accelerator()
    state = accelerator.state
    if state.is_main_process:
        print("**Initialization**")
        print(state)
    accelerator.state._reset_state()
    accelerator.gradient_state._reset_state()
    from accelerate_tpu.state import PartialState

    rng_sync_check()
    print("**DataLoader integration test**") if state.is_main_process else None
    dl_preparation_check()
    print("**Training integration test**") if state.is_main_process else None
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    training_check(use_seedable_sampler=False)
    training_check(use_seedable_sampler=True)
    split_between_processes_check()
    AcceleratorState._reset_state()
    GradientState._reset_state()
    trigger_check()
    print("Test is a success!")


if __name__ == "__main__":
    main()
