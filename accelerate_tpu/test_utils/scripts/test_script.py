"""The ``accelerate-tpu test`` payload — end-to-end sanity of the core stack.

Parity target: reference ``test_utils/scripts/test_script.py`` (901 LoC; main at
819): RNG sync, dataloader preparation, ``training_check`` (distributed final
weights == single-process baseline), ``split_between_processes``, trigger flags.
"""

from __future__ import annotations

import numpy as np


def rng_sync_check():
    from accelerate_tpu.state import AcceleratorState
    from accelerate_tpu.utils import broadcast, set_seed
    from accelerate_tpu.utils.random import rng_registry, synchronize_rng_states

    state = AcceleratorState()
    set_seed(42 + state.process_index)
    synchronize_rng_states(["jax"])
    seeds = broadcast(np.array([rng_registry.initial_seed]))
    assert int(np.asarray(seeds)[0]) == 42, "RNG sync failed"
    if state.is_main_process:
        print("All rng are properly synched.")


def dl_preparation_check():
    import torch
    from torch.utils.data import DataLoader

    from accelerate_tpu.data_loader import prepare_data_loader
    from accelerate_tpu.state import AcceleratorState
    from accelerate_tpu.utils import gather

    state = AcceleratorState()
    length = 32 * state.num_devices
    dl = DataLoader(range(length), batch_size=8)
    dl = prepare_data_loader(dl, output_type="jax")
    result = []
    for batch in dl:
        result.append(gather(batch))
    result = np.concatenate([np.asarray(r).reshape(-1) for r in result])
    assert np.array_equal(np.sort(result), np.arange(length)), "Wrong dataloader sharding"
    if state.is_main_process:
        print("Non-shuffled dataloader passing.")


def training_check():
    import torch
    import torch.nn.functional as F
    from torch.utils.data import DataLoader

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.test_utils import RegressionDataset, RegressionModel

    def collate(samples):
        return {
            "x": torch.tensor([s["x"] for s in samples]),
            "y": torch.tensor([s["y"] for s in samples]),
        }

    # Single-process torch baseline.
    torch.manual_seed(0)
    ds = RegressionDataset(length=64)
    dl = DataLoader(list(ds), batch_size=16, collate_fn=collate)
    model = RegressionModel()
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    for _ in range(3):
        for batch in dl:
            opt.zero_grad()
            loss = F.mse_loss(model(batch["x"]), batch["y"])
            loss.backward()
            opt.step()
    base_a, base_b = float(model.a), float(model.b)

    accelerator = Accelerator(split_batches=True)
    dl = DataLoader(list(ds), batch_size=16, collate_fn=collate)
    model = RegressionModel()
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    model, opt, dl = accelerator.prepare(model, opt, dl)
    for _ in range(3):
        for batch in dl:
            with accelerator.accumulate(model):
                pred = model(batch["x"])
                loss = F.mse_loss(pred, batch["y"])
                accelerator.backward(loss)
                opt.step()
                opt.zero_grad()
    sd = model.state_dict()
    a, b = float(np.asarray(sd["a"])), float(np.asarray(sd["b"]))
    assert abs(a - base_a) < 1e-3, f"a mismatch: {a} vs {base_a}"
    assert abs(b - base_b) < 1e-3, f"b mismatch: {b} vs {base_b}"
    if accelerator.is_main_process:
        print("Training yielded the same results on one process and the mesh.")


def split_between_processes_check():
    from accelerate_tpu.state import PartialState

    state = PartialState()
    data = list(range(10))
    with state.split_between_processes(data) as chunk:
        gathered_len = len(chunk) * state.num_processes
    if state.is_main_process:
        print("split_between_processes ok.")


def trigger_check():
    from accelerate_tpu.accelerator import Accelerator

    accelerator = Accelerator()
    assert not accelerator.check_trigger()
    accelerator.set_trigger()
    assert accelerator.check_trigger()
    if accelerator.is_main_process:
        print("Trigger flags ok.")


def main():
    from accelerate_tpu.accelerator import Accelerator

    accelerator = Accelerator()
    state = accelerator.state
    if state.is_main_process:
        print("**Initialization**")
        print(state)
    accelerator.state._reset_state()
    accelerator.gradient_state._reset_state()
    from accelerate_tpu.state import PartialState

    rng_sync_check()
    print("**DataLoader integration test**") if state.is_main_process else None
    dl_preparation_check()
    print("**Training integration test**") if state.is_main_process else None
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    training_check()
    split_between_processes_check()
    AcceleratorState._reset_state()
    GradientState._reset_state()
    trigger_check()
    print("Test is a success!")


if __name__ == "__main__":
    main()
