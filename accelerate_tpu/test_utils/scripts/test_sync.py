"""Launchable gradient-accumulation sync oracle (reference
``test_utils/scripts/test_sync.py``, 410 LoC; oracle at 29-43/207/248).

The contract under test: during accumulation (``accumulate()`` on non-sync
micro-steps) optimizer/scheduler steps are no-ops and gradients keep
accumulating; on the sync step one update fires whose gradient equals the mean
of the micro-batch gradients — byte-identical final weights to feeding the
concatenated batch once.

Run:
    accelerate-tpu launch -m accelerate_tpu.test_utils.scripts.test_sync
"""

from __future__ import annotations

import numpy as np


def _make_model_and_data(seed: int = 0):
    import torch

    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    torch.manual_seed(seed)
    model = RegressionModel()
    dataset = RegressionDataset(length=16, seed=seed)
    xs = np.stack([np.atleast_1d(s["x"]) for s in dataset]).astype(np.float32)
    ys = np.stack([np.atleast_1d(s["y"]) for s in dataset]).astype(np.float32)
    return model, xs, ys


def _run(accum_steps: int, micro_batches):
    """Train one accumulation window; return (final_a, final_b, stepped_flags)."""
    import torch

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

    # The oracle asserts bit-level equality of accumulated vs full-batch
    # updates — an fp32 exactness property; pin precision so a launcher-level
    # --mixed_precision bf16 doesn't (correctly) propagate in and break it.
    accelerator = Accelerator(gradient_accumulation_steps=accum_steps, mixed_precision="no")
    model, _, _ = _make_model_and_data()
    optimizer = torch.optim.SGD(model.parameters(), lr=0.1)
    model, optimizer = accelerator.prepare(model, optimizer)

    stepped = []
    for x, y in micro_batches:
        with accelerator.accumulate(model):
            out = model(torch.tensor(x))
            loss = torch.nn.functional.mse_loss(out, torch.tensor(y))
            accelerator.backward(loss)
            optimizer.step()
            stepped.append(not optimizer.step_was_skipped)
            optimizer.zero_grad()
    params = model.params
    return float(np.asarray(params["a"])), float(np.asarray(params["b"])), stepped


def test_noop_on_non_sync_steps():
    _, xs, ys = _make_model_and_data()
    micro = [(xs[i * 4 : (i + 1) * 4], ys[i * 4 : (i + 1) * 4]) for i in range(4)]
    _, _, stepped = _run(accum_steps=4, micro_batches=micro)
    assert stepped == [False, False, False, True], stepped
    print("no-op on non-sync steps ok")


def test_accumulation_matches_full_batch():
    _, xs, ys = _make_model_and_data()
    micro = [(xs[i * 4 : (i + 1) * 4], ys[i * 4 : (i + 1) * 4]) for i in range(4)]
    a_accum, b_accum, _ = _run(accum_steps=4, micro_batches=micro)
    a_full, b_full, stepped_full = _run(accum_steps=1, micro_batches=[(xs, ys)])
    assert stepped_full == [True]
    assert np.isclose(a_accum, a_full, atol=1e-6), (a_accum, a_full)
    assert np.isclose(b_accum, b_full, atol=1e-6), (b_accum, b_full)
    print("accumulated update == full-batch update ok")


def test_grads_differ_until_sync():
    """Accumulated gradient must grow across micro-steps (unequal between
    non-sync steps), then clear after the sync step — the reference's
    grads-equal-exactly-when-they-should-be oracle."""
    import torch

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

    _, xs, ys = _make_model_and_data()
    accelerator = Accelerator(gradient_accumulation_steps=2, mixed_precision="no")
    model, _, _ = _make_model_and_data()
    optimizer = torch.optim.SGD(model.parameters(), lr=0.1)
    model, optimizer = accelerator.prepare(model, optimizer)

    snapshots = []
    for i in range(2):
        with accelerator.accumulate(model):
            out = model(torch.tensor(xs[i * 8 : (i + 1) * 8]))
            loss = torch.nn.functional.mse_loss(out, torch.tensor(ys[i * 8 : (i + 1) * 8]))
            accelerator.backward(loss)
            grabbed = model._accum_grads
            snapshots.append(
                None if grabbed is None else float(np.asarray(grabbed["a"]))
            )
            optimizer.step()
            optimizer.zero_grad()
    assert snapshots[0] is not None and snapshots[1] is not None
    assert not np.isclose(snapshots[0], snapshots[1]), snapshots
    assert model._accum_grads is None, "grads not cleared after sync step"
    print("grad accumulation growth/clear ok")


def main():
    test_noop_on_non_sync_steps()
    test_accumulation_matches_full_batch()
    test_grads_differ_until_sync()
    print("test_sync: success")


if __name__ == "__main__":
    main()
