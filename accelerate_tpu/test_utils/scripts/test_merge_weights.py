"""Launchable sharded-save + merge check (reference
``test_utils/scripts/test_merge_weights.py``): train a step under
SHARDED_STATE_DICT, save per-process shards, consolidate with
``merge_fsdp_weights``, and verify the merged weights equal the live ones.

Run standalone or through the launcher:
    accelerate-tpu launch -m accelerate_tpu.test_utils.scripts.test_merge_weights
"""

from __future__ import annotations

import os
import tempfile

import numpy as np


def main():
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.test_utils.training import RegressionModelWithLoss
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, merge_fsdp_weights
    from accelerate_tpu.utils.fsdp_utils import save_fsdp_model

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

    plugin = FullyShardedDataParallelPlugin(state_dict_type="SHARDED_STATE_DICT")
    accelerator = Accelerator(fsdp_plugin=plugin)
    model = accelerator.prepare(RegressionModelWithLoss())

    with tempfile.TemporaryDirectory() as work:
        save_fsdp_model(plugin, accelerator, model, work)
        shard_dir = os.path.join(work, "model_0")
        assert os.path.isdir(shard_dir), os.listdir(work)

        out_dir = os.path.join(work, "merged")
        merge_fsdp_weights(shard_dir, out_dir, safe_serialization=True)
        merged_path = os.path.join(out_dir, "model.safetensors")
        assert os.path.exists(merged_path), os.listdir(out_dir)

        from safetensors.numpy import load_file

        import jax

        merged = load_file(merged_path)
        live = {k: np.asarray(v) for k, v in jax.device_get(model.params).items()}
        for key, value in live.items():
            np.testing.assert_allclose(merged[key], value, rtol=1e-6, atol=1e-6)

    accelerator.print("test_merge_weights: merged weights match live params")


if __name__ == "__main__":
    main()
