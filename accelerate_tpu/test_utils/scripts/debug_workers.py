"""Importable worker payloads for debug_launcher tests (spawn requires module-level
functions)."""

from __future__ import annotations

import numpy as np


def check_cluster_formed(expected: int):

    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == expected, (state.num_processes, expected)
    # A real cross-process collective.
    from accelerate_tpu.utils import gather

    out = gather(np.array([float(state.process_index)]))
    assert sorted(np.asarray(out).tolist()) == [float(i) for i in range(expected)], out
    state.wait_for_everyone()


def check_object_collectives(expected: int):
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import broadcast_object_list, gather_object

    state = PartialState()
    objs = gather_object([{"rank": state.process_index}])
    assert len(objs) == expected
    payload = [f"hello-{state.process_index}"]
    broadcast_object_list(payload, from_process=0)
    assert payload[0] == "hello-0"


def run_data_loop_suite(expected: int):
    """Run the full distributed-data-loop payload on a real multi-process
    cluster (VERDICT r2 item 8: even_batches=False + dispatcher + join
    override, end-to-end across OS processes — reference runs
    test_distributed_data_loop.py the same way under torchrun)."""
    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == expected, (state.num_processes, expected)

    from accelerate_tpu.test_utils.scripts import test_distributed_data_loop as s

    # Shared roster (s.ALL_TESTS) so this worker cannot drift from main();
    # the pickle test is single-process-only (fresh-process restore probe).
    s.run_all()
    # The payload resets state singletons; re-attach and sync before exit.
    PartialState().wait_for_everyone()


def check_broadcast_checkpoint_load(expected: int):
    """load_checkpoint_in_model(broadcast_from_rank0=True): only rank 0 reads
    from disk — other ranks pass a NONEXISTENT path and still end up with
    rank-0's weights (reference
    tests/test_load_checkpoint_and_dispatch_with_broadcast.py)."""
    import tempfile

    import torch

    from accelerate_tpu.checkpointing import save_model_weights
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import gather_object
    from accelerate_tpu.utils.modeling import load_checkpoint_in_model

    state = PartialState()
    assert state.num_processes == expected
    torch.manual_seed(100 + state.process_index)  # divergent init per rank
    model = torch.nn.Linear(4, 4)

    if state.is_main_process:
        ckpt_dir = tempfile.mkdtemp()
        torch.manual_seed(7)
        ref = torch.nn.Linear(4, 4)
        save_model_weights(ref, ckpt_dir)
    else:
        ckpt_dir = "/nonexistent/rank-local/never-read"
    load_checkpoint_in_model(model, ckpt_dir, broadcast_from_rank0=True)

    flat = model.weight.detach().numpy().ravel().tolist()
    gathered = gather_object([flat])
    assert len(gathered) == expected
    for other in gathered[1:]:
        assert other == gathered[0], "ranks diverged after broadcast load"
    torch.manual_seed(7)
    expected_ref = torch.nn.Linear(4, 4)
    assert np.allclose(flat, expected_ref.weight.detach().numpy().ravel()), (
        "broadcast weights do not match rank-0's checkpoint"
    )
    state.wait_for_everyone()


def check_broadcast_load_rank0_failure(expected: int):
    """A rank-0 read failure under broadcast_from_rank0 raises on EVERY rank
    (sentinel-first protocol) instead of deadlocking the followers."""
    import torch

    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils.modeling import load_checkpoint_in_model

    state = PartialState()
    model = torch.nn.Linear(2, 2)
    try:
        load_checkpoint_in_model(
            model, "/nonexistent/everywhere", broadcast_from_rank0=True
        )
    except RuntimeError as e:
        assert "rank 0 failed" in str(e), e
    else:
        raise AssertionError("expected a cross-rank RuntimeError")
    state.wait_for_everyone()


def check_split_between_processes(expected: int):
    from accelerate_tpu.state import PartialState

    state = PartialState()
    with state.split_between_processes(list(range(7)), apply_padding=True) as chunk:
        assert len(chunk) == 4 if expected == 2 else True


def run_training_matrix(expected: int):
    """The test_script training_check matrix across a real multi-process
    cluster (reference: torchrun test_script.py) — quick combos via
    ACCELERATE_TEST_QUICK so each process's recompiles stay bounded."""
    import os

    from accelerate_tpu.state import PartialState

    os.environ["ACCELERATE_TEST_QUICK"] = "1"
    state = PartialState()
    assert state.num_processes == expected, (state.num_processes, expected)
    from accelerate_tpu.test_utils.scripts.test_script import training_check

    training_check(use_seedable_sampler=False)
    training_check(use_seedable_sampler=True)
    state.wait_for_everyone()


def run_local_state_dict_roundtrip(expected: int):
    """FSDP LOCAL_STATE_DICT across a REAL multi-process cluster: every
    process dumps only its own addressable shards and restores them — the
    contract single-process tests cannot exercise."""
    import os
    import tempfile

    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from accelerate_tpu.checkpointing import load_local_model, save_local_model
    from accelerate_tpu.parallel.mesh import build_mesh
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils.dataclasses import ParallelismConfig

    state = PartialState()
    assert state.num_processes == expected, (state.num_processes, expected)
    assert jax.process_count() == expected

    mesh = build_mesh(ParallelismConfig(fsdp=jax.device_count()))

    class _PM:
        def __init__(self, params):
            self.params = params

        def _set_params(self, p):
            self.params = p

    n = 8 * jax.device_count()
    host_rows = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    sharding = NamedSharding(mesh, P("fsdp", None))
    w = jax.make_array_from_process_local_data(
        sharding, host_rows[state.process_index * (n // expected):(state.process_index + 1) * (n // expected)]
    )
    model = _PM({"w": w})

    # Every process writes its own dump into a SHARED tmp dir (rank 0 picks).
    from accelerate_tpu.utils.operations import broadcast_object_list

    path = [tempfile.mkdtemp() if state.is_main_process else None]
    broadcast_object_list(path, from_process=0)
    directory = os.path.join(path[0], "local")
    save_local_model(model, directory)
    state.wait_for_everyone()
    assert os.path.exists(os.path.join(directory, f"local_rank{state.process_index}.bin"))

    # Perturb, then restore — every shard must come home exactly.
    model._set_params({"w": jax.device_put(jax.numpy.zeros((n, 4)), sharding)})
    load_local_model(model, directory)
    for sh in model.params["w"].addressable_shards:
        start = sh.index[0].start or 0
        np.testing.assert_array_equal(
            np.asarray(sh.data), host_rows[start:start + np.asarray(sh.data).shape[0]]
        )
    state.wait_for_everyone()


def check_fleet_agree(expected: int):
    """fleet.agree over the coordinator KV service: every rank contributes a
    value, all ranks see the rank-ordered list; two rounds under the SAME name
    prove the lockstep sequence counters keep keys collision-free."""
    from accelerate_tpu.resilience import fleet
    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == expected
    assert fleet.fleet_client() is not None

    rank = state.process_index
    out = fleet.agree("payload", {"rank": rank, "v": rank * 10}, timeout_s=60)
    assert [o["rank"] for o in out] == list(range(expected)), out
    assert [o["v"] for o in out] == [r * 10 for r in range(expected)], out
    # Round 2, same name: a fresh key sequence, not a stale-read of round 1.
    out2 = fleet.agree("payload", rank + 100, timeout_s=60)
    assert out2 == [r + 100 for r in range(expected)], out2
    fleet.barrier("fleet_agree_done", timeout_s=60)


def check_fleet_barrier_timeout(expected: int):
    """A barrier nobody else joins must raise FleetError within its deadline
    instead of hanging forever — the anti-hang contract.  Rank 0 waits at a
    barrier rank 1 skips; afterwards everyone resyncs on a joined barrier."""
    import time as _time

    from accelerate_tpu.resilience import fleet
    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == expected

    if state.process_index == 0:
        t0 = _time.monotonic()
        try:
            fleet.barrier("lonely", timeout_s=2.0)
        except fleet.FleetError:
            elapsed = _time.monotonic() - t0
            assert elapsed < 30, f"deadline not honored: {elapsed:.1f}s"
        else:
            raise AssertionError("barrier with an absent peer did not raise")
    # Resync: everyone joins this one (generous window for rank 0's timeout).
    fleet.barrier("resync", timeout_s=60.0)


def check_drain_agreement(expected: int):
    """Coordinated drain across real processes: ONE rank receives SIGTERM, yet
    every rank's ``PreemptionGuard.should_stop()`` — routed through
    ``fleet.agree`` — returns True on the same round."""
    import os as _os
    import signal as _signal

    from accelerate_tpu.resilience import PreemptionGuard, fleet
    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == expected

    guard = PreemptionGuard(coordinate_every=1, agree_timeout_s=60)
    guard.install()
    # Round 1: nobody signaled — every rank must agree "keep going".
    assert guard.should_stop() is False
    fleet.barrier("pre_signal", timeout_s=60)
    if state.process_index == expected - 1:
        _os.kill(_os.getpid(), _signal.SIGTERM)
    # Round 2: the one local flag must spread to every rank via the fleet.
    assert guard.should_stop() is True
    fleet.barrier("post_signal", timeout_s=60)
