"""Importable worker payloads for debug_launcher tests (spawn requires module-level
functions)."""

from __future__ import annotations

import numpy as np


def check_cluster_formed(expected: int):

    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == expected, (state.num_processes, expected)
    # A real cross-process collective.
    from accelerate_tpu.utils import gather

    out = gather(np.array([float(state.process_index)]))
    assert sorted(np.asarray(out).tolist()) == [float(i) for i in range(expected)], out
    state.wait_for_everyone()


def check_object_collectives(expected: int):
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import broadcast_object_list, gather_object

    state = PartialState()
    objs = gather_object([{"rank": state.process_index}])
    assert len(objs) == expected
    payload = [f"hello-{state.process_index}"]
    broadcast_object_list(payload, from_process=0)
    assert payload[0] == "hello-0"


def run_data_loop_suite(expected: int):
    """Run the full distributed-data-loop payload on a real multi-process
    cluster (VERDICT r2 item 8: even_batches=False + dispatcher + join
    override, end-to-end across OS processes — reference runs
    test_distributed_data_loop.py the same way under torchrun)."""
    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == expected, (state.num_processes, expected)

    from accelerate_tpu.test_utils.scripts import test_distributed_data_loop as s

    # Shared roster (s.ALL_TESTS) so this worker cannot drift from main();
    # the pickle test is single-process-only (fresh-process restore probe).
    s.run_all()
    # The payload resets state singletons; re-attach and sync before exit.
    PartialState().wait_for_everyone()


def check_split_between_processes(expected: int):
    from accelerate_tpu.state import PartialState

    state = PartialState()
    with state.split_between_processes(list(range(7)), apply_padding=True) as chunk:
        assert len(chunk) == 4 if expected == 2 else True
