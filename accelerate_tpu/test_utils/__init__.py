from .training import RegressionDataset, RegressionModel, RegressionModelWithLoss
