"""Experiment trackers.

Parity target: reference ``src/accelerate/tracking.py`` (1089 LoC):
``GeneralTracker`` ABC with ``main_process_only`` gating (``tracking.py:69``),
the full backend set — TensorBoard (167), WandB (278), CometML (401), Aim (493),
MLflow (592), ClearML (790), DVCLive (942) — plus a dependency-free JSONL
tracker, registry ``LOGGER_TYPE_TO_CLASS`` (1026) and ``filter_trackers``
(1037).  Backends import their SDK lazily and are filtered by availability, so
the module works in environments with none of them installed.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Optional

from .logging import get_logger
from .state import PartialState
from .utils.imports import (
    is_aim_available,
    is_clearml_available,
    is_comet_ml_available,
    is_dvclive_available,
    is_mlflow_available,
    is_tensorboard_available,
    is_wandb_available,
)

logger = get_logger(__name__)

__all__ = [
    "GeneralTracker",
    "GenericTracker",
    "TensorBoardTracker",
    "WandBTracker",
    "CometMLTracker",
    "AimTracker",
    "MLflowTracker",
    "ClearMLTracker",
    "DVCLiveTracker",
    "LOGGER_TYPE_TO_CLASS",
    "filter_trackers",
    "init_trackers",
    "on_main_process",
    "telemetry_rows",
]


def on_main_process(function):
    """Run only on the main process (reference ``tracking.py:69``)."""

    @functools.wraps(function)
    def wrapper(self, *args, **kwargs):
        if getattr(self, "main_process_only", True) and not PartialState().is_main_process:
            return None
        return function(self, *args, **kwargs)

    return wrapper


def _is_scalar(v) -> bool:
    """Loggable-as-metric predicate shared by the backends."""
    return isinstance(v, (int, float)) or hasattr(v, "__float__")


class GeneralTracker:
    """Base tracker (reference ``tracking.py:93-166``)."""

    name: str = "general"
    requires_logging_directory: bool = False
    main_process_only: bool = True

    def __init__(self, _blank: bool = False):
        pass

    @property
    def tracker(self):
        raise NotImplementedError

    def store_init_configuration(self, values: dict):
        raise NotImplementedError

    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        raise NotImplementedError

    def finish(self):
        pass


class GenericTracker(GeneralTracker):
    """Dependency-free JSONL tracker (each log call appends one line)."""

    name = "generic"
    requires_logging_directory = True

    def __init__(self, run_name: str, logging_dir: str = "."):
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        os.makedirs(self.logging_dir, exist_ok=True)
        self.path = os.path.join(self.logging_dir, "metrics.jsonl")

    @property
    def tracker(self):
        return self.path

    @on_main_process
    def store_init_configuration(self, values: dict):
        with open(os.path.join(self.logging_dir, "config.json"), "w") as f:
            json.dump(values, f, default=str)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        rec = {"_step": step, "_time": time.time()}
        rec.update({k: (float(v) if hasattr(v, "__float__") else v) for k, v in values.items()})
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, default=str) + "\n")


class TensorBoardTracker(GeneralTracker):
    """Reference ``tracking.py:167``."""

    name = "tensorboard"
    requires_logging_directory = True

    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        try:
            from torch.utils import tensorboard
        except ImportError:
            import tensorboardX as tensorboard
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        self.writer = tensorboard.SummaryWriter(self.logging_dir, **kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.add_hparams(
            {k: v for k, v in values.items() if isinstance(v, (int, float, str, bool))}, {}
        )
        self.writer.flush()

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in values.items():
            if _is_scalar(v):
                self.writer.add_scalar(k, float(v), global_step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.add_text(k, v, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs):
        """Log a dict of image batches (reference ``tracking.py:253``): each
        value is an [N, H, W, C] (or [N, C, H, W]) array."""
        import numpy as np

        explicit_format = kwargs.pop("dataformats", None)
        for k, v in values.items():
            arr = np.asarray(v)
            dataformats = explicit_format or ("NHWC" if arr.shape[-1] in (1, 3, 4) else "NCHW")
            self.writer.add_images(k, arr, global_step=step, dataformats=dataformats, **kwargs)
        self.writer.flush()

    @on_main_process
    def finish(self):
        self.writer.close()


class WandBTracker(GeneralTracker):
    """Reference ``tracking.py:278``."""

    name = "wandb"
    requires_logging_directory = False

    def __init__(self, run_name: str, **kwargs):
        import wandb

        self.run_name = run_name
        self.run = wandb.init(project=run_name, **kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import wandb

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs):
        """Log image lists as ``wandb.Image``s (reference ``tracking.py:343``)."""
        import wandb

        for k, v in values.items():
            self.log({k: [wandb.Image(image) for image in v]}, step=step, **kwargs)

    @on_main_process
    def log_table(
        self,
        table_name: str,
        columns: Optional[list] = None,
        data: Optional[list] = None,
        dataframe=None,
        step: Optional[int] = None,
        **kwargs,
    ):
        """Log a ``wandb.Table`` from columns+data or a dataframe (reference
        ``tracking.py:362``)."""
        import wandb

        self.log(
            {table_name: wandb.Table(columns=columns, data=data, dataframe=dataframe)},
            step=step,
            **kwargs,
        )

    @on_main_process
    def finish(self):
        self.run.finish()


class CometMLTracker(GeneralTracker):
    """Reference ``tracking.py:401``."""

    name = "comet_ml"
    requires_logging_directory = False

    def __init__(self, run_name: str, **kwargs):
        import comet_ml

        self.run_name = run_name
        self.experiment = comet_ml.start(project_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.experiment

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.experiment.log_parameters(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self.experiment.log_current_epoch(step)
        for k, v in values.items():
            if _is_scalar(v):
                self.experiment.log_metric(k, float(v), step=step, **kwargs)
            elif isinstance(v, str):
                self.experiment.log_other(k, v, **kwargs)

    @on_main_process
    def finish(self):
        self.experiment.end()


class AimTracker(GeneralTracker):
    """Reference ``tracking.py:493``."""

    name = "aim"
    requires_logging_directory = True

    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        from aim import Run

        self.run_name = run_name
        self.writer = Run(repo=logging_dir, **kwargs)
        self.writer.name = run_name

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer["hparams"] = values

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in values.items():
            self.writer.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, kwargs: Optional[dict] = None):
        """Track images as ``aim.Image``s (reference ``tracking.py:553``);
        ``kwargs`` may hold per-call dicts under "aim_image" and "track"."""
        import aim

        aim_image_kw = (kwargs or {}).get("aim_image", {})
        track_kw = (kwargs or {}).get("track", {})
        for k, v in values.items():
            img, caption = v if isinstance(v, tuple) else (v, "")
            self.writer.track(
                aim.Image(img, caption=caption, **aim_image_kw), name=k, step=step, **track_kw
            )

    @on_main_process
    def finish(self):
        self.writer.close()


class MLflowTracker(GeneralTracker):
    """Reference ``tracking.py:592``."""

    name = "mlflow"
    requires_logging_directory = False

    def __init__(self, run_name: str, logging_dir: Optional[str] = None, **kwargs):
        import mlflow

        self.run_name = run_name
        experiment_name = kwargs.pop("experiment_name", run_name)
        mlflow.set_experiment(experiment_name)
        self.active_run = mlflow.start_run(run_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.active_run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import mlflow

        # MLflow caps param value length; stringify + truncate like the reference.
        items = [(k, str(v)[:500]) for k, v in values.items()]
        for i in range(0, len(items), 100):  # batch limit per call
            mlflow.log_params(dict(items[i : i + 100]))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        import mlflow

        metrics = {k: float(v) for k, v in values.items() if _is_scalar(v)}
        mlflow.log_metrics(metrics, step=step)

    @on_main_process
    def log_figure(self, figure, artifact_file: str, **save_kwargs):
        """Log a matplotlib figure as an artifact (reference ``tracking.py:728``)."""
        import mlflow

        mlflow.log_figure(figure, artifact_file, **save_kwargs)

    @on_main_process
    def log_artifact(self, local_path: str, artifact_path: Optional[str] = None):
        """Upload one local file as an artifact (reference ``tracking.py:764``)."""
        import mlflow

        mlflow.log_artifact(local_path, artifact_path)

    @on_main_process
    def log_artifacts(self, local_dir: str, artifact_path: Optional[str] = None):
        """Upload a local directory of artifacts (reference ``tracking.py:747``)."""
        import mlflow

        mlflow.log_artifacts(local_dir, artifact_path)

    @on_main_process
    def finish(self):
        import mlflow

        mlflow.end_run()


class ClearMLTracker(GeneralTracker):
    """Reference ``tracking.py:790``."""

    name = "clearml"
    requires_logging_directory = False

    def __init__(self, run_name: str, **kwargs):
        from clearml import Task

        self.run_name = run_name
        self.task = Task.init(project_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.task.connect_configuration(dict(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        clearml_logger = self.task.get_logger()
        for k, v in values.items():
            if not (_is_scalar(v)):
                continue
            if step is None:
                clearml_logger.report_single_value(name=k, value=float(v), **kwargs)
                continue
            title, _, series = k.partition("/")
            series = series or title
            clearml_logger.report_scalar(
                title=title, series=series, value=float(v), iteration=step, **kwargs
            )

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs):
        """Report images to the ClearML debug-samples tab (reference
        ``tracking.py:870``)."""
        clearml_logger = self.task.get_logger()
        for k, v in values.items():
            title, _, series = k.partition("/")
            series = series or title
            clearml_logger.report_image(
                title=title, series=series, iteration=step, image=v, **kwargs
            )

    @on_main_process
    def log_table(
        self,
        table_name: str,
        columns: Optional[list] = None,
        data: Optional[list] = None,
        dataframe=None,
        step: Optional[int] = None,
        **kwargs,
    ):
        """Report a table from columns+data or a dataframe (reference
        ``tracking.py:888``)."""
        if dataframe is None:
            if columns is None or data is None:
                raise ValueError(
                    "log_table needs either a `dataframe` or both `columns` and `data`"
                )
            dataframe = [list(columns)] + [list(row) for row in data]
        title, _, series = table_name.partition("/")
        series = series or title
        self.task.get_logger().report_table(
            title=title, series=series, iteration=step, table_plot=dataframe, **kwargs
        )

    @on_main_process
    def finish(self):
        self.task.close()


class DVCLiveTracker(GeneralTracker):
    """Reference ``tracking.py:942``."""

    name = "dvclive"
    requires_logging_directory = False

    def __init__(self, run_name: Optional[str] = None, live=None, **kwargs):
        from dvclive import Live

        self.live = live if live is not None else Live(**kwargs)

    @property
    def tracker(self):
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.live.log_params(dict(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self.live.step = step
        for k, v in values.items():
            if _is_scalar(v):
                self.live.log_metric(k, float(v), **kwargs)
        self.live.next_step()

    @on_main_process
    def finish(self):
        self.live.end()


LOGGER_TYPE_TO_CLASS = {
    "generic": GenericTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "comet_ml": CometMLTracker,
    "aim": AimTracker,
    "mlflow": MLflowTracker,
    "clearml": ClearMLTracker,
    "dvclive": DVCLiveTracker,
}

# name -> availability probe; "generic" has no dependency so it is always on.
_TRACKER_AVAILABLE = {
    "tensorboard": is_tensorboard_available,
    "wandb": is_wandb_available,
    "comet_ml": is_comet_ml_available,
    "aim": is_aim_available,
    "mlflow": is_mlflow_available,
    "clearml": is_clearml_available,
    "dvclive": is_dvclive_available,
}


def filter_trackers(log_with: list, logging_dir: Optional[str] = None) -> list:
    """Validate requested trackers against availability (reference
    ``tracking.py:1037``): "all" expands to every installed backend, unavailable
    backends warn + drop, unknown names raise."""
    out = []
    for item in log_with or []:
        if isinstance(item, GeneralTracker):
            out.append(item)
            continue
        name = str(item).lower()
        if name == "all":
            out.extend(n for n, avail in _TRACKER_AVAILABLE.items() if avail())
            continue
        if name not in LOGGER_TYPE_TO_CLASS:
            raise ValueError(f"Unknown tracker {name}; options: {sorted(LOGGER_TYPE_TO_CLASS)}")
        if name in _TRACKER_AVAILABLE and not _TRACKER_AVAILABLE[name]():
            logger.warning(f"{name} not available; skipping tracker")
            continue
        out.append(name)
    # Dedupe preserving order ("all" + an explicit name must not instantiate a
    # backend twice — a second mlflow.start_run/wandb.init would raise).
    seen: set = set()
    deduped = []
    for item in out:
        key = item if isinstance(item, str) else id(item)
        if key not in seen:
            seen.add(key)
            deduped.append(item)
    return deduped


def telemetry_rows(prefix: str = "telemetry/") -> dict:
    """Scalar snapshot of the telemetry metrics registry, prefixed for tracker
    namespaces.  Empty when telemetry is disabled — ``Accelerator.log`` merges
    this into every ``log()`` call, so any ``GeneralTracker`` backend receives
    step-time / compile / HBM / MFU rows for free once telemetry is on."""
    from .telemetry import get_telemetry

    tel = get_telemetry()
    if not tel.enabled:
        return {}
    return {
        f"{prefix}{k}": v
        for k, v in tel.registry.snapshot().items()
        if isinstance(v, (int, float))
    }


def init_trackers(log_with, project_name, config, init_kwargs, accelerator) -> list[GeneralTracker]:
    # Constructors create SDK runs/tasks, so non-main processes must not build
    # backends at all (reference gates Accelerator.init_trackers itself with
    # @on_main_process): only already-constructed instances pass through.
    if not PartialState().is_main_process:
        return [t for t in (log_with or []) if isinstance(t, GeneralTracker)]
    init_kwargs = init_kwargs or {}
    logging_dir = accelerator.project_configuration.logging_dir or "."
    trackers = []
    for item in filter_trackers(log_with, logging_dir):
        if isinstance(item, GeneralTracker):
            trackers.append(item)
            continue
        cls = LOGGER_TYPE_TO_CLASS[item]
        kwargs = init_kwargs.get(item, {})
        if cls.requires_logging_directory:
            trackers.append(cls(project_name, logging_dir=logging_dir, **kwargs))
        else:
            trackers.append(cls(project_name, **kwargs))
    if config is not None:
        for t in trackers:
            t.store_init_configuration(config)
    return trackers
