"""Experiment trackers.

Parity target: reference ``src/accelerate/tracking.py`` (1089 LoC):
``GeneralTracker`` ABC with ``main_process_only`` gating, 8 backends, registry +
``filter_trackers``.  Round 1 ships the ABC, the generic dict/JSONL tracker, and
TensorBoard/WandB adapters (gated on availability); remaining backends follow the
same adapter shape.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Any, Optional, Union

from .logging import get_logger
from .state import PartialState
from .utils.imports import is_tensorboard_available, is_wandb_available

logger = get_logger(__name__)

__all__ = [
    "GeneralTracker",
    "GenericTracker",
    "TensorBoardTracker",
    "WandBTracker",
    "LOGGER_TYPE_TO_CLASS",
    "filter_trackers",
    "init_trackers",
    "on_main_process",
]


def on_main_process(function):
    """Run only on the main process (reference ``tracking.py:69``)."""

    @functools.wraps(function)
    def wrapper(self, *args, **kwargs):
        if getattr(self, "main_process_only", True) and not PartialState().is_main_process:
            return None
        return function(self, *args, **kwargs)

    return wrapper


class GeneralTracker:
    """Base tracker (reference ``tracking.py:93-166``)."""

    name: str = "general"
    requires_logging_directory: bool = False
    main_process_only: bool = True

    def __init__(self, _blank: bool = False):
        pass

    @property
    def tracker(self):
        raise NotImplementedError

    def store_init_configuration(self, values: dict):
        raise NotImplementedError

    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        raise NotImplementedError

    def finish(self):
        pass


class GenericTracker(GeneralTracker):
    """Dependency-free JSONL tracker (each log call appends one line)."""

    name = "generic"
    requires_logging_directory = True

    def __init__(self, run_name: str, logging_dir: str = "."):
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        os.makedirs(self.logging_dir, exist_ok=True)
        self.path = os.path.join(self.logging_dir, "metrics.jsonl")

    @property
    def tracker(self):
        return self.path

    @on_main_process
    def store_init_configuration(self, values: dict):
        with open(os.path.join(self.logging_dir, "config.json"), "w") as f:
            json.dump(values, f, default=str)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        rec = {"_step": step, "_time": time.time()}
        rec.update({k: (float(v) if hasattr(v, "__float__") else v) for k, v in values.items()})
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, default=str) + "\n")


class TensorBoardTracker(GeneralTracker):
    """Reference ``tracking.py:167``."""

    name = "tensorboard"
    requires_logging_directory = True

    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        try:
            from torch.utils import tensorboard
        except ImportError:
            import tensorboardX as tensorboard
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        self.writer = tensorboard.SummaryWriter(self.logging_dir, **kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.add_hparams(
            {k: v for k, v in values.items() if isinstance(v, (int, float, str, bool))}, {}
        )
        self.writer.flush()

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in values.items():
            if isinstance(v, (int, float)) or hasattr(v, "__float__"):
                self.writer.add_scalar(k, float(v), global_step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.add_text(k, v, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def finish(self):
        self.writer.close()


class WandBTracker(GeneralTracker):
    """Reference ``tracking.py:278``."""

    name = "wandb"
    requires_logging_directory = False

    def __init__(self, run_name: str, **kwargs):
        import wandb

        self.run_name = run_name
        self.run = wandb.init(project=run_name, **kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import wandb

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.run.finish()


LOGGER_TYPE_TO_CLASS = {
    "generic": GenericTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
}


def filter_trackers(log_with: list, logging_dir: Optional[str] = None) -> list[str]:
    """Validate requested trackers against availability (reference
    ``tracking.py:1037``)."""
    out = []
    for item in log_with or []:
        if isinstance(item, GeneralTracker):
            out.append(item)
            continue
        name = str(item).lower()
        if name == "all":
            if is_tensorboard_available():
                out.append("tensorboard")
            if is_wandb_available():
                out.append("wandb")
            continue
        if name == "tensorboard" and not is_tensorboard_available():
            logger.warning("tensorboard not available; skipping tracker")
            continue
        if name == "wandb" and not is_wandb_available():
            logger.warning("wandb not available; skipping tracker")
            continue
        if name not in LOGGER_TYPE_TO_CLASS:
            raise ValueError(f"Unknown tracker {name}; options: {sorted(LOGGER_TYPE_TO_CLASS)}")
        out.append(name)
    return out


def init_trackers(log_with, project_name, config, init_kwargs, accelerator) -> list[GeneralTracker]:
    init_kwargs = init_kwargs or {}
    logging_dir = accelerator.project_configuration.logging_dir or "."
    trackers = []
    for item in filter_trackers(log_with, logging_dir):
        if isinstance(item, GeneralTracker):
            trackers.append(item)
            continue
        cls = LOGGER_TYPE_TO_CLASS[item]
        kwargs = init_kwargs.get(item, {})
        if cls.requires_logging_directory:
            trackers.append(cls(project_name, logging_dir=logging_dir, **kwargs))
        else:
            trackers.append(cls(project_name, **kwargs))
    if config is not None:
        for t in trackers:
            t.store_init_configuration(config)
    return trackers
