"""Paged KV-cache storage: a block pool plus a host-side free-list allocator.

The resident KV cache is a pool of ``num_blocks`` fixed-size blocks shared by
every in-flight request (``[L, num_blocks, block_size, ...]`` per leaf — the
int8 codes+scale layout from ``quantize_kv`` pages identically), with a
per-request **block table** mapping logical token positions to physical
blocks.  A request holding ``n`` tokens costs ``ceil(n / block_size)`` blocks
instead of ``max_len`` rows, so a 32-token request and a 2k-token request can
share the pool that a dense cache would tile to 2k each.

Fixed-size blocks mean external fragmentation is structurally zero: any free
block serves any request, and the only waste is the tail of the last block
(< ``block_size`` rows per request).  The allocator is plain host Python —
allocation decisions happen between dispatches, never inside the jitted
decode step.

Block 0 is reserved as the **null block**: it is never handed out, block
tables are padded with it, and inactive decode slots write their garbage row
into it, so stray gathers/scatters can never touch a live request's KV.
"""

from __future__ import annotations

from typing import Callable, List

__all__ = ["BlockAllocator", "BlockOutOfMemory", "PagedKVCache", "blocks_for_tokens"]

NULL_BLOCK = 0


class BlockOutOfMemory(RuntimeError):
    """No free block available; the caller decides (preempt, queue, reject)."""


def blocks_for_tokens(tokens: int, block_size: int) -> int:
    """ceil(tokens / block_size) — blocks needed to hold ``tokens`` rows."""
    return -(-tokens // block_size)


class BlockAllocator:
    """LIFO free-list over block ids ``1..num_blocks-1`` (0 is the null
    block).  LIFO keeps recently-freed (cache-warm) blocks hot, and makes
    alloc/free O(1)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (one null + one usable), got {num_blocks}"
            )
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._allocated: set = set()

    @property
    def capacity(self) -> int:
        """Usable blocks (excludes the null block)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return len(self._allocated)

    @property
    def occupancy(self) -> float:
        """Fraction of usable blocks currently allocated."""
        return self.used_blocks / self.capacity

    def alloc(self, n: int = 1) -> List[int]:
        """Pop ``n`` free blocks; raises :class:`BlockOutOfMemory` (allocating
        NOTHING) when fewer than ``n`` are free — partial grants would leak
        on the error path."""
        if n < 0:
            raise ValueError(f"alloc count must be >= 0, got {n}")
        if n > len(self._free):
            raise BlockOutOfMemory(
                f"need {n} blocks, {len(self._free)} free of {self.capacity}"
            )
        out = [self._free.pop() for _ in range(n)]
        self._allocated.update(out)
        return out

    def free(self, blocks: List[int]) -> None:
        """Return blocks to the free list; double-free and freeing the null
        block are hard errors (both indicate scheduler corruption)."""
        for b in blocks:
            if b == NULL_BLOCK:
                raise ValueError("cannot free the null block")
            if b not in self._allocated:
                raise ValueError(f"double free / foreign block: {b}")
            self._allocated.remove(b)
            self._free.append(b)


class PagedKVCache:
    """The device-side block pool plus its allocator.

    ``init_cache`` is a model family's cache constructor (``models/*.py``);
    the pool leaves are derived from its batch-1 template, so the fp and
    int8-quantized layouts both page without special cases
    (:func:`accelerate_tpu.models.generation.make_paged_pool`).
    """

    def __init__(
        self,
        init_cache: Callable,
        config,
        num_blocks: int,
        block_size: int,
    ):
        from ..models.generation import make_paged_pool

        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = block_size
        self.allocator = BlockAllocator(num_blocks)
        self.pool = make_paged_pool(init_cache, config, num_blocks, block_size)

    @property
    def leaf_names(self) -> list:
        return sorted(self.pool)

    def pool_bytes(self) -> int:
        return sum(leaf.size * leaf.dtype.itemsize for leaf in self.pool.values())
