"""Paged KV-cache storage: a block pool, a refcounting allocator, and a
content-addressed prefix cache.

The resident KV cache is a pool of ``num_blocks`` fixed-size blocks shared by
every in-flight request (``[L, num_blocks, block_size, ...]`` per leaf — the
int8 codes+scale layout from ``quantize_kv`` pages identically), with a
per-request **block table** mapping logical token positions to physical
blocks.  A request holding ``n`` tokens costs ``ceil(n / block_size)`` blocks
instead of ``max_len`` rows, so a 32-token request and a 2k-token request can
share the pool that a dense cache would tile to 2k each.

Fixed-size blocks mean external fragmentation is structurally zero: any free
block serves any request, and the only waste is the tail of the last block
(< ``block_size`` rows per request).  The allocator is plain host Python —
allocation decisions happen between dispatches, never inside the jitted
decode step.

Blocks are **refcounted** so physical blocks can be shared: a fresh ``alloc``
grants refcount 1, :meth:`BlockAllocator.retain` adds a reader (prefix
sharing), and ``free`` releases one reference — the block returns to the free
list only when the last holder lets go.  Two additional states ride the
refcounts:

- **dirty** (:meth:`mark_dirty`) — the quarantine path poisons a block's
  K/V; a dirty block must be scrubbed to zero before any reuse.  With
  sharing this becomes **scrub-on-last-release**: a dirty block that still
  has live readers keeps serving them (their own finiteness checks guard
  them) and is zeroed only when its refcount hits 0, so a shared block is
  never scrubbed under a live reader.  Such blocks land in a
  ``pending_scrub`` set the engine drains (the scrub is a device write) and
  re-enters the free list via :meth:`finish_scrub`.
- **reclaimable** — blocks whose only reference is the
  :class:`PrefixCache`.  They count as free capacity (``free_blocks``):
  ``alloc`` evicts them LRU-first when the free list runs dry, so caching
  never causes an OOM a cacheless pool would not have had.

Block 0 is reserved as the **null block**: it is never handed out, block
tables are padded with it, and inactive decode slots write their garbage row
into it, so stray gathers/scatters can never touch a live request's KV.

:class:`PrefixCache` shares **full prompt blocks across requests by
content**: block ``i`` of a request's token feed is keyed by a chain hash
``h_i = H(h_{i-1} || tokens[i*bs:(i+1)*bs])`` — K/V rows depend on the whole
prefix, so the chain (not the block's own tokens) is the sound identity.  A
lookup walks the chain until the first miss, retains every matched block for
the new reader, and the engine starts that request's prefill past the shared
prefix (TTFT collapses to the unshared suffix).  The partial tail is handled
with **copy-on-write**: when the cached chain covers more rows than the new
request may reuse wholesale (it must keep >= 1 token to feed), the next
chain block is copied into a private block and writing continues there —
shared blocks are never written after registration (writes always move
forward from ``cache_len``; every shared block ends before it).

**Host tier.**  :class:`PagedKVCache` can carry a second, host-DRAM block
pool (:class:`HostBlockPool`) mirroring the device pool's leaf layout, with
explicit :meth:`PagedKVCache.demote` / :meth:`PagedKVCache.promote` block
migrations (batched device_get / device-scatter per call — never inside the
fused decode dispatch).  The host tier has no refcounts: every host block has
exactly one owner (a preempted request's demoted KV, or a cold prefix-cache
chain entry), and the scrub contract carries over — a host block marked dirty
is zeroed synchronously on free, so quarantined content can never leak into a
later resident.  On real accelerators the host leaves live in pinned host
memory (``memory_kind="pinned_host"``); here they are numpy arrays so the
D2H/H2D copies are real transfers on every backend, including the CPU one
where host *is* the default memory kind and a same-kind ``device_put`` would
silently commit the leaf instead of moving it.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "BlockAllocator",
    "BlockOutOfMemory",
    "HostBlockPool",
    "PagedKVCache",
    "PrefixCache",
    "blocks_for_tokens",
]

NULL_BLOCK = 0


class BlockOutOfMemory(RuntimeError):
    """No free block available; the caller decides (preempt, queue, reject)."""


def blocks_for_tokens(tokens: int, block_size: int) -> int:
    """ceil(tokens / block_size) — blocks needed to hold ``tokens`` rows."""
    return -(-tokens // block_size)


class BlockAllocator:
    """Refcounting LIFO free-list over block ids ``1..num_blocks-1`` (0 is
    the null block).  LIFO keeps recently-freed (cache-warm) blocks hot, and
    makes alloc/free O(1).  ``free`` releases ONE reference; a block shared
    via :meth:`retain` stays allocated until its last holder frees it."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (one null + one usable), got {num_blocks}"
            )
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        self._dirty: set = set()
        self._pending_scrub: List[int] = []
        self._cache: Optional["PrefixCache"] = None

    def attach_cache(self, cache: "PrefixCache") -> None:
        """Wire a :class:`PrefixCache` in: its cache-only blocks count as
        reclaimable free capacity and are evicted LRU-first on pressure."""
        self._cache = cache

    @property
    def capacity(self) -> int:
        """Usable blocks (excludes the null block)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        """Immediately allocatable blocks: the free list plus cache-only
        (reclaimable) blocks an ``alloc`` would evict on demand."""
        n = len(self._free)
        if self._cache is not None:
            n += self._cache.reclaimable_count
        return n

    @property
    def used_blocks(self) -> int:
        """Blocks held by at least one non-cache reference."""
        n = len(self._ref)
        if self._cache is not None:
            n -= self._cache.reclaimable_count
        return n

    @property
    def occupancy(self) -> float:
        """Fraction of usable blocks currently allocated (cache-only blocks
        are reclaimable and therefore not counted)."""
        return self.used_blocks / self.capacity

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def alloc(self, n: int = 1) -> List[int]:
        """Pop ``n`` free blocks (each at refcount 1); evicts cache-only
        blocks when the free list alone cannot cover the grant.  Raises
        :class:`BlockOutOfMemory` (allocating NOTHING) when fewer than ``n``
        are reachable — partial grants would leak on the error path."""
        if n < 0:
            raise ValueError(f"alloc count must be >= 0, got {n}")
        if n > len(self._free) and self._cache is not None:
            self._cache.evict(n - len(self._free))
        if n > len(self._free):
            raise BlockOutOfMemory(
                f"need {n} blocks, {self.free_blocks} free of {self.capacity}"
            )
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def retain(self, block: int) -> None:
        """Add one reference to an allocated block (prefix sharing)."""
        if block == NULL_BLOCK:
            raise ValueError("cannot retain the null block")
        if block not in self._ref:
            raise ValueError(f"retain of unallocated block: {block}")
        if self._ref[block] == 1 and self._cache is not None:
            self._cache._note_first_reader(block)
        self._ref[block] += 1

    def free(self, blocks: List[int]) -> None:
        """Release one reference per block; the last release returns the
        block to the free list (or to ``pending_scrub`` when it was marked
        dirty — scrub-on-last-release).  Releasing the null block or a block
        with no references is a hard error (scheduler corruption)."""
        for b in blocks:
            if b == NULL_BLOCK:
                raise ValueError("cannot free the null block")
            if b not in self._ref:
                raise ValueError(f"double free / foreign block: {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                if b in self._dirty:
                    self._pending_scrub.append(b)
                else:
                    self._free.append(b)
            elif self._ref[b] == 1 and self._cache is not None:
                self._cache._note_last_reader_left(b)

    # -- dirty blocks (quarantine scrub-on-last-release) ----------------------

    def mark_dirty(self, blocks: List[int]) -> None:
        """Mark blocks as needing a zero-scrub before reuse.  Blocks still
        referenced keep serving their live readers; they are scrubbed when
        the last reference releases."""
        for b in blocks:
            if b in self._ref:
                self._dirty.add(b)

    def is_dirty(self, block: int) -> bool:
        """Whether a block is quarantine-poisoned (pending its scrub).  The
        tiering paths refuse to demote dirty blocks — copying possibly
        poisoned KV into the host tier would outlive the device scrub."""
        return block in self._dirty

    def pop_pending_scrub(self) -> List[int]:
        """Dirty blocks whose last reference released since the previous
        drain.  The caller (the engine) zeroes them on device and hands them
        back via :meth:`finish_scrub`; until then they are NOT allocatable."""
        out, self._pending_scrub = self._pending_scrub, []
        for b in out:
            self._dirty.discard(b)
        return out

    def finish_scrub(self, blocks: List[int]) -> None:
        """Return scrubbed blocks to the free list."""
        self._free.extend(blocks)


class HostBlockPool:
    """Host-DRAM mirror of the device block pool: one numpy leaf per pool
    leaf with the same ``[L, num_blocks, block_size, *rest]`` layout (fp and
    int8 codes+scale alike), plus a LIFO free-list allocator over ids
    ``0..num_blocks-1`` (no null block — host blocks are never gathered
    through a block table, only copied wholesale).

    There are no refcounts: a host block has exactly one owner at a time —
    either a preempted request's demoted KV or a cold prefix-cache chain
    entry — so ownership transfers are plain id hand-offs.  The scrub
    contract from the device tier carries over in synchronous form: a block
    marked dirty (:meth:`mark_dirty`) is zeroed at :meth:`free` time, before
    it can ever be re-allocated, because host writes are cheap and need no
    deferred drain stage."""

    def __init__(self, pool: dict, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"host tier needs >= 1 block, got {num_blocks}")
        self.num_blocks = num_blocks
        self.leaves: Dict[str, np.ndarray] = {
            name: np.zeros(
                (leaf.shape[0], num_blocks) + tuple(leaf.shape[2:]),
                dtype=np.dtype(leaf.dtype),
            )
            for name, leaf in pool.items()
        }
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._used: set = set()
        self._dirty: set = set()

    @property
    def capacity(self) -> int:
        return self.num_blocks

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return len(self._used)

    @property
    def occupancy(self) -> float:
        return len(self._used) / self.num_blocks

    def block_bytes(self) -> int:
        """Bytes behind ONE host block across every leaf and layer (equal to
        the device pool's per-block footprint by construction)."""
        return sum(
            (leaf.size // self.num_blocks) * leaf.dtype.itemsize
            for leaf in self.leaves.values()
        )

    def pool_bytes(self) -> int:
        return sum(leaf.size * leaf.dtype.itemsize for leaf in self.leaves.values())

    def used_bytes(self) -> int:
        return len(self._used) * self.block_bytes()

    def alloc(self, n: int = 1) -> List[int]:
        """Pop ``n`` free host blocks; all-or-nothing like the device
        allocator so a failed demotion never strands a partial grant."""
        if n < 0:
            raise ValueError(f"alloc count must be >= 0, got {n}")
        if n > len(self._free):
            raise BlockOutOfMemory(
                f"host tier needs {n} blocks, {len(self._free)} free of {self.num_blocks}"
            )
        out = [self._free.pop() for _ in range(n)]
        self._used.update(out)
        return out

    def mark_dirty(self, ids: List[int]) -> None:
        """Mark host blocks as quarantine-poisoned: they are zeroed at free
        time, before any reuse (the host half of the two-tier scrub)."""
        for i in ids:
            if i in self._used:
                self._dirty.add(i)

    def free(self, ids: List[int]) -> None:
        """Return host blocks to the free list, zero-scrubbing dirty ones
        synchronously.  Freeing an unallocated id is a hard error (tier
        bookkeeping corruption)."""
        for i in ids:
            if i not in self._used:
                raise ValueError(f"host double free / foreign block: {i}")
            self._used.discard(i)
            if i in self._dirty:
                self._dirty.discard(i)
                for leaf in self.leaves.values():
                    leaf[:, i] = 0
            self._free.append(i)


class PrefixCache:
    """Content-addressed cache of full prompt blocks for cross-request
    sharing (see the module docstring for the chain-hash identity and the
    copy-on-write tail rule).

    The cache holds ONE allocator reference per cached block, so a finished
    request's prefix blocks survive it; :meth:`evict` releases cache-only
    blocks LRU-first when the allocator needs room.  Evicting a middle chain
    block strands the later entries of that chain (a lookup stops at the
    first miss); they age out of the same LRU order.

    With a host tier attached (:meth:`attach_tier`), eviction pressure
    **demotes** cold cache-only chains to host DRAM instead of dropping them
    — the chain key moves to a host-side LRU map, the device block is freed,
    and a later lookup that walks onto the demoted key **promotes** it back
    (one device block allocation + wholesale H2D copy) and keeps sharing.
    The chain-hash identity and the device-side refcounts are untouched; the
    effective prefix cache simply grows past HBM by the host pool's size.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int):
        self.allocator = allocator
        self.block_size = block_size
        self._entries: "OrderedDict[bytes, int]" = OrderedDict()  # LRU: oldest first
        self._by_block: Dict[int, bytes] = {}
        # Cache-only block count, maintained incrementally: the scheduler
        # reads free_blocks (and the gauges occupancy) several times per
        # tick, so an O(cached-blocks) refcount scan here would put an O(N)
        # walk on the per-tick host path the allocator promises is O(1).
        self._reclaimable = 0
        # Host tier: chain key -> host block id, LRU oldest first.  Entries
        # live in exactly one of _entries / _host_entries at a time.
        self._host_entries: "OrderedDict[bytes, int]" = OrderedDict()
        self._kv: Optional["PagedKVCache"] = None
        # Monotonic tiering counters; the engine publishes per-tick deltas.
        self.host_demotions = 0
        self.host_promotions = 0
        self.host_drops = 0  # evictions that fell through to a plain drop
        allocator.attach_cache(self)

    def attach_tier(self, kv: "PagedKVCache") -> None:
        """Enable host-tier spillover through ``kv`` (which must have its
        host tier enabled): eviction demotes instead of dropping, and lookups
        promote demoted chain entries back on a hit."""
        if kv.host is None:
            raise ValueError("attach_tier requires an enabled host tier")
        self._kv = kv

    @staticmethod
    def chain_keys(tokens: List[int], block_size: int, limit: Optional[int] = None) -> List[bytes]:
        """Chain hash per FULL block of ``tokens``: ``h_i`` digests every
        token up to and including block ``i`` — the identity of a block's
        K/V content, which depends on the entire prefix."""
        nb = len(tokens) // block_size
        if limit is not None:
            nb = min(nb, limit)
        h = hashlib.sha256()
        keys = []
        for i in range(nb):
            h.update(np.asarray(
                tokens[i * block_size:(i + 1) * block_size], np.int64
            ).tobytes())
            keys.append(h.digest())
        return keys

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def reclaimable_count(self) -> int:
        """Cached blocks whose ONLY reference is this cache (free capacity
        in waiting).  O(1): tracked on the allocator's 1<->2 refcount
        transitions of cached blocks and this cache's own entry churn."""
        return self._reclaimable

    @property
    def host_count(self) -> int:
        """Chain entries currently demoted to the host tier."""
        return len(self._host_entries)

    def _note_first_reader(self, block: int) -> None:
        """Allocator hook: a block at refcount 1 gained a reader — if that
        lone reference was ours, the block just stopped being reclaimable."""
        if block in self._by_block:
            self._reclaimable -= 1

    def _note_last_reader_left(self, block: int) -> None:
        """Allocator hook: a block dropped back to refcount 1 — if the
        survivor is our reference, the block is reclaimable again."""
        if block in self._by_block:
            self._reclaimable += 1

    def lookup(self, tokens: List[int], max_rows: int) -> Tuple[List[int], int, Optional[int]]:
        """Longest cached chain over the full blocks of ``tokens``, capped at
        ``max_rows`` reusable rows.  Returns ``(blocks, rows, cow_src)``:
        ``blocks`` are the wholesale-shared full blocks (each retained for
        the caller), ``rows = len(blocks) * block_size``, and ``cow_src`` —
        also retained, the caller MUST release it after copying — is the next
        chain block when a partial tail (``max_rows % block_size`` rows of
        it) is still reusable via copy-on-write."""
        bs = self.block_size
        matched: List[Tuple[bytes, int]] = []
        for key in self.chain_keys(tokens, bs, limit=blocks_for_tokens(max_rows, bs)):
            block = self._entries.get(key)
            if block is None:
                block = self._promote_entry(key)
            if block is None:
                break
            # Retain NOW, not in a second pass: promoting the NEXT key
            # allocates a device block, and that allocation may evict
            # cache-only blocks — an unretained earlier match could be freed
            # out from under this walk.
            self.allocator.retain(block)
            self._entries.move_to_end(key)
            matched.append((key, block))
        if not matched:
            return [], 0, None
        full_usable = min(len(matched), max_rows // bs)
        blocks = [block for _, block in matched[:full_usable]]
        extra = matched[full_usable:]
        cow_src = None
        if extra and max_rows % bs:
            cow_src = extra[0][1]
            extra = extra[1:]
        for _, block in extra:  # matched past the reusable window: release
            self.allocator.free([block])
        return blocks, full_usable * bs, cow_src

    def _promote_entry(self, key: bytes) -> Optional[int]:
        """Promote a host-demoted chain entry back to the device tier on a
        lookup hit: allocate one device block (may itself evict LRU cache
        blocks; a device OOM degrades to a miss), copy the host block's rows
        back, and re-enter the device LRU.  Returns the device block, or
        ``None`` when the key is not host-resident or no device block is
        reachable."""
        if self._kv is None:
            return None
        host_id = self._host_entries.get(key)
        if host_id is None:
            return None
        try:
            block = self.allocator.alloc(1)[0]
        except BlockOutOfMemory:
            return None
        self._kv.promote([host_id], [block])
        del self._host_entries[key]
        # Same ordering invariant as register(): the alloc granted refcount
        # 1 and that lone reference is now the cache's, so the block is
        # reclaimable until the caller retains it (the 1->2 hook then
        # decrements — net zero).
        self._entries[key] = block
        self._by_block[block] = key
        self._reclaimable += 1
        self.host_promotions += 1
        return block

    def register(self, chain_key: bytes, block: int) -> bool:
        """Publish a fully-written prompt block under its chain key; returns
        False when the key (a concurrent prefill of the same prefix) or the
        block is already cached.  The block must never be written again —
        the engine registers only blocks entirely below ``cache_len``, and
        writes only move forward from there."""
        if chain_key in self._entries or block in self._by_block:
            return False
        self.allocator.retain(block)
        self._entries[chain_key] = block
        self._by_block[block] = chain_key
        return True

    def evict(self, n: int) -> int:
        """Release up to ``n`` cache-only blocks, least recently used first;
        returns how many were released.  Blocks with live readers are never
        touched.  With a host tier attached, a clean victim's content is
        demoted to host DRAM first (the chain key moves to the host LRU map)
        so the eviction costs a D2H copy instead of the cached prefix —
        only when the host tier is also full (or the block is quarantine
        dirty) does the entry drop outright."""
        released = 0
        for key in list(self._entries):
            if released >= n:
                break
            block = self._entries[key]
            if self.allocator.refcount(block) != 1:
                continue
            if self._kv is not None:
                host_ids = (
                    self._kv.try_demote([block])
                    if not self.allocator.is_dirty(block)
                    else None  # never spill quarantine-dirty rows to host
                )
                if host_ids is not None:
                    self._host_entries[key] = host_ids[0]
                    self._host_entries.move_to_end(key)
                    self.host_demotions += 1
                else:
                    self.host_drops += 1
            del self._entries[key]
            del self._by_block[block]
            self._reclaimable -= 1
            self.allocator.free([block])
            released += 1
        return released

    def drop_host_entries(self, n: Optional[int] = None) -> int:
        """Free up to ``n`` host-demoted chain entries (all of them when
        ``n`` is None), least recently used first; returns how many were
        dropped.  The engine uses this to reclaim host room for request
        migrations (a live request outranks a cold cached prefix) and to
        leave the host tier empty at drain."""
        dropped = 0
        for key in list(self._host_entries):
            if n is not None and dropped >= n:
                break
            host_id = self._host_entries.pop(key)
            if self._kv is not None and self._kv.host is not None:
                self._kv.host.free([host_id])
            dropped += 1
        return dropped

    def invalidate_blocks(self, blocks: List[int]) -> None:
        """Drop cached entries for ``blocks`` (quarantine: no new sharers may
        attach to a possibly-poisoned block) and release the cache's
        reference."""
        for b in blocks:
            key = self._by_block.pop(b, None)
            if key is not None:
                del self._entries[key]
                if self.allocator.refcount(b) == 1:
                    self._reclaimable -= 1
                self.allocator.free([b])


class PagedKVCache:
    """The device-side block pool plus its allocator.

    ``init_cache`` is a model family's cache constructor (``models/*.py``);
    the pool leaves are derived from its batch-1 template, so the fp and
    int8-quantized layouts both page without special cases
    (:func:`accelerate_tpu.models.generation.make_paged_pool`).

    With ``num_host_blocks > 0`` (or a later :meth:`enable_host_tier`) the
    cache carries a second, host-DRAM tier mirroring the pool's leaf layout;
    :meth:`demote` and :meth:`promote` move whole blocks between the tiers
    as batched copies on the host path between dispatches.
    """

    def __init__(
        self,
        init_cache: Callable,
        config,
        num_blocks: int,
        block_size: int,
        num_host_blocks: int = 0,
    ):
        from ..models.generation import make_paged_pool

        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = block_size
        self.allocator = BlockAllocator(num_blocks)
        self.pool = make_paged_pool(init_cache, config, num_blocks, block_size)
        self.host: Optional[HostBlockPool] = None
        if num_host_blocks:
            self.enable_host_tier(num_host_blocks)

    def enable_host_tier(self, num_host_blocks: int) -> HostBlockPool:
        """Attach a host-DRAM block pool of ``num_host_blocks`` blocks with
        the same leaf layout as the device pool."""
        if self.host is not None:
            raise ValueError("host tier already enabled")
        self.host = HostBlockPool(self.pool, num_host_blocks)
        return self.host

    def host_can_fit(self, n: int) -> bool:
        """Whether a demotion of ``n`` blocks can be granted right now.
        False when no host tier is attached, when the tier lacks room, or
        when the ``SERVING_HOST_FULL`` fault arm forces the host-exhausted
        fallback paths for testing."""
        if self.host is None or self.host.free_blocks < n:
            return False
        from ..resilience import faultinject

        if faultinject.serving_host_full():
            return False
        return True

    def demote(self, blocks: List[int]) -> List[int]:
        """Copy device ``blocks`` into freshly-allocated host blocks (one
        batched D2H gather per leaf) and return the host ids, in order.  The
        caller keeps its device references and decides when to release them
        — demotion is a copy, not a move, so refcounted sharing survives.
        Raises :class:`BlockOutOfMemory` when the host tier cannot fit."""
        from ..models.generation import demote_pool_blocks

        if not blocks:
            return []
        if not self.host_can_fit(len(blocks)):
            free = self.host.free_blocks if self.host is not None else 0
            cap = self.host.capacity if self.host is not None else 0
            raise BlockOutOfMemory(
                f"host tier cannot fit {len(blocks)} blocks ({free} free of {cap})"
            )
        host_ids = self.host.alloc(len(blocks))
        rows = demote_pool_blocks(self.pool, blocks)
        for name, leaf in self.host.leaves.items():
            leaf[:, host_ids] = rows[name]
        return host_ids

    def try_demote(self, blocks: List[int]) -> Optional[List[int]]:
        """:meth:`demote`, returning ``None`` instead of raising when the
        host tier cannot fit (the waterfall callers fall through to the
        free/drop path)."""
        if not self.host_can_fit(len(blocks)):
            return None
        return self.demote(blocks)

    def promote(self, host_ids: List[int], dst_blocks: List[int]) -> None:
        """Copy host blocks back into already-allocated device blocks
        ``dst_blocks`` (one batched H2D scatter per leaf) and free the host
        ids.  The caller owns ``dst_blocks``' references."""
        from ..models.generation import promote_pool_blocks

        if len(host_ids) != len(dst_blocks):
            raise ValueError(
                f"promote id mismatch: {len(host_ids)} host vs {len(dst_blocks)} device"
            )
        if not host_ids:
            return
        if self.host is None:
            raise ValueError("promote without a host tier")
        rows = {name: leaf[:, host_ids] for name, leaf in self.host.leaves.items()}
        self.pool = promote_pool_blocks(self.pool, rows, dst_blocks)
        self.host.free(host_ids)

    @property
    def leaf_names(self) -> list:
        return sorted(self.pool)

    def pool_bytes(self) -> int:
        return sum(leaf.size * leaf.dtype.itemsize for leaf in self.pool.values())

    def block_bytes(self) -> int:
        """Bytes of pool data behind ONE block across every leaf and layer —
        the unit of the ``serving.decode_gather_bytes`` accounting."""
        num_blocks = next(iter(self.pool.values())).shape[1]
        return sum(
            (leaf.size // num_blocks) * leaf.dtype.itemsize
            for leaf in self.pool.values()
        )
