"""Serving smoke: continuous batching equivalence + dispatch proof on CPU.

Run via ``make serving-smoke`` (or ``python -m accelerate_tpu.serving.smoke``).
On a forced 8-device CPU mesh, a staggered mix of requests (heterogeneous
prompt lengths and token budgets, submitted while earlier requests are
mid-flight, through a pool tight enough to force at least one preemption)
flows through the continuous-batching engine.  Asserts:

- **equivalence** — every request's output is token-identical to the offline
  ``generate_loop`` for that prompt alone;
- **1 fused dispatch per decode step** — the ``serving.decode_dispatches``
  telemetry counter delta equals the engine's decode tick count and never
  exceeds ticks;
- **preemption exercised** — the tight pool actually evicted someone
  (otherwise the smoke is not covering the hard path);
- **SLO metrics land** — ``serving.*`` counters/gauges/histograms are in the
  registry snapshot and the telemetry report renders the serving block.

Exit code 0 only when every assertion holds.
"""

from __future__ import annotations

import os
import sys
import tempfile


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ.setdefault("ACCELERATE_TPU_COMPILE_CACHE", "")
    os.environ.setdefault("ACCELERATE_TPU_SENTINEL_PROFILE", "0")

    import numpy as np

    import jax
    import jax.numpy as jnp

    from accelerate_tpu import telemetry
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import gpt2
    from accelerate_tpu.telemetry.report import format_report, summarize
    from accelerate_tpu.utils.dataclasses import ParallelismConfig

    tel = telemetry.enable(dir=tempfile.mkdtemp(prefix="atpu_serving_smoke_"))
    assert jax.device_count() == 8, f"expected 8 CPU devices, got {jax.device_count()}"
    acc = Accelerator(parallelism_config=ParallelismConfig(dp=8))

    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg, jax.random.key(0))

    rng = np.random.default_rng(0)
    lengths = [5, 14, 3, 22, 9, 7]
    budgets = [7, 4, 10, 3, 6, 8]
    prompts = [list(rng.integers(0, cfg.vocab_size, size=n)) for n in lengths]

    print("# serving smoke: offline oracle (generate_loop, greedy)")
    want = {}
    for i, (p, m) in enumerate(zip(prompts, budgets)):
        out = gpt2.generate(params, jnp.asarray([p], jnp.int32), cfg, max_new_tokens=m)
        want[i] = [int(t) for t in np.asarray(out[0])]

    # Tight pool (10 usable blocks of 4 rows vs ~6 in-flight sequences) so
    # the run must exercise preemption, not just the happy path.
    engine = acc.prepare_serving(
        gpt2.apply_cached, gpt2.init_cache, params, cfg,
        block_size=4, num_blocks=11, max_slots=4, prefill_chunk=8,
        max_blocks_per_seq=8,
    )

    counter = tel.registry.counter("serving.decode_dispatches")
    d0 = counter.value
    ids = {}
    # Staggered arrivals: requests join while the decode batch is in flight.
    for k, i in enumerate(rng.permutation(len(prompts))):
        ids[engine.submit(prompts[i], budgets[i])] = int(i)
        if k % 2 == 1:
            engine.step()
    outputs = engine.run(max_ticks=2000)
    stats = engine.stats()
    print(f"# serving smoke: stats {stats}")

    for rid, out in outputs.items():
        assert out == want[ids[rid]], (
            f"request {rid} (prompt #{ids[rid]}) diverged from generate_loop:\n"
            f"  got  {out}\n  want {want[ids[rid]]}"
        )
    print(f"# serving smoke: {len(outputs)} requests token-identical to generate_loop")

    delta = counter.value - d0
    assert delta == engine.decode_dispatches, (
        f"telemetry counted {delta} decode dispatches, engine ran "
        f"{engine.decode_dispatches}"
    )
    assert delta <= engine.ticks, f"{delta} decode dispatches > {engine.ticks} ticks"
    print(f"# serving smoke: {delta} fused decode dispatches over {engine.ticks} ticks (<= 1/step)")

    assert stats["preempted"] > 0, "tight pool never preempted — smoke lost its hard path"

    snap = tel.registry.snapshot()
    for key in (
        "serving.requests", "serving.completed", "serving.tokens",
        "serving.decode_dispatches", "serving.prefill_dispatches",
        "serving.active_slots", "serving.queue_depth", "serving.blocks_used",
        "serving.block_occupancy", "serving.preempted",
        "serving.ttft_ms.count", "serving.inter_token_ms.count",
        "serving.queue_wait_ms.count",
    ):
        assert key in snap, f"metric {key} missing from registry snapshot"
    assert snap["serving.completed"] == len(prompts)
    assert snap["serving.ttft_ms.count"] == len(prompts)

    telemetry.disable()  # flush the final snapshot record
    from accelerate_tpu.telemetry.report import load_records

    report = format_report(summarize(load_records(tel.dir)))
    assert "serving engine (continuous batching):" in report, "report lacks serving block"
    assert "TTFT: p50" in report
    print("# serving smoke: serving.* gauges render in the telemetry report")
    print("\n".join(line for line in report.splitlines() if "serving" in line or "TTFT" in line))
    print("serving smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
