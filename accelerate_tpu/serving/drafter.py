"""Draft proposers for speculative serving decode (``ServingConfig.spec_tokens``).

The engine's draft-then-verify tick needs up to ``k`` candidate next tokens
per slot BEFORE the fused verify dispatch (see ``serving/engine.py``).
Correctness never depends on the drafts: the target model verifies every
window position in-dispatch and greedy acceptance keeps outputs
token-identical to greedy decoding with the target alone.  Draft quality
only moves the *acceptance rate*, i.e. how many tokens each dispatch lands.

Two built-ins behind one duck-typed interface —
``propose(feed: Sequence[int], k: int) -> list[int]`` returns up to ``k``
candidate continuations of ``feed`` (prompt + everything emitted so far),
possibly fewer, possibly empty (empty ⇒ the slot contributes no drafts and
the tick degrades gracefully toward plain greedy):

- :class:`NgramDrafter` (the default) — prompt-lookup / n-gram drafting:
  match the feed's trailing n-gram against its own earlier occurrences and
  propose the continuation that followed last time.  Pure host-side list
  scanning — no second model to shard, no extra device dispatch — and it
  targets exactly the workloads speculative serving is for (templated,
  retrieval-augmented, and code traffic re-emits its own substrings; so do
  the repetition loops greedy decoding itself falls into).
- :class:`DraftModelDrafter` — a small draft model proposes greedily via
  bucketed full forwards (jit-cached per power-of-two bucket).  The
  draft-model option behind the same interface; a production deployment
  would route the draft model through its own cached engine, but the
  interface — and everything downstream of it — is identical.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["NgramDrafter", "DraftModelDrafter"]


class NgramDrafter:
    """Prompt-lookup drafts: propose the continuation that followed the most
    recent earlier occurrence of the feed's trailing n-gram.

    Tries match lengths ``max_ngram`` down to ``min_ngram`` (longer matches
    first — higher precision), scanning for the *latest* earlier occurrence
    (recency beats distance for repetitive decode loops).  Among occurrences
    of the same n-gram, the latest one whose continuation is a full ``k``
    tokens wins over a later-but-truncated one: in a short repetition loop
    the most recent match sits at the very end of the feed where the
    continuation runs off the list after one token, while a match one period
    earlier yields the same continuation at full length.  The proposed
    continuation may run past the historical match back into the suffix
    region; that is fine — it is still the verbatim historical continuation.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1:
            raise ValueError(f"min_ngram must be >= 1, got {min_ngram}")
        if max_ngram < min_ngram:
            raise ValueError(
                f"max_ngram ({max_ngram}) must be >= min_ngram ({min_ngram})"
            )
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, feed: Sequence[int], k: int) -> List[int]:
        toks = list(feed)
        n_feed = len(toks)
        if k <= 0 or n_feed < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, n_feed - 1), self.min_ngram - 1, -1):
            suffix = toks[-n:]
            best: List[int] = []
            # Latest occurrence whose match ends strictly before the end of
            # the feed, so at least one continuation token exists.  Keep
            # scanning earlier occurrences until one yields a full-length
            # continuation (the latest match truncates at the feed end when
            # the loop period is short).
            for i in range(n_feed - n - 1, -1, -1):
                if toks[i : i + n] == suffix:
                    cont = toks[i + n : i + n + k]
                    if len(cont) >= k:
                        return [int(t) for t in cont]
                    if len(cont) > len(best):
                        best = [int(t) for t in cont]
            if best:
                return best
        return []


class DraftModelDrafter:
    """Greedy proposals from a small draft model's full forward.

    ``apply`` is a model-family forward ``apply(params, ids, config,
    attention_mask=...) -> logits [B, S, V]`` (``gpt2.apply`` /
    ``llama.apply``).  Feeds are right-padded to power-of-two buckets so the
    jitted forward compiles once per bucket, with the padding masked out of
    the keys; the next token is the argmax at the last real position.
    """

    def __init__(self, apply, params, config, max_len: Optional[int] = None):
        self._apply = apply
        self.params = params
        self.config = config
        self._max_len = int(max_len) if max_len else getattr(config, "max_seq_len", None)
        self._jitted: Dict[int, object] = {}

    @staticmethod
    def _bucket(n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return b

    def _fn(self, bucket: int):
        fn = self._jitted.get(bucket)
        if fn is None:
            apply, config = self._apply, self.config

            def fwd(params, ids, n_real):
                mask = (jnp.arange(ids.shape[1]) < n_real)[None]
                logits = apply(params, ids, config, attention_mask=mask)
                row = jax.lax.dynamic_index_in_dim(
                    logits[0], n_real - 1, axis=0, keepdims=False
                )
                return jnp.argmax(row, axis=-1).astype(jnp.int32)

            fn = jax.jit(fwd)
            self._jitted[bucket] = fn
        return fn

    def propose(self, feed: Sequence[int], k: int) -> List[int]:
        toks = [int(t) for t in feed]
        out: List[int] = []
        for _ in range(max(int(k), 0)):
            n = len(toks)
            if self._max_len is not None and n >= self._max_len:
                break
            bucket = self._bucket(n)
            if self._max_len is not None:
                bucket = min(bucket, self._max_len)
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :n] = toks
            nxt = int(self._fn(bucket)(self.params, ids, jnp.int32(n)))
            out.append(nxt)
            toks.append(nxt)
        return out
