"""Continuous-batching request scheduler: admission queue, slot map, preemption.

State machine per request::

    QUEUED --admit--> PREFILLING --last chunk--> DECODING --max_new reached--> DONE
       ^                  |                          |
       +---- preempt -----+------------ preempt ----+

A fixed number of **slots** (the fused decode step's static batch axis) holds
the in-flight requests; new requests join as others finish — the decode batch
never drains to refill.  Preemption is the block-pressure valve: when the
allocator runs dry mid-flight, the most recently admitted request is evicted
(LIFO — the oldest request always makes progress, so the policy cannot
livelock), its blocks are freed, and it re-enters the queue FRONT carrying
the tokens it already emitted.  Re-prefilling ``prompt + emitted`` rebuilds a
bit-identical cache (K/V rows depend only on the prefix), so preemption never
changes a request's output — the equivalence oracle in
``tests/test_serving.py`` covers exactly this path.

With the engine's KV host tier enabled, preemption first offers the victim to
the ``on_migrate_out`` hook: the engine demotes the victim's blocks to host
DRAM (stashing the host ids on the request) before the device references are
released, and re-admission promotes them back and resumes decode with zero
re-prefill dispatches.  The free-and-re-prefill path above survives as the
fallback whenever the host tier cannot take the blocks.

The scheduler is pure host-side bookkeeping: admission/preemption decisions
happen between dispatches and the jitted decode step never sees them (slots
simply flip their active mask)."""

from __future__ import annotations

import itertools
import time
from collections import deque
from enum import Enum
from typing import Callable, Deque, Dict, List, Optional

from .blocks import BlockAllocator, BlockOutOfMemory, blocks_for_tokens

__all__ = ["Request", "RequestState", "Scheduler"]


class RequestState(Enum):
    QUEUED = "queued"
    PREFILLING = "prefilling"
    DECODING = "decoding"
    DONE = "done"


class Request:
    """One serving request plus its lifecycle bookkeeping.

    ``emitted`` accumulates generated tokens across preemptions; the tokens a
    slot must (re)prefill are always ``prompt + emitted`` — the final chunk's
    logits produce the next emitted token, whether that is the first token of
    a fresh request or the resume point of a preempted one.

    Deadlines are relative to ``arrival_t``: ``ttft_deadline_ms`` bounds the
    wait for the FIRST token, ``deadline_ms`` bounds the whole request.  The
    engine sheds expired queued requests before spending a prefill chunk on
    them and cancels expired in-flight ones (blocks freed) — see
    :meth:`ServingEngine.step`."""

    _ids = itertools.count()

    def __init__(
        self,
        prompt_ids: List[int],
        max_new_tokens: int,
        arrival_t: Optional[float] = None,
        tag: Optional[str] = None,
        ttft_deadline_ms: Optional[float] = None,
        deadline_ms: Optional[float] = None,
    ):
        if max_new_tokens < 0:
            raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
        if not prompt_ids:
            raise ValueError("empty prompt")
        self.id = next(Request._ids)
        self.prompt = [int(t) for t in prompt_ids]
        self.max_new_tokens = int(max_new_tokens)
        self.arrival_t = time.monotonic() if arrival_t is None else arrival_t
        self.tag = tag
        self.ttft_deadline_ms = ttft_deadline_ms
        self.deadline_ms = deadline_ms
        self.emitted: List[int] = []
        self.state = RequestState.QUEUED
        # SLO timeline (monotonic seconds; None until the event happens).
        self.admit_t: Optional[float] = None  # FIRST admission only
        self.first_token_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        self.inter_token_ms: List[float] = []
        self.preemptions = 0
        # Re-queue wait accounting: ``admit_t`` records the FIRST admission
        # only, so time spent re-queued after a preemption would otherwise be
        # invisible to the queue-wait metrics.  ``requeued_t`` marks each
        # re-queue; re-admission moves the elapsed wait into
        # ``requeue_waits_ms``, which the engine drains into the
        # ``serving.requeue_wait_ms`` histogram (one sample per re-admission).
        self.requeued_t: Optional[float] = None
        self.requeue_waits_ms: List[float] = []
        # KV host-tier residency (engine/blocks.py tiering): while the
        # request sits re-queued after a preemption-as-migration, its cache
        # lives in host DRAM as ``demoted_blocks`` (host block ids, table
        # order) covering ``demoted_rows`` cache rows with the prefix-cache
        # registration cursor parked at ``demoted_registered``.  Re-admission
        # promotes the blocks back and restores the slot exactly; the fields
        # clear on promotion (or on the host-full re-prefill fallback).
        self.demoted_blocks: Optional[List[int]] = None
        self.demoted_rows = 0
        self.demoted_registered = 0
        # Robustness accounting: prefill dispatches this request consumed
        # (the zero-re-prefill oracle for migrated resumes), migrations it
        # survived, and times the host tier was full so it fell back to a
        # plain re-prefill.
        self.prefill_dispatches = 0
        self.migrations = 0
        self.fallback_reprefills = 0

    def pop_requeue_waits(self) -> List[float]:
        out, self.requeue_waits_ms = self.requeue_waits_ms, []
        return out

    def expired(self, now: float) -> Optional[str]:
        """``"deadline"`` / ``"ttft"`` when the matching deadline has passed
        (total first: a request past its overall budget is expired even if
        its first token already landed), else None."""
        elapsed_ms = (now - self.arrival_t) * 1e3
        if self.deadline_ms is not None and elapsed_ms > self.deadline_ms:
            return "deadline"
        if (
            self.ttft_deadline_ms is not None
            and self.first_token_t is None
            and elapsed_ms > self.ttft_deadline_ms
        ):
            return "ttft"
        return None

    @property
    def to_feed(self) -> List[int]:
        return self.prompt + self.emitted

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.emitted)

    @property
    def output(self) -> List[int]:
        # Today the served output IS the feed sequence (prompt echoed +
        # everything emitted); keep one definition so they can't diverge.
        return self.to_feed

    def note_token(self, now: float) -> None:
        """Record one emitted token's latency sample (TTFT for the first,
        inter-token for the rest)."""
        if self.first_token_t is None:
            self.first_token_t = now
        elif self.last_token_t is not None:
            self.inter_token_ms.append((now - self.last_token_t) * 1e3)
        self.last_token_t = now


class _Slot:
    """One decode-batch lane: the bound request, its block table, and how many
    cache rows have been written.  ``registered_blocks`` is the prefix-cache
    registration cursor — leading full blocks up to it are already published
    (or were attached FROM the cache) and are never re-registered."""

    __slots__ = ("request", "blocks", "cache_len", "admit_seq", "registered_blocks")

    def __init__(self, request: Request, admit_seq: int):
        self.request = request
        self.blocks: List[int] = []
        self.cache_len = 0
        self.admit_seq = admit_seq
        self.registered_blocks = 0


class Scheduler:
    """Slot map + admission queue over a shared :class:`BlockAllocator`."""

    def __init__(
        self,
        allocator: BlockAllocator,
        num_slots: int,
        block_size: int,
        max_blocks_per_seq: int,
        prefill_chunk: int,
        spec_overshoot: int = 0,
    ):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.allocator = allocator
        self.num_slots = num_slots
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.prefill_chunk = prefill_chunk
        self.spec_overshoot = max(int(spec_overshoot), 0)
        self.queue: Deque[Request] = deque()
        self.slots: Dict[int, _Slot] = {}  # slot index -> lane
        self._admit_seq = itertools.count()
        self.preempted_count = 0
        # Observer hook: called with the evicted Request on every preemption
        # (the engine wires its tracer here — one site sees the LIFO victim,
        # the self-preemption, and the drain flavors alike).
        self.on_preempt: Optional[Callable[[Request], None]] = None
        # Migration hook: offered the victim's slot BEFORE its blocks are
        # freed.  Returning True means the hook demoted the KV to the host
        # tier and released the device references itself (the request now
        # carries ``demoted_blocks``); False falls through to the plain
        # free-and-re-prefill preemption.
        self.on_migrate_out: Optional[Callable[[_Slot], bool]] = None

    # -- capacity validation -------------------------------------------------

    def max_rows(self, request: Request) -> int:
        """Worst-case cache rows the request ever needs: the prompt plus every
        generated token except the last (which is emitted but never fed),
        plus the speculative verify window's overshoot (``spec_overshoot`` is
        the engine's draft window ``k`` — a verify dispatch writes ``k+1``
        rows starting at the last fed position, so the final dispatch can
        write ``k`` rows past the plain-greedy extent), rounded up to the
        prefill-chunk boundary a re-admission after maximal preemption would
        pad to."""
        rows = (
            len(request.prompt)
            + max(request.max_new_tokens - 1, 0)
            + self.spec_overshoot
        )
        chunks = blocks_for_tokens(rows, self.prefill_chunk)
        return chunks * self.prefill_chunk

    def validate(self, request: Request) -> None:
        """Reject requests the engine geometry can never serve (otherwise a
        sole OOM-ing request would preempt itself forever)."""
        need = blocks_for_tokens(self.max_rows(request), self.block_size)
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"request needs {need} blocks > max_blocks_per_seq "
                f"{self.max_blocks_per_seq} (prompt {len(request.prompt)} + "
                f"max_new {request.max_new_tokens}, block_size {self.block_size})"
            )
        if need > self.allocator.capacity:
            raise ValueError(
                f"request needs {need} blocks > pool capacity "
                f"{self.allocator.capacity}"
            )

    # -- queue / admission ---------------------------------------------------

    def submit(self, request: Request) -> None:
        self.validate(request)
        self.queue.append(request)

    def free_slot_indices(self) -> List[int]:
        return [i for i in range(self.num_slots) if i not in self.slots]

    def admit(self, now: float) -> List[int]:
        """Move queue-head requests into free slots while blocks for their
        first prefill chunk are available.  FIFO order is preserved —
        skipping the head to admit a smaller request behind it would starve
        long prompts."""
        admitted = []
        for idx in self.free_slot_indices():
            if not self.queue:
                break
            head = self.queue[0]
            first_chunk = min(len(head.to_feed), self.prefill_chunk)
            if blocks_for_tokens(first_chunk, self.block_size) > self.allocator.free_blocks:
                break
            self.queue.popleft()
            head.state = RequestState.PREFILLING
            if head.admit_t is None:
                head.admit_t = now
            if head.requeued_t is not None:
                head.requeue_waits_ms.append((now - head.requeued_t) * 1e3)
                head.requeued_t = None
            self.slots[idx] = _Slot(head, next(self._admit_seq))
            admitted.append(idx)
        return admitted

    def cancel_queued(self, request: Request) -> None:
        """Remove a QUEUED request (deadline shed); the caller completes it
        with its error status.  Raises ValueError when it is not queued."""
        self.queue.remove(request)

    # -- preemption ----------------------------------------------------------

    def preempt_one(self) -> Optional[int]:
        """Evict the most recently admitted in-flight request: free its
        blocks, push it back onto the queue FRONT (it keeps priority — it
        already waited), carrying its emitted tokens.  Returns the freed slot
        index, or None when nothing is in flight."""
        if not self.slots:
            return None
        return self.preempt_slot(max(self.slots, key=lambda i: self.slots[i].admit_seq))

    def preempt_slot(self, idx: int) -> int:
        """Evict slot ``idx`` specifically (the LIFO victim policy lives in
        :meth:`preempt_one`; the engine's graceful drain evicts EVERY slot):
        demote its blocks to the host tier when the ``on_migrate_out`` hook
        accepts the victim, else free them; either way the request re-enters
        the queue FRONT, emitted tokens carried."""
        slot = self.slots.pop(idx)
        migrated = False
        if slot.blocks and self.on_migrate_out is not None:
            migrated = self.on_migrate_out(slot)
        if slot.blocks and not migrated:
            self.allocator.free(slot.blocks)
        req = slot.request
        req.state = RequestState.QUEUED
        req.preemptions += 1
        req.requeued_t = time.monotonic()
        self.preempted_count += 1
        self.queue.appendleft(req)
        if self.on_preempt is not None:
            self.on_preempt(req)
        return idx

    def grow_to(self, idx: int, rows: int) -> bool:
        """Ensure slot ``idx``'s block table covers ``rows`` cache rows,
        allocating (and preempting LIFO victims) as needed.  Returns False
        when the slot itself was preempted to satisfy the growth — the caller
        must drop it from this tick."""
        slot = self.slots.get(idx)
        while slot is not None:
            need = blocks_for_tokens(rows, self.block_size) - len(slot.blocks)
            if need <= 0:
                return True
            try:
                slot.blocks.extend(self.allocator.alloc(need))
                return True
            except BlockOutOfMemory as exc:
                victim = self.preempt_one()
                if victim is None:
                    # Terminal pool exhaustion (nothing left to evict —
                    # geometry validation failed us): snapshot the ranked
                    # HBM ledger before the engine dies on this raise.
                    from ..telemetry.memledger import get_memory_ledger

                    get_memory_ledger().note_oom(
                        source="serving.admission",
                        error=exc,
                        slot=idx,
                        rows=rows,
                        free_blocks=self.allocator.free_blocks,
                        capacity=self.allocator.capacity,
                    )
                    raise
                slot = self.slots.get(idx)  # self-preemption returns None
        return False

    def finish(self, idx: int, now: float) -> Request:
        """Release slot ``idx``; the request is complete."""
        slot = self.slots.pop(idx)
        if slot.blocks:
            self.allocator.free(slot.blocks)
        req = slot.request
        req.state = RequestState.DONE
        req.finish_t = now
        return req

    # -- introspection -------------------------------------------------------

    @property
    def active(self) -> int:
        return len(self.slots)

    @property
    def pending(self) -> int:
        return len(self.queue)

    def idle(self) -> bool:
        return not self.slots and not self.queue
