"""Serving chaos campaign: the serving engine under fire, seeded.

``make serving-chaos-smoke`` (or ``python -m accelerate_tpu.serving.chaos``,
also reachable as ``python -m accelerate_tpu.resilience.chaos --mode
serving``) drives one engine lineage through every robustness front at once
— the serving analog of the training chaos campaign:

1. **overload burst** — more submissions than ``max_queue_depth`` can hold;
   the surplus must shed with :class:`AdmissionRejected` (``serving.shed``),
   exactly as many as the plan predicts;
2. **poison request** — ``ACCELERATE_TPU_FAULT_SERVING_NAN_REQUEST`` NaNs
   one request's logits inside the fused decode; it must quarantine while
   every other slot keeps decoding bit-identically;
3. **deadline storm** — a batch of already-expired requests; all must shed
   from the queue before a prefill chunk is spent on them;
4. **SIGTERM drain** — a real signal through a ``PreemptionGuard``; the
   next tick drains and the write-ahead journal persists emitted progress;
5. **SIGKILL + journal recovery** — a successor recovers the journal,
   makes progress, and is SIGKILLed mid-flight (no handler runs); a second
   successor recovers again and finishes everything.

The parent asserts, across the whole campaign:

- **token identity** — every surviving request's tokens equal the offline
  ``generate_loop`` oracle for its prompt alone, no matter which life (or
  how many journal recoveries later) completed it;
- **zero block leaks** — each life that exits cleanly reports its allocator
  free count back at full capacity;
- **no starvation** — every non-shed request reaches a terminal state
  (completed, deadline-expired, or quarantined);
- **exact fault accounting** — shed / deadline_expired / quarantined
  counts match the plan, and the SIGKILLed life really died by signal 9.

Fully deterministic for a given ``--seed`` (:func:`plan_serving_campaign`).

``--campaign tiering`` (``make tiering-chaos-smoke``) runs the **tiered**
campaign instead: the same lineage discipline pointed at the host-DRAM KV
tier.  A pool tight enough that every life preempts drives four fronts:

1. **memory-pressure life** — preemptions migrate KV blocks to host DRAM
   and re-admissions promote them back; the parent asserts real migrations
   happened, every output is token-identical to the offline oracle, and a
   migrated request that never fell back paid ZERO extra prefill
   dispatches on resume (the zero-re-prefill contract);
2. **host-full life** — ``ACCELERATE_TPU_FAULT_SERVING_HOST_FULL`` forces
   the host-exhausted path: every preemption falls back to PR 9 re-prefill
   (fallbacks > 0, promotions == 0) and stays token-identical;
3. **SIGKILL while demoted** — a victim life dies by signal 9 at the exact
   moment a request's blocks sit in host DRAM; the parent then reads the
   journal and asserts the ``tier`` record shows ``"host"`` residency;
4. **recovery** — a finisher life recovers the journal (host DRAM died with
   the victim, so it re-prefills) and finishes everything token-identically.

Both campaigns run with the host tier enabled; the classic campaign's
loose pool keeps its exact-shed accounting while exercising construction,
drain, and recovery with tiering on.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
from typing import Optional

CHILD_TIMEOUT_S = 600.0
QUEUE_DEPTH = 4
MAX_TICKS = 2000


def plan_serving_campaign(seed: int) -> dict:
    """Deterministic request mix for one campaign.  ``burst`` arrives before
    any tick, so exactly ``len(burst) - queue_depth`` requests shed (queue
    admission only happens inside ``step``).  The poison ordinal counts
    ACCEPTED submissions (shed raises before the ordinal increments):
    ``queue_depth`` burst survivors, then the poison request itself."""
    import random

    rnd = random.Random(seed)

    def prompt(n):
        return [rnd.randrange(0, 64) for _ in range(n)]

    burst = [
        {"tag": f"n{i}", "prompt": prompt(rnd.randint(3, 12)),
         "max_new": rnd.randint(3, 7)}
        for i in range(QUEUE_DEPTH + 2)
    ]
    poison = {"tag": "poison", "prompt": prompt(rnd.randint(4, 9)),
              "max_new": rnd.randint(3, 6)}
    storm = [
        {"tag": f"s{i}", "prompt": prompt(rnd.randint(3, 8)),
         "max_new": rnd.randint(2, 5), "deadline_ms": 0.0}
        for i in range(3)
    ]
    # Submitted right before the SIGTERM with zero ticks left: guaranteed
    # in-flight at the drain, so the SIGKILL-recovery leg always has real
    # work to hand across TWO journal recoveries.
    late = [
        {"tag": f"l{i}", "prompt": prompt(rnd.randint(3, 10)),
         "max_new": rnd.randint(3, 6)}
        for i in range(2)
    ]
    return {
        "seed": seed,
        "queue_depth": QUEUE_DEPTH,
        "burst": burst,
        "poison": poison,
        "poison_ordinal": QUEUE_DEPTH + 1,
        "storm": storm,
        "late": late,
        "expect_shed": [r["tag"] for r in burst[QUEUE_DEPTH:]],
        "expect_expired": [r["tag"] for r in storm],
        "survivor_tags": [r["tag"] for r in burst[:QUEUE_DEPTH]]
        + [r["tag"] for r in late],
    }


def plan_tiering_campaign(seed: int) -> dict:
    """Deterministic request mix for the tiered campaign: enough concurrent
    prompts that the 8-usable-block pool must preempt, every request sized
    to need several blocks (so a migration moves real KV state)."""
    import random

    rnd = random.Random(seed)

    def prompt(n):
        return [rnd.randrange(0, 64) for _ in range(n)]

    requests = [
        {"tag": f"t{i}", "prompt": prompt(rnd.randint(5, 12)),
         "max_new": rnd.randint(5, 8), "chunk": 4}
        for i in range(4)
    ]
    return {"seed": seed, "requests": requests}


# ---------------------------------------------------------------------------
# Lives (child-process roles)
# ---------------------------------------------------------------------------


def _build_engine(journal_path: str, queue_depth: Optional[int] = None):
    import jax
    import jax.numpy as jnp

    from ..models import gpt2
    from . import ServingConfig, ServingEngine

    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg, jax.random.key(0))
    engine = ServingEngine(
        gpt2.apply_cached, gpt2.init_cache, params, cfg,
        serving=ServingConfig(
            block_size=4, num_blocks=40, max_slots=2, prefill_chunk=8,
            max_blocks_per_seq=8, max_queue_depth=queue_depth,
            journal_path=journal_path,
            # Tiering on even in the classic campaign: the loose pool rarely
            # preempts (the exact-shed oracles stay untouched — shed is
            # queue-depth-only), but construction, drain, and recovery all
            # run with the host tier attached.
            host_blocks=16,
        ),
    )
    return engine


def _build_tiered_engine(journal_path: Optional[str] = None):
    """The tiering campaign's engine: a pool tight enough (8 usable blocks
    vs 3 slots) that preemption — and therefore migration — is guaranteed,
    with host room for every victim."""
    import jax
    import jax.numpy as jnp

    from ..models import gpt2
    from . import ServingConfig, ServingEngine

    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg, jax.random.key(0))
    return ServingEngine(
        gpt2.apply_cached, gpt2.init_cache, params, cfg,
        serving=ServingConfig(
            block_size=4, num_blocks=9, max_slots=3, prefill_chunk=4,
            max_blocks_per_seq=6, journal_path=journal_path,
            host_blocks=16,
        ),
    )


def _emit(out, record: dict) -> None:
    """One JSON line per fact, flushed immediately: a SIGKILL later must not
    lose what already happened (the parent parses whatever landed)."""
    print(json.dumps(record), file=out, flush=True)


def run_first_life(plan: dict, journal_path: str) -> int:
    """Overload burst -> poison quarantine -> deadline storm -> SIGTERM
    drain.  Every observable lands on stdout as JSON lines."""
    from ..resilience import PreemptionGuard
    from . import AdmissionRejected

    engine = _build_engine(journal_path, queue_depth=plan["queue_depth"])
    out = sys.stdout

    shed = []
    for rec in plan["burst"]:
        try:
            engine.submit(rec["prompt"], rec["max_new"], tag=rec["tag"])
        except AdmissionRejected:
            shed.append(rec["tag"])
    _emit(out, {"kind": "shed", "tags": shed})

    for _ in range(4):
        engine.step()

    # Poison request: the armed ordinal (env) matches THIS submission.
    engine.submit(
        plan["poison"]["prompt"], plan["poison"]["max_new"],
        tag=plan["poison"]["tag"],
    )
    ticks = 0
    while engine.quarantined_count < 1 and ticks < MAX_TICKS:
        engine.step()
        ticks += 1
    assert engine.quarantined_count == 1, "poison request never quarantined"

    # Deadline storm: drain the queue enough that overload shedding cannot
    # race the deadline shed (the storm must die by deadline, not depth).
    for rec in plan["storm"]:
        ticks = 0
        while engine.sched.pending >= plan["queue_depth"] and ticks < MAX_TICKS:
            engine.step()
            ticks += 1
        engine.submit(
            rec["prompt"], rec["max_new"], tag=rec["tag"],
            deadline_ms=rec["deadline_ms"],
        )
    engine.step()  # expiry runs before admission: the whole storm sheds here

    # Late arrivals: no tick runs between these and the SIGTERM, so they are
    # guaranteed to ride the journal into the successor lives.
    for rec in plan["late"]:
        ticks = 0
        while engine.sched.pending >= plan["queue_depth"] and ticks < MAX_TICKS:
            engine.step()
            ticks += 1
        engine.submit(rec["prompt"], rec["max_new"], tag=rec["tag"])

    for c in engine.pop_finished():
        _emit(out, {"kind": "done", "tag": c.tag, "status": c.status,
                    "tokens": c.tokens})

    # SIGTERM drain through a REAL signal + guard (not a direct drain()).
    guard = PreemptionGuard(signals=(signal.SIGTERM,), coordinated=False)
    guard.install()
    try:
        engine.install_preemption_guard(guard)
        os.kill(os.getpid(), signal.SIGTERM)
        engine.step()  # this tick drains
    finally:
        guard.uninstall()
    assert engine.drained, "SIGTERM did not drain the engine"
    for c in engine.pop_finished():
        _emit(out, {"kind": "done", "tag": c.tag, "status": c.status,
                    "tokens": c.tokens})
    _emit(out, {
        "kind": "exit",
        "counters": {
            "shed": engine.shed_count,
            "deadline_expired": engine.deadline_expired_count,
            "quarantined": engine.quarantined_count,
        },
        "drain_pending": [r["tag"] for r in engine.requeue_journal],
        "free_blocks": engine.cache.allocator.free_blocks,
        "capacity": engine.cache.allocator.capacity,
    })
    return 0


def run_victim_life(journal_path: str, kill_after: int) -> int:
    """Recover the journal, complete ``kill_after`` requests, then SIGKILL
    ourselves mid-flight — no handler, no drain, no atexit.  The write-ahead
    journal alone must carry the rest."""
    engine = _build_engine(journal_path)
    mapping = engine.recover_from_journal()
    _emit(sys.stdout, {"kind": "recovered", "count": len(mapping)})
    completed = 0
    ticks = 0
    while ticks < MAX_TICKS:
        engine.step()
        ticks += 1
        for c in engine.pop_finished():
            _emit(sys.stdout, {"kind": "done", "tag": c.tag,
                               "status": c.status, "tokens": c.tokens})
            completed += 1
        if completed >= kill_after:
            os.kill(os.getpid(), signal.SIGKILL)
    raise AssertionError("victim life drained before reaching its kill point")


def run_finisher_life(journal_path: str) -> int:
    """Recover whatever the SIGKILL left behind and finish every request."""
    engine = _build_engine(journal_path)
    mapping = engine.recover_from_journal()
    _emit(sys.stdout, {"kind": "recovered", "count": len(mapping)})
    engine.run(max_ticks=MAX_TICKS)
    for c in engine.pop_finished():
        _emit(sys.stdout, {"kind": "done", "tag": c.tag, "status": c.status,
                           "tokens": c.tokens})
    _emit(sys.stdout, {
        "kind": "exit",
        "free_blocks": engine.cache.allocator.free_blocks,
        "capacity": engine.cache.allocator.capacity,
    })
    return 0


def _emit_done_tiered(out, c) -> None:
    _emit(out, {
        "kind": "done", "tag": c.tag, "status": c.status, "tokens": c.tokens,
        "migrations": c.migrations, "fallback_reprefills": c.fallback_reprefills,
        "prefill_dispatches": c.prefill_dispatches, "prompt_len": c.prompt_len,
    })


def _emit_tier_exit(out, engine) -> None:
    st = engine.stats()["tiering"]
    prefix_host = engine._prefix.host_count if engine._prefix is not None else 0
    _emit(out, {
        "kind": "exit",
        "tiering": st,
        "preempted": engine.sched.preempted_count,
        "free_blocks": engine.cache.allocator.free_blocks,
        "capacity": engine.cache.allocator.capacity,
        "host_used": engine.cache.host.used_blocks,
        "prefix_host_entries": prefix_host,
    })


def run_tier_pressure_life(plan: dict) -> int:
    """Memory-pressure life: the tight pool preempts, preemption migrates,
    re-admission promotes.  Also serves as the host-full life when the
    parent arms ``SERVING_HOST_FULL`` in this child's environment (same
    code path; the fault flips every migration into a fallback)."""
    engine = _build_tiered_engine()
    out = sys.stdout
    for rec in plan["requests"]:
        engine.submit(rec["prompt"], rec["max_new"], tag=rec["tag"])
    engine.run(max_ticks=MAX_TICKS)
    assert engine.sched.preempted_count > 0, (
        "tiering life never preempted — the pool is not tight enough"
    )
    for c in engine.pop_finished():
        _emit_done_tiered(out, c)
    _emit_tier_exit(out, engine)
    return 0


def run_tier_victim_life(plan: dict, journal_path: str) -> int:
    """SIGKILL-while-demoted: run until some request's KV blocks sit in host
    DRAM, then die by signal 9 on the spot — the journal's tier record must
    carry what the host tier cannot (host DRAM dies with this process)."""
    engine = _build_tiered_engine(journal_path)
    out = sys.stdout
    for rec in plan["requests"]:
        engine.submit(rec["prompt"], rec["max_new"], tag=rec["tag"])
    for _ in range(MAX_TICKS):
        engine.step()
        for c in engine.pop_finished():
            _emit_done_tiered(out, c)
        if any(req.demoted_blocks for req in engine.sched.queue):
            os.kill(os.getpid(), signal.SIGKILL)
    raise AssertionError(
        "victim life finished without ever holding a request in the host tier"
    )


def run_tier_finisher_life(journal_path: str) -> int:
    """Recover the SIGKILLed victim's journal (all host-resident state is
    gone; re-prefill from the journaled progress) and finish everything."""
    engine = _build_tiered_engine(journal_path)
    mapping = engine.recover_from_journal()
    _emit(sys.stdout, {"kind": "recovered", "count": len(mapping)})
    engine.run(max_ticks=MAX_TICKS)
    for c in engine.pop_finished():
        _emit_done_tiered(sys.stdout, c)
    _emit_tier_exit(sys.stdout, engine)
    return 0


# ---------------------------------------------------------------------------
# Orchestration (parent)
# ---------------------------------------------------------------------------


def _child_env(extra: Optional[dict] = None) -> dict:
    env = dict(os.environ)
    for key in (
        "ACCELERATE_TPU_FAULT_SERVING_NAN_REQUEST",
        "ACCELERATE_TPU_FAULT_SERVING_HOST_FULL",
        "ACCELERATE_TPU_TELEMETRY",
        "ACCELERATE_TPU_TELEMETRY_DIR",
        "XLA_FLAGS",  # token identity across lives needs ONE device layout
    ):
        env.pop(key, None)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "ACCELERATE_TPU_COMPILE_CACHE": "",
            "ACCELERATE_TPU_SENTINEL_PROFILE": "0",
            "ACCELERATE_TPU_CHECKPOINT_FSYNC": "0",
        }
    )
    env.update(extra or {})
    return env


def _spawn(role: str, plan_path: str, journal_path: str,
           extra_env: Optional[dict] = None, expect_rc=0,
           kill_after: Optional[int] = None) -> list[dict]:
    cmd = [
        sys.executable, "-m", "accelerate_tpu.serving.chaos",
        "--role", role, "--plan", plan_path, "--journal", journal_path,
    ]
    if kill_after is not None:
        cmd += ["--kill-after", str(kill_after)]
    proc = subprocess.run(
        cmd, env=_child_env(extra_env), capture_output=True, text=True,
        timeout=CHILD_TIMEOUT_S,
    )
    sys.stderr.write(proc.stderr)
    if proc.returncode != expect_rc:
        print(proc.stdout)
        raise RuntimeError(
            f"serving life {role!r} exited rc={proc.returncode}, "
            f"expected {expect_rc}"
        )
    records = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            records.append(json.loads(line))
    return records


def run_serving_campaign(seed: int, workdir: Optional[str] = None) -> dict:
    """Run the full campaign; asserts every oracle, returns a summary."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..models import gpt2

    work = workdir or tempfile.mkdtemp(prefix="atpu_serving_chaos_")
    os.makedirs(work, exist_ok=True)
    plan = plan_serving_campaign(seed)
    plan_path = os.path.join(work, "plan.json")
    with open(plan_path, "w") as f:
        json.dump(plan, f)
    journal_path = os.path.join(work, "journal.json")

    # Offline oracle, computed in THIS process: greedy generate_loop per
    # prompt alone (the same determinism contract the serving smoke uses
    # cross-process — same code, same params key, same CPU backend).
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg, jax.random.key(0))
    oracle = {}
    for rec in plan["burst"] + [plan["poison"]] + plan["storm"] + plan["late"]:
        out = gpt2.generate(
            params, jnp.asarray([rec["prompt"]], jnp.int32), cfg,
            max_new_tokens=rec["max_new"],
        )
        oracle[rec["tag"]] = [int(t) for t in np.asarray(out[0])]

    print(f"# serving-chaos: life 0 (burst + poison + storm + SIGTERM drain), seed {seed}",
          file=sys.stderr)
    recs0 = _spawn(
        "first", plan_path, journal_path,
        extra_env={
            "ACCELERATE_TPU_FAULT_SERVING_NAN_REQUEST": str(plan["poison_ordinal"]),
        },
    )
    by_kind = lambda recs, kind: [r for r in recs if r["kind"] == kind]
    shed = by_kind(recs0, "shed")[0]["tags"]
    assert shed == plan["expect_shed"], (shed, plan["expect_shed"])
    exit0 = by_kind(recs0, "exit")[0]
    assert exit0["counters"]["shed"] == len(plan["expect_shed"]), exit0
    assert exit0["counters"]["deadline_expired"] == len(plan["expect_expired"]), exit0
    assert exit0["counters"]["quarantined"] == 1, exit0
    assert exit0["free_blocks"] == exit0["capacity"], f"life 0 leaked blocks: {exit0}"

    done: dict[str, dict] = {}

    def collect(records):
        for r in by_kind(records, "done"):
            assert r["tag"] not in done, f"request {r['tag']} completed twice"
            done[r["tag"]] = r

    collect(recs0)
    quarantined = [t for t, r in done.items() if r["status"] == "quarantined"]
    expired = [t for t, r in done.items() if r["status"] == "deadline_expired"]
    assert quarantined == [plan["poison"]["tag"]], quarantined
    assert sorted(expired) == sorted(plan["expect_expired"]), expired

    pending = set(exit0["drain_pending"])
    assert pending >= {r["tag"] for r in plan["late"]}, (
        f"late requests missing from the drain journal: {pending}"
    )
    print(f"# serving-chaos: life 1 (journal recovery, then SIGKILL mid-flight); "
          f"{len(pending)} pending", file=sys.stderr)
    recs1 = _spawn(
        "victim", plan_path, journal_path,
        expect_rc=-signal.SIGKILL, kill_after=1,
    )
    assert by_kind(recs1, "recovered")[0]["count"] == len(pending), recs1
    collect(recs1)

    print("# serving-chaos: life 2 (journal recovery after SIGKILL, finish everything)",
          file=sys.stderr)
    recs2 = _spawn("finisher", plan_path, journal_path)
    collect(recs2)
    exit2 = by_kind(recs2, "exit")[0]
    assert exit2["free_blocks"] == exit2["capacity"], f"life 2 leaked blocks: {exit2}"

    # -- campaign-wide oracles ------------------------------------------------
    all_tags = {
        r["tag"]
        for r in plan["burst"] + [plan["poison"]] + plan["storm"] + plan["late"]
    }
    terminal = set(done) | set(shed)
    assert terminal == all_tags, (
        f"starvation: requests never reached a terminal state: {all_tags - terminal}"
    )
    survivors = [t for t, r in done.items() if r["status"] == "ok"]
    assert sorted(survivors) == sorted(plan["survivor_tags"]), (
        survivors, plan["survivor_tags"]
    )
    for tag in survivors:
        assert done[tag]["tokens"] == oracle[tag], (
            f"survivor {tag} diverged from generate_loop:\n"
            f"  got  {done[tag]['tokens']}\n  want {oracle[tag]}"
        )

    return {
        "seed": seed,
        "requests": len(all_tags),
        "survivors": len(survivors),
        "shed": len(shed),
        "deadline_expired": len(expired),
        "quarantined": len(quarantined),
        "recoveries": 2,
        "workdir": work,
    }


def run_tiering_campaign(seed: int, workdir: Optional[str] = None) -> dict:
    """The tiered chaos campaign; asserts every oracle, returns a summary."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..models import gpt2
    from .journal import ServingJournal

    work = workdir or tempfile.mkdtemp(prefix="atpu_tiering_chaos_")
    os.makedirs(work, exist_ok=True)
    plan = plan_tiering_campaign(seed)
    plan_path = os.path.join(work, "plan.json")
    with open(plan_path, "w") as f:
        json.dump(plan, f)
    journal_path = os.path.join(work, "journal.json")

    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg, jax.random.key(0))
    oracle = {}
    for rec in plan["requests"]:
        out = gpt2.generate(
            params, jnp.asarray([rec["prompt"]], jnp.int32), cfg,
            max_new_tokens=rec["max_new"],
        )
        oracle[rec["tag"]] = [int(t) for t in np.asarray(out[0])]
    all_tags = {r["tag"] for r in plan["requests"]}
    by_kind = lambda recs, kind: [r for r in recs if r["kind"] == kind]

    def check_identity(done_recs):
        for r in done_recs:
            assert r["status"] == "ok", f"request {r['tag']} ended {r['status']}"
            assert r["tokens"] == oracle[r["tag"]], (
                f"request {r['tag']} diverged from generate_loop:\n"
                f"  got  {r['tokens']}\n  want {oracle[r['tag']]}"
            )

    # -- life 0: memory pressure (preempt -> demote -> promote -> resume) ----
    print(f"# tiering-chaos: life 0 (memory pressure: preemption as migration), "
          f"seed {seed}", file=sys.stderr)
    recs0 = _spawn("tier-pressure", plan_path, journal_path)
    done0 = by_kind(recs0, "done")
    assert {r["tag"] for r in done0} == all_tags, "life 0 starved a request"
    check_identity(done0)
    exit0 = by_kind(recs0, "exit")[0]
    st0 = exit0["tiering"]
    assert st0["demotions"] > 0 and st0["promotions"] > 0, (
        f"pressure life never migrated: {st0}"
    )
    migrated0 = [r for r in done0 if r["migrations"] > 0]
    assert migrated0, "no request round-tripped through the host tier"
    for r in migrated0:
        if r["fallback_reprefills"] == 0:
            base = -(-r["prompt_len"] // 4)  # ceil(prompt / prefill_chunk)
            assert r["prefill_dispatches"] == base, (
                f"{r['tag']} re-prefilled on the migrated resume path: "
                f"{r['prefill_dispatches']} dispatches vs {base}"
            )
    assert exit0["host_used"] == exit0["prefix_host_entries"], (
        f"life 0 leaked host blocks: {exit0}"
    )
    assert exit0["free_blocks"] == exit0["capacity"], f"life 0 leaked: {exit0}"

    # -- life 1: host tier full (fault-forced fallback re-prefill) -----------
    print("# tiering-chaos: life 1 (SERVING_HOST_FULL: forced fallback re-prefill)",
          file=sys.stderr)
    recs1 = _spawn(
        "tier-pressure", plan_path, journal_path,
        extra_env={"ACCELERATE_TPU_FAULT_SERVING_HOST_FULL": "1"},
    )
    done1 = by_kind(recs1, "done")
    assert {r["tag"] for r in done1} == all_tags, "host-full life starved a request"
    check_identity(done1)
    st1 = by_kind(recs1, "exit")[0]["tiering"]
    assert st1["fallback_reprefills"] > 0, (
        f"host-full fault never forced a fallback: {st1}"
    )
    assert st1["promotions"] == 0, f"a promotion happened with the host full: {st1}"

    # -- lives 2+3: SIGKILL while demoted, then journal recovery -------------
    print("# tiering-chaos: life 2 (SIGKILL at the instant a request is "
          "host-resident)", file=sys.stderr)
    recs2 = _spawn(
        "tier-victim", plan_path, journal_path, expect_rc=-signal.SIGKILL,
    )
    # The victim died with blocks in host DRAM: its journal must say so.
    state = ServingJournal.load(journal_path)
    host_resident = [
        rid for rid, rec in state["requests"].items()
        if rec.get("tier", {}).get("residency") == "host"
        and rid not in state["done"]
    ]
    assert host_resident, (
        "victim's journal carries no host-resident tier record at the kill"
    )

    print("# tiering-chaos: life 3 (journal recovery: host state is gone, "
          "re-prefill finishes everything)", file=sys.stderr)
    recs3 = _spawn("tier-finisher", plan_path, journal_path)
    done: dict[str, dict] = {}
    for r in by_kind(recs2, "done") + by_kind(recs3, "done"):
        assert r["tag"] not in done, f"request {r['tag']} completed twice"
        done[r["tag"]] = r
    assert set(done) == all_tags, (
        f"starvation across the kill: {all_tags - set(done)}"
    )
    check_identity(done.values())
    exit3 = by_kind(recs3, "exit")[0]
    assert exit3["free_blocks"] == exit3["capacity"], f"life 3 leaked: {exit3}"
    assert exit3["host_used"] == exit3["prefix_host_entries"], (
        f"life 3 leaked host blocks: {exit3}"
    )

    return {
        "seed": seed,
        "requests": len(all_tags),
        "migrations": st0["demotions"],
        "promotions": st0["promotions"],
        "fallbacks_forced": st1["fallback_reprefills"],
        "host_resident_at_kill": len(host_resident),
        "workdir": work,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m accelerate_tpu.serving.chaos",
    )
    parser.add_argument("--role",
                        choices=("first", "victim", "finisher",
                                 "tier-pressure", "tier-victim",
                                 "tier-finisher"),
                        default=None)
    parser.add_argument("--campaign", choices=("serving", "tiering"),
                        default="serving")
    parser.add_argument("--plan", default=None)
    parser.add_argument("--journal", default=None)
    parser.add_argument("--kill-after", type=int, default=1)
    parser.add_argument("--seed", type=int, default=20260804)
    args = parser.parse_args(argv)

    if args.role is not None:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        with open(args.plan) as f:
            plan = json.load(f)
        if args.role == "first":
            return run_first_life(plan, args.journal)
        if args.role == "victim":
            return run_victim_life(args.journal, args.kill_after)
        if args.role == "finisher":
            return run_finisher_life(args.journal)
        if args.role == "tier-pressure":
            return run_tier_pressure_life(plan)
        if args.role == "tier-victim":
            return run_tier_victim_life(plan, args.journal)
        return run_tier_finisher_life(args.journal)

    if args.campaign == "tiering":
        summary = run_tiering_campaign(args.seed)
        print(
            f"tiering-chaos-smoke OK — seed {summary['seed']}: "
            f"{summary['requests']} requests under memory pressure "
            f"({summary['migrations']} demotions / {summary['promotions']} "
            f"promotions through the host tier, zero re-prefill on migrated "
            f"resumes), a host-full life ({summary['fallbacks_forced']} forced "
            f"fallback re-prefills), and a SIGKILL landed while "
            f"{summary['host_resident_at_kill']} request(s) sat host-resident "
            "+ journal recovery; every output token-identical to "
            "generate_loop, zero block leaks in either tier"
        )
        return 0

    summary = run_serving_campaign(args.seed)
    print(
        f"serving-chaos-smoke OK — seed {summary['seed']}: "
        f"{summary['requests']} requests through overload burst "
        f"({summary['shed']} shed), a poisoned request "
        f"({summary['quarantined']} quarantined), a deadline storm "
        f"({summary['deadline_expired']} expired), SIGTERM drain, and "
        f"SIGKILL + {summary['recoveries']} journal recoveries; every "
        f"survivor ({summary['survivors']}) token-identical to generate_loop, "
        "zero block leaks, terminal state for every request"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
