"""Per-request serving traces: phase timelines, blame decomposition, export.

Every observability layer so far explains the *run* — the SLO histograms say
*that* a p99 request was slow, but nothing can say *why*.  This module gives
each request a timeline of typed **phase intervals** so the question has an
answer per request:

- ``queue_wait`` — submission until first admission into a slot;
- ``prefill`` — one interval per prefill chunk (``chunk`` index and
  ``padded_rows`` recorded); turn-waiting ticks where the slot held the
  request but another slot's chunk ran carry ``waiting=True``;
- ``decode`` — slot residency across decode ticks, one interval per run of
  ticks with the same batch shape (``co_batch``, bucket ``width``, ``ticks``
  count and summed ``dispatch_ms`` recorded);
- ``preempted`` — zero-duration marker at each eviction;
- ``requeued_wait`` — the post-preemption wait back to re-admission;
- ``compile_in_path`` — a tick whose dispatch hit a fresh per-width jit
  cache entry (the bucket-width recompile that spikes TTFT);
- ``quarantine`` — zero-duration marker at a poison quarantine;
- ``journal_recovery`` — marker on a journal-recovered request in-life; as
  a *duration* it is the inter-life gap, computed by the offline stitcher.

**Conservation invariant** (the goodput discipline): intervals are disjoint
and lie inside the request's submission→terminal window, by construction —
every interval starts at the trace's cursor or later and advances it.  The
residual is exposed as ``unattributed_ms`` (inter-tick host bookkeeping,
partial work discarded by a preemption), never silently absorbed.

On top of the timelines:

- a **blame decomposer** naming the dominant badput phase per completed
  request (``serving.trace.blame.*`` counters — "what is eating our p99"
  becomes a Prometheus query);
- **Chrome-trace export** (:func:`export_chrome_trace`): one track per
  engine slot plus one per request, round-trippable through
  ``telemetry/timeline.py`` so captures open in Perfetto next to
  ``jax.profiler`` dumps;
- **offline postmortem** (:func:`load_serving_traces` /
  :func:`stitch_traces` / :func:`summarize_traces`): the trace JSONL is
  re-summarized by ``telemetry.report`` so dead engines get blame
  decomposition too, with traces **stitched across engine lives** by the
  stable journal ``tag`` (the inter-life gap becomes ``journal_recovery``).

Cost model: host-side interval bookkeeping only — a few ``time.monotonic``
reads and list appends per tick, no effect on the compiled programs.
Completed traces live in a bounded ring (``ACCELERATE_TPU_SERVING_TRACE_CAPACITY``,
default 1024) like the flight recorder.  Tracing is **default-on**
(``ACCELERATE_TPU_SERVING_TRACE=0`` is the kill switch); the JSONL file only
exists when a directory is configured (``ServingConfig.trace_dir``,
``ACCELERATE_TPU_SERVING_TRACE_DIR``, or the enabled telemetry run dir).
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import time
from typing import Dict, List, Optional

from ..telemetry import get_telemetry

__all__ = [
    "PHASES",
    "BADPUT_PHASES",
    "PhaseInterval",
    "RequestTrace",
    "ServingTracer",
    "tracing_enabled",
    "resolve_trace_dir",
    "export_chrome_trace",
    "load_serving_traces",
    "stitch_traces",
    "summarize_traces",
    "format_trace_block",
    "ENV_ENABLE",
    "ENV_DIR",
    "ENV_CAPACITY",
    "ENV_FLUSH_EVERY",
]

ENV_ENABLE = "ACCELERATE_TPU_SERVING_TRACE"
ENV_DIR = "ACCELERATE_TPU_SERVING_TRACE_DIR"
ENV_CAPACITY = "ACCELERATE_TPU_SERVING_TRACE_CAPACITY"
ENV_FLUSH_EVERY = "ACCELERATE_TPU_SERVING_TRACE_FLUSH_EVERY"

DEFAULT_CAPACITY = 1024
DEFAULT_FLUSH_EVERY = 32

PHASES = (
    "queue_wait",
    "prefill",
    "decode",
    "verify",  # speculative draft-then-verify dispatch (productive, like decode)
    "preempted",
    "requeued_wait",
    "compile_in_path",
    "quarantine",
    "journal_recovery",
)

# Phases the blame decomposer may name (productive prefill/decode time is
# never "blamed"; a request slow because it generated many tokens is not
# suffering badput).  ``quarantine``/``journal_recovery`` are markers
# in-life, but quarantine is blamed by terminal status and journal_recovery
# by the stitcher's inter-life gap.
BADPUT_PHASES = (
    "queue_wait",
    "requeued_wait",
    "compile_in_path",
    "quarantine",
    "journal_recovery",
)

# Blame floor: the dominant badput phase is only named when it is material —
# at least this fraction of the request's wall window (and >= 1 ms), else
# the request's blame is "none".  Without the floor every healthy request
# would blame its microseconds of queue wait.
BLAME_FLOOR_FRACTION = 0.1
BLAME_FLOOR_MS = 1.0

_OFF = {"0", "false", "no", "off"}


def tracing_enabled(flag: Optional[bool] = None) -> bool:
    """Whether per-request tracing is on: an explicit ``ServingConfig.trace``
    wins; otherwise default-on with ``ACCELERATE_TPU_SERVING_TRACE=0`` as
    the kill switch."""
    if flag is not None:
        return bool(flag)
    return os.environ.get(ENV_ENABLE, "1").strip().lower() not in _OFF


def resolve_trace_dir(explicit: Optional[str] = None) -> Optional[str]:
    """Where trace JSONL persists: explicit config, then the env override,
    then the enabled telemetry run directory (so ``telemetry.report <dir>``
    finds the traces next to the telemetry stream), else nowhere — tracing
    stays purely in-memory (ring + live map) with no file I/O."""
    path = explicit or os.environ.get(ENV_DIR, "").strip() or None
    if path:
        return path
    tel = get_telemetry()
    if tel.enabled and tel.dir:
        return tel.dir
    return None


def _env_int(key: str, default: int) -> int:
    try:
        return int(os.environ.get(key, "") or default)
    except ValueError:
        return default


class PhaseInterval:
    """One typed interval on a request's timeline (monotonic seconds;
    ``start == end`` for markers)."""

    __slots__ = ("phase", "start", "end", "meta")

    def __init__(self, phase: str, start: float, end: float, meta: Optional[dict] = None):
        self.phase = phase
        self.start = start
        self.end = end
        self.meta = meta or {}

    @property
    def dur_ms(self) -> float:
        return (self.end - self.start) * 1e3


class RequestTrace:
    """One request's phase timeline plus the cursor that enforces the
    conservation invariant: every interval starts at or after the cursor and
    advances it, so intervals are disjoint and ordered by construction and
    ``unattributed_ms`` is exactly the window minus the attributed total."""

    __slots__ = (
        "rid", "tag", "arrival", "arrival_wall", "prompt_len", "max_new",
        "intervals", "cursor", "wait_phase", "slot", "prefill_chunks",
        "status", "finish", "blame", "recovered_from", "orig_arrival_wall",
    )

    def __init__(
        self,
        rid: int,
        tag: Optional[str],
        arrival: float,
        prompt_len: int,
        max_new: int,
    ):
        self.rid = rid
        self.tag = tag
        self.arrival = arrival
        # Wall anchor for cross-process stitching: monotonic clocks die with
        # their process; time.time() survives an engine's SIGKILL.
        self.arrival_wall = time.time() - (time.monotonic() - arrival)
        self.prompt_len = prompt_len
        self.max_new = max_new
        self.intervals: List[PhaseInterval] = []
        self.cursor = arrival
        self.wait_phase = "queue_wait"
        self.slot: Optional[int] = None
        self.prefill_chunks = 0
        self.status: Optional[str] = None
        self.finish: Optional[float] = None
        self.blame: Optional[str] = None
        self.recovered_from: Optional[int] = None
        self.orig_arrival_wall: Optional[float] = None

    def add(self, phase: str, end: float, start: Optional[float] = None, **meta) -> PhaseInterval:
        start = self.cursor if start is None else max(start, self.cursor)
        end = max(end, start)
        iv = PhaseInterval(phase, start, end, meta)
        self.intervals.append(iv)
        self.cursor = max(self.cursor, end)
        return iv

    def phase_ms(self, now: Optional[float] = None) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for iv in self.intervals:
            out[iv.phase] = out.get(iv.phase, 0.0) + iv.dur_ms
        return out

    def window_ms(self, now: Optional[float] = None) -> float:
        end = self.finish if self.finish is not None else (now or time.monotonic())
        return max(end - self.arrival, 0.0) * 1e3

    def unattributed_ms(self, now: Optional[float] = None) -> float:
        attributed = sum(iv.dur_ms for iv in self.intervals)
        return max(self.window_ms(now) - attributed, 0.0)

    def current_phase(self, now: Optional[float] = None) -> str:
        """What the request is doing *right now* (for ``/debug/requests``):
        the in-progress wait when off-slot, else the last recorded phase."""
        if self.finish is not None:
            return "done"
        if self.slot is None:
            return self.wait_phase
        return self.intervals[-1].phase if self.intervals else self.wait_phase

    def to_record(self, status: Optional[str] = None, now: Optional[float] = None) -> dict:
        """JSONL record (offsets in ms relative to arrival, wall anchor for
        stitching).  ``status="inflight"`` snapshots are superseded by the
        terminal record for the same request in the same file."""
        end = self.finish if self.finish is not None else (now or time.monotonic())
        return {
            "kind": "serving_trace",
            "rid": self.rid,
            "tag": self.tag,
            "status": status or self.status or "inflight",
            "arrival_wall": self.arrival_wall,
            "duration_ms": round((end - self.arrival) * 1e3, 3),
            "prompt_len": self.prompt_len,
            "max_new": self.max_new,
            "blame": self.blame,
            "recovered_from": self.recovered_from,
            "orig_arrival_wall": self.orig_arrival_wall,
            "unattributed_ms": round(self.unattributed_ms(end), 3),
            "phase_ms": {k: round(v, 3) for k, v in self.phase_ms().items()},
            "phases": [
                [
                    iv.phase,
                    round((iv.start - self.arrival) * 1e3, 3),
                    round((iv.end - self.arrival) * 1e3, 3),
                    iv.meta,
                ]
                for iv in self.intervals
            ],
        }


def decompose_blame(phase_ms: Dict[str, float], window_ms: float, status: str = "ok") -> str:
    """Name the dominant badput phase, or ``"none"`` when the request's
    badput is immaterial (below the blame floor).  A quarantined request is
    always blamed on ``quarantine`` — its wall time is irrelevant, its
    decode was poisoned."""
    if status == "quarantined":
        return "quarantine"
    bad = {p: phase_ms.get(p, 0.0) for p in BADPUT_PHASES}
    best = max(bad, key=lambda p: bad[p])
    floor = max(BLAME_FLOOR_MS, BLAME_FLOOR_FRACTION * window_ms)
    return best if bad[best] >= floor else "none"


class ServingTracer:
    """The engine-side trace collector: live traces keyed by request id, a
    bounded ring of completed traces, blame counters, and (when a directory
    is configured) an append-only JSONL file — terminal records plus
    periodic in-flight snapshots so a SIGKILLed engine's partial timelines
    survive for the offline stitcher."""

    def __init__(
        self,
        dir: Optional[str] = None,
        capacity: Optional[int] = None,
        flush_every: Optional[int] = None,
    ):
        self.live: Dict[int, RequestTrace] = {}
        self.capacity = int(capacity or _env_int(ENV_CAPACITY, DEFAULT_CAPACITY))
        self.flush_every = max(1, int(flush_every or _env_int(ENV_FLUSH_EVERY, DEFAULT_FLUSH_EVERY)))
        self.completed: collections.deque = collections.deque(maxlen=self.capacity)
        self.blame_counts: Dict[str, int] = {}
        self.dir = dir
        self.path: Optional[str] = None
        self._file = None
        if dir:
            os.makedirs(dir, exist_ok=True)
            # One file per engine life (pid-keyed): a successor engine on
            # the same run dir appends its OWN file, so the stitcher sees
            # both lives instead of the survivor clobbering the victim.
            self.path = os.path.join(dir, f"serving_trace_{os.getpid()}_{id(self) & 0xffff:x}.jsonl")
        self._events = 0
        self._tick_t0: Optional[float] = None
        self._ticked: set = set()

    # -- engine hooks --------------------------------------------------------

    def on_submit(self, req) -> None:
        self.live[req.id] = RequestTrace(
            req.id, req.tag, req.arrival_t, len(req.prompt), req.max_new_tokens
        )

    def on_admit(self, req, now: float, slot: int) -> None:
        t = self.live.get(req.id)
        if t is None:
            return
        if now > t.cursor:
            t.add(t.wait_phase, now)
        t.slot = slot
        self._note_event()

    def on_preempt(self, req, now: float) -> None:
        t = self.live.get(req.id)
        if t is None:
            return
        t.add("preempted", now, start=now, emitted=len(req.emitted))
        t.wait_phase = "requeued_wait"
        t.slot = None
        self._note_event()

    def on_recover(self, rid: int, journal_rec: dict) -> None:
        t = self.live.get(rid)
        if t is None:
            return
        t.recovered_from = journal_rec.get("id")
        t.orig_arrival_wall = journal_rec.get("arrival_wall")
        t.add(
            "journal_recovery", t.cursor, start=t.cursor,
            recovered_from=t.recovered_from,
        )

    def begin_tick(self, now: float) -> None:
        self._tick_t0 = now
        self._ticked = set()

    def on_prefill(
        self, req, slot: int, end: float,
        padded_rows: int, width: Optional[int], fresh: bool,
    ) -> None:
        t = self.live.get(req.id)
        if t is None:
            return
        phase = "compile_in_path" if fresh else "prefill"
        # Start at the request's cursor, not the tick boundary: a slotted
        # request idle between ticks (the driver wasn't stepping) is still
        # *resident* — that host gap belongs to its phase, not to
        # unattributed.
        t.add(
            phase, end,
            chunk=t.prefill_chunks, padded_rows=padded_rows,
            width=width, slot=slot,
            **({"kind": "prefill"} if fresh else {}),
        )
        t.prefill_chunks += 1
        self._ticked.add(req.id)
        self._note_event()

    def on_decode(
        self, reqs_slots, end: float,
        co_batch: int, width: Optional[int], fresh: bool, dispatch_ms: float,
        phase: str = "decode",
    ) -> None:
        """One fused decode/verify dispatch.  ``phase`` is ``"decode"`` for
        the single-token program and ``"verify"`` for a speculative
        draft-then-verify dispatch — both productive (never blamed); the
        phase key keeps greedy and speculative runs from coalescing into one
        interval, so a trace shows exactly where the engine ran verify
        windows."""
        for req, slot in reqs_slots:
            t = self.live.get(req.id)
            if t is None:
                continue
            last = t.intervals[-1] if t.intervals else None
            if (
                not fresh
                and last is not None
                and last.phase == phase
                and last.meta.get("co_batch") == co_batch
                and last.meta.get("width") == width
                and t.cursor == last.end
            ):
                # Coalesce the run: slot residency across consecutive decode
                # ticks of one batch shape is ONE interval (bounds memory and
                # folds the inter-tick host gap into attributed residency);
                # pure dispatch wall stays separately summed in dispatch_ms.
                last.end = end
                last.meta["ticks"] += 1
                last.meta["dispatch_ms"] = round(last.meta["dispatch_ms"] + dispatch_ms, 3)
                t.cursor = end
            else:
                # Cursor start (see on_prefill): in-slot residency across a
                # shape change or host gap stays attributed to the request.
                t.add(
                    "compile_in_path" if fresh else phase, end,
                    co_batch=co_batch, width=width, slot=slot,
                    ticks=1, dispatch_ms=round(dispatch_ms, 3),
                    **({"kind": phase} if fresh else {}),
                )
            self._ticked.add(req.id)
        self._note_event()

    def end_tick(self, now: float, slots: dict) -> None:
        """Close the tick for every resident request: dispatched requests'
        last interval stretches to the tick boundary (the emit/bookkeeping
        tail stays attributed); a prefilling slot that never got its chunk
        turn records a ``waiting`` prefill interval — the co-batched-behind-
        another-prefill time the blame question asks about."""
        if self._tick_t0 is None:
            return
        for idx, slot in slots.items():
            t = self.live.get(slot.request.id)
            if t is None:
                continue
            if t.rid in self._ticked:
                last = t.intervals[-1]
                if now > last.end:
                    last.end = now
                    t.cursor = max(t.cursor, now)
                continue
            last = t.intervals[-1] if t.intervals else None
            if (
                last is not None
                and last.phase == "prefill"
                and last.meta.get("waiting")
                and t.cursor == last.end
            ):
                last.end = now
                last.meta["ticks"] += 1
                t.cursor = now
            else:
                t.add("prefill", now, waiting=True, ticks=1, slot=idx)
        self._tick_t0 = None
        self._note_event()

    def on_terminal(self, req, status: str) -> None:
        t = self.live.pop(req.id, None)
        if t is None:
            return
        finish = req.finish_t if req.finish_t is not None else time.monotonic()
        if t.slot is None and finish > t.cursor:
            # Off-slot terminal (deadline-shed from the queue, instant-done):
            # the residual IS the wait — attribute it, don't leak it.
            t.add(t.wait_phase, finish, terminal=True)
        if status == "quarantined":
            t.add("quarantine", finish, start=finish)
        t.finish = max(finish, t.cursor)
        t.status = status
        t.blame = decompose_blame(t.phase_ms(), t.window_ms(), status)
        self.blame_counts[t.blame] = self.blame_counts.get(t.blame, 0) + 1
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter(f"serving.trace.blame.{t.blame}").inc()
            tel.registry.histogram("serving.trace.unattributed_ms").observe(
                t.unattributed_ms()
            )
        self.completed.append(t)
        self._write(t.to_record())
        if self._file is not None:
            self._file.flush()  # terminal records are durability points
        self._note_event()

    # -- persistence ---------------------------------------------------------

    def _note_event(self) -> None:
        self._events += 1
        if self.path is not None and self._events % self.flush_every == 0:
            self.flush()

    def flush(self) -> None:
        """Append an in-flight snapshot line per live request (last line per
        request id wins at load time).  Called on the flush cadence, at
        drain, and after a recovery — the SIGKILL-durability hook."""
        if self.path is None:
            return
        now = time.monotonic()
        for t in self.live.values():
            if t.intervals or now > t.arrival:
                self._write(t.to_record(status="inflight", now=now))
        if self._file is not None:
            self._file.flush()

    def _write(self, record: dict) -> None:
        if self.path is None:
            return
        if self._file is None:
            # Block-buffered: a syscall per snapshot line would tax every
            # tick.  Both callers (flush() and on_terminal) flush the file
            # before returning, so a SIGKILL can only lose lines from a
            # flush call it interrupted mid-write.
            self._file = open(self.path, "a")
        self._file.write(json.dumps(record) + "\n")

    # -- introspection -------------------------------------------------------

    def snapshot_request(self, rid: int, now: Optional[float] = None) -> dict:
        """Phase-so-far for one live request (``/debug/requests``)."""
        t = self.live.get(rid)
        if t is None:
            return {}
        now = now or time.monotonic()
        phase_ms = dict(t.phase_ms())
        if t.slot is None and now > t.cursor:
            # The in-progress wait is real badput already — show it.
            phase_ms[t.wait_phase] = (
                phase_ms.get(t.wait_phase, 0.0) + (now - t.cursor) * 1e3
            )
        return {
            "current_phase": t.current_phase(now),
            "phase_ms": {k: round(v, 3) for k, v in phase_ms.items()},
            "unattributed_ms": round(t.unattributed_ms(now), 3),
            "preempt_markers": sum(1 for iv in t.intervals if iv.phase == "preempted"),
        }

    def traces(self) -> List[RequestTrace]:
        return list(self.completed) + list(self.live.values())


# ---------------------------------------------------------------------------
# Chrome-trace export
# ---------------------------------------------------------------------------

_SLOT_PID = 1
_REQ_PID = 2


def export_chrome_trace(path: str, traces: List[RequestTrace]) -> str:
    """Write the traces as a Chrome trace-event bundle: one thread per
    engine slot (what each decode lane was doing) under process 1, one
    thread per request (its full phase timeline) under process 2 —
    ``ph=="M"`` name metadata plus ``ph=="X"`` complete events with
    ``ts``/``dur`` in microseconds, wall-anchored so two engine lives line
    up on one axis.  The format round-trips through
    ``telemetry.timeline.load_trace_events``/``build_timeline`` (the same
    parser that reads ``jax.profiler`` dumps), so the file opens in
    Perfetto; ``.gz`` paths are gzip-compressed like the profiler's own."""
    events: List[dict] = [
        {"ph": "M", "pid": _SLOT_PID, "name": "process_name",
         "args": {"name": "serving engine slots"}},
        {"ph": "M", "pid": _REQ_PID, "name": "process_name",
         "args": {"name": "serving requests"}},
    ]
    if not traces:
        base_wall = 0.0
    else:
        base_wall = min(t.arrival_wall for t in traces)
    slots_seen: set = set()
    for t in sorted(traces, key=lambda t: t.arrival_wall):
        label = f"req {t.rid}" + (f" [{t.tag}]" if t.tag else "")
        events.append({
            "ph": "M", "pid": _REQ_PID, "tid": t.rid, "name": "thread_name",
            "args": {"name": label},
        })
        for iv in t.intervals:
            ts = (t.arrival_wall - base_wall + (iv.start - t.arrival)) * 1e6
            dur = (iv.end - iv.start) * 1e6
            args = dict(iv.meta, request=t.rid, phase=iv.phase)
            if t.tag is not None:
                args["tag"] = t.tag
            events.append({
                "ph": "X", "pid": _REQ_PID, "tid": t.rid, "name": iv.phase,
                "ts": round(ts, 3), "dur": round(dur, 3), "args": args,
            })
            slot = iv.meta.get("slot")
            if slot is not None:
                slots_seen.add(slot)
                events.append({
                    "ph": "X", "pid": _SLOT_PID, "tid": slot,
                    "name": f"r{t.rid}/{iv.phase}",
                    "ts": round(ts, 3), "dur": round(dur, 3), "args": args,
                })
    for slot in sorted(slots_seen):
        events.append({
            "ph": "M", "pid": _SLOT_PID, "tid": slot, "name": "thread_name",
            "args": {"name": f"slot {slot}"},
        })
    bundle = {"traceEvents": events, "displayTimeUnit": "ms"}
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    if path.endswith(".gz"):
        with gzip.open(path, "wt", encoding="utf-8") as f:
            json.dump(bundle, f)
    else:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(bundle, f)
    return path


# ---------------------------------------------------------------------------
# Offline: load / stitch / summarize (stdlib only — report runs these)
# ---------------------------------------------------------------------------


def load_serving_traces(path: str) -> List[dict]:
    """Parse trace records from a ``serving_trace_*.jsonl`` file or a run
    directory.  Per (file, request id) the LAST record wins — terminal
    records land after every in-flight snapshot of the same request, so a
    completed request is never double-counted as also in flight."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "serving_trace_*.jsonl")))
    else:
        files = [path]
    out: Dict[tuple, dict] = {}
    for file in files:
        try:
            with open(file) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # a SIGKILLed writer's torn tail
            if rec.get("kind") != "serving_trace":
                continue
            rec["source"] = os.path.basename(file)
            out[(file, rec.get("rid"))] = rec
    return sorted(out.values(), key=lambda r: (r.get("arrival_wall") or 0.0))


def stitch_traces(records: List[dict], eps_ms: Optional[float] = None) -> List[dict]:
    """Join one logical request's records across engine lives by journal
    ``tag``: lives sorted by wall arrival, each inter-life gap attributed to
    ``journal_recovery`` (the dead time between the victim's last trace and
    the successor's resubmission).  Conservation must hold ACROSS the
    stitch: summed phases + gaps + summed per-life unattributed == first
    arrival → last end, within ``eps_ms``."""
    by_tag: Dict[str, List[dict]] = {}
    for rec in records:
        tag = rec.get("tag")
        if tag is not None:
            by_tag.setdefault(tag, []).append(rec)
    out = []
    for tag in sorted(by_tag):
        lives = sorted(by_tag[tag], key=lambda r: r.get("arrival_wall") or 0.0)
        if len(lives) < 2 and not any(r.get("recovered_from") is not None for r in lives):
            continue
        phase_ms: Dict[str, float] = {}
        unattributed = 0.0
        gap_ms = 0.0
        for i, rec in enumerate(lives):
            for phase, ms in (rec.get("phase_ms") or {}).items():
                phase_ms[phase] = phase_ms.get(phase, 0.0) + float(ms)
            unattributed += float(rec.get("unattributed_ms") or 0.0)
            if i > 0:
                prev = lives[i - 1]
                prev_end = (prev.get("arrival_wall") or 0.0) + float(
                    prev.get("duration_ms") or 0.0
                ) / 1e3
                gap = ((rec.get("arrival_wall") or 0.0) - prev_end) * 1e3
                gap_ms += max(gap, 0.0)
        phase_ms["journal_recovery"] = phase_ms.get("journal_recovery", 0.0) + gap_ms
        first, last = lives[0], lives[-1]
        total_ms = (
            (last.get("arrival_wall") or 0.0)
            + float(last.get("duration_ms") or 0.0) / 1e3
            - (first.get("arrival_wall") or 0.0)
        ) * 1e3
        attributed = sum(phase_ms.values())
        error_ms = total_ms - attributed - unattributed
        eps = eps_ms if eps_ms is not None else max(5.0, 0.02 * total_ms)
        out.append({
            "tag": tag,
            "lives": len(lives),
            "status": last.get("status"),
            "total_ms": round(total_ms, 3),
            "phase_ms": {k: round(v, 3) for k, v in sorted(phase_ms.items())},
            "journal_recovery_ms": round(gap_ms, 3),
            "unattributed_ms": round(unattributed, 3),
            "conservation_error_ms": round(error_ms, 3),
            "conservation_ok": abs(error_ms) <= eps,
            "blame": decompose_blame(phase_ms, total_ms, last.get("status") or "ok"),
        })
    return out


def summarize_traces(records: List[dict]) -> dict:
    """The report's offline blame decomposition: terminal counts, blame
    tally, unattributed residual stats, cross-life stitches, and the
    slowest completed requests."""
    terminal = [r for r in records if r.get("status") != "inflight"]
    inflight = [r for r in records if r.get("status") == "inflight"]
    blame: Dict[str, int] = {}
    for rec in terminal:
        b = rec.get("blame") or "none"
        blame[b] = blame.get(b, 0) + 1
    unattr = sorted(float(r.get("unattributed_ms") or 0.0) for r in terminal)
    worst = sorted(
        terminal, key=lambda r: -(float(r.get("duration_ms") or 0.0))
    )[:3]
    return {
        "requests": len(terminal),
        "inflight": len(inflight),
        "by_status": _tally(terminal, "status"),
        "by_blame": blame,
        "unattributed_ms": {
            "mean": round(sum(unattr) / len(unattr), 3) if unattr else 0.0,
            "max": round(unattr[-1], 3) if unattr else 0.0,
        },
        "stitched": stitch_traces(records),
        "worst": [
            {
                "rid": r.get("rid"),
                "tag": r.get("tag"),
                "duration_ms": r.get("duration_ms"),
                "blame": r.get("blame"),
                "phase_ms": r.get("phase_ms"),
            }
            for r in worst
        ],
    }


def _tally(records: List[dict], key: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for rec in records:
        v = str(rec.get(key))
        out[v] = out.get(v, 0) + 1
    return out


def format_trace_block(summary: dict) -> List[str]:
    """Human renderer for the report's "serving traces" postmortem block."""
    if not summary or (not summary.get("requests") and not summary.get("inflight")):
        return []
    lines = [
        f"serving traces (per-request blame) — {summary['requests']} completed, "
        f"{summary['inflight']} in-flight snapshot(s)"
    ]
    blame = summary.get("by_blame") or {}
    if blame:
        lines.append(
            "  blame: "
            + ", ".join(f"{k} {blame[k]}" for k in sorted(blame, key=lambda k: -blame[k]))
        )
    un = summary.get("unattributed_ms") or {}
    lines.append(
        f"  unattributed residual: mean {un.get('mean', 0.0)} ms, "
        f"max {un.get('max', 0.0)} ms"
    )
    for st in summary.get("stitched") or []:
        ok = "ok" if st.get("conservation_ok") else f"VIOLATED ({st.get('conservation_error_ms')} ms)"
        lines.append(
            f"  stitched tag {st['tag']!r}: {st['lives']} lives, "
            f"{st['total_ms']} ms total (journal_recovery {st['journal_recovery_ms']} ms), "
            f"blame {st['blame']}, conservation {ok}"
        )
    for w in summary.get("worst") or []:
        tag = f" [{w['tag']}]" if w.get("tag") else ""
        lines.append(
            f"  slowest: rid {w['rid']}{tag} {w['duration_ms']} ms — blame {w['blame']}"
        )
    return lines
