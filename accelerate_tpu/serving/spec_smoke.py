"""Speculative-serving smoke: draft-then-verify proof on an 8-device CPU mesh.

Run via ``make spec-smoke`` (or ``python -m accelerate_tpu.serving.spec_smoke``).
A mix of pattern-heavy prompts (the n-gram drafter's best case) and random
prompts (mostly-rejected drafts) flows through a speculative engine
(``ServingConfig.spec_tokens > 0``) on a forced 8-device CPU mesh.  Asserts:

- **speculation is live** — ``serving.spec.acceptance_rate`` ends above zero
  and more than one token lands per slot-dispatch on the pattern traffic;
- **one decode program per tick per bucket** — the decode-dispatch counter
  delta equals the engine's dispatch count, never exceeds ticks, and every
  decode dispatch is a verify dispatch (``spec.rounds`` == dispatches: the
  fixed ``k+1`` window means a draft-less tick reuses the SAME program
  instead of compiling a fresh single-token one);
- **token identity** — every request's output is token-identical to the
  offline greedy ``generate_loop`` for that prompt alone, including the
  requests whose drafts were mostly rejected;
- **zero block leaks** — the KV pool is fully free after the last
  completion (accept/rewind never strands a block).

Exit code 0 only when every assertion holds.
"""

from __future__ import annotations

import os
import sys
import tempfile


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ.setdefault("ACCELERATE_TPU_COMPILE_CACHE", "")
    os.environ.setdefault("ACCELERATE_TPU_SENTINEL_PROFILE", "0")

    import numpy as np

    import jax
    import jax.numpy as jnp

    from accelerate_tpu import telemetry
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import gpt2
    from accelerate_tpu.utils.dataclasses import ParallelismConfig

    tel = telemetry.enable(dir=tempfile.mkdtemp(prefix="atpu_spec_smoke_"))
    assert jax.device_count() == 8, f"expected 8 CPU devices, got {jax.device_count()}"
    acc = Accelerator(parallelism_config=ParallelismConfig(dp=8))

    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg, jax.random.key(0))

    rng = np.random.default_rng(7)
    pattern = [int(t) for t in rng.integers(0, cfg.vocab_size, size=4)]
    # Pattern prompts feed the prompt-lookup drafter from the first tick;
    # the random prompts ride in the same co-batch with near-zero acceptance
    # so variable per-slot accept/rewind is exercised inside one dispatch.
    prompts = [
        pattern * 3,
        pattern * 2 + pattern[:2],
        list(rng.integers(0, cfg.vocab_size, size=9)),
        pattern * 2 + pattern[:3],
        list(rng.integers(0, cfg.vocab_size, size=6)),
    ]
    budgets = [10, 8, 6, 9, 7]

    print("# spec smoke: offline oracle (generate_loop, greedy)")
    want = {}
    for i, (p, m) in enumerate(zip(prompts, budgets)):
        out = gpt2.generate(params, jnp.asarray([p], jnp.int32), cfg, max_new_tokens=m)
        want[i] = [int(t) for t in np.asarray(out[0])]

    engine = acc.prepare_serving(
        gpt2.apply_cached, gpt2.init_cache, params, cfg,
        block_size=4, num_blocks=24, max_slots=4, prefill_chunk=8,
        max_blocks_per_seq=8, spec_tokens=3, prefix_cache=False,
    )

    dispatch_counter = tel.registry.counter("serving.decode_dispatches")
    rounds_counter = tel.registry.counter("serving.spec.rounds")
    d0, r0 = dispatch_counter.value, rounds_counter.value

    ids = {}
    for k, i in enumerate(rng.permutation(len(prompts))):
        ids[engine.submit(prompts[i], budgets[i])] = int(i)
        if k % 2 == 1:
            engine.step()
    outputs = engine.run(max_ticks=2000)
    stats = engine.stats()
    print(f"# spec smoke: stats {stats}")

    for rid, out in outputs.items():
        assert out == want[ids[rid]], (
            f"request {rid} (prompt #{ids[rid]}) diverged from generate_loop:\n"
            f"  got  {out}\n  want {want[ids[rid]]}"
        )
    print(f"# spec smoke: {len(outputs)} requests token-identical to generate_loop")

    spec = stats["spec"]
    assert spec["acceptance_rate"] > 0, "drafter never landed a token"
    assert spec["tokens_per_dispatch"] > 1.0, (
        f"tokens/slot-dispatch {spec['tokens_per_dispatch']:.3f} <= 1 — "
        "speculation emitted no more than plain greedy would"
    )
    snap_rate = tel.registry.gauge("serving.spec.acceptance_rate").value
    assert snap_rate > 0, "serving.spec.acceptance_rate gauge never moved"
    print(
        f"# spec smoke: acceptance {spec['acceptance_rate']:.3f} "
        f"({spec['accepted']}/{spec['proposed']} drafts), "
        f"{spec['tokens_per_dispatch']:.3f} tokens per slot-dispatch"
    )

    delta = dispatch_counter.value - d0
    assert delta == engine.decode_dispatches, (
        f"telemetry counted {delta} decode dispatches, engine ran "
        f"{engine.decode_dispatches}"
    )
    assert delta <= engine.ticks, f"{delta} decode dispatches > {engine.ticks} ticks"
    rounds = rounds_counter.value - r0
    assert rounds == delta, (
        f"{rounds} verify rounds != {delta} decode dispatches — a tick fell "
        "out of the fixed k+1 window program (fresh single-token compile)"
    )
    print(
        f"# spec smoke: {delta} fused verify dispatches over {engine.ticks} "
        "ticks (<= 1/step, every dispatch a k+1 window)"
    )

    assert engine.cache.allocator.used_blocks == 0, (
        f"{engine.cache.allocator.used_blocks} blocks still allocated after "
        "the last completion — accept/rewind leaked pool blocks"
    )
    print("# spec smoke: KV pool fully free after drain (zero block leaks)")

    telemetry.disable()
    print("spec smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
