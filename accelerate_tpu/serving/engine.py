"""The serving engine: continuous batching over the paged KV cache.

One engine **tick** (:meth:`ServingEngine.step`) is:

1. **admit** — queue-head requests take free decode slots (FIFO);
2. **prefill** — at most ONE bounded chunk (``prefill_chunk`` tokens, padded
   to a static shape) of the oldest prefilling request runs, so a 10k-token
   prompt costs many small dispatches interleaved with decode instead of one
   huge dispatch that stalls every in-flight request;
3. **decode** — ONE fused jitted dispatch advances every decoding slot by one
   token: the block tables gather each slot's paged KV into the dense view
   the family's ``apply_cached`` consumes, a ``vmap`` over slots runs the
   per-token forward with per-slot write indices, and the freshly written
   K/V rows scatter back into the pool.  The 1-dispatch-per-decode-step
   invariant from ``make_train_step`` carries over — the
   ``serving.decode_dispatches`` counter is the proof hook.

Token selection is **greedy** (argmax, inside the fused program): outputs are
token-identical to the offline ``generate_loop`` with ``temperature=0`` per
request, which is the engine's equivalence oracle (``tests/test_serving.py``,
``make serving-smoke``).

Chunked-prefill padding contract: chunks are padded to the static
``prefill_chunk`` length.  Padded queries produce ignored logits; padded K/V
rows land at positions past the real prefix — positions the causal mask hides
from every existing query and that sequential future writes overwrite before
any query of that position exists.  Pool writes for positions past the block
table route to the null block.  The scheduler's geometry validation
guarantees ``ceil(rows / prefill_chunk) * prefill_chunk <= max_blocks_per_seq
* block_size``, so the padded write never clamps inside the dense view.

SLO metrics per request — TTFT, inter-token latency, queue wait, tokens/s,
preemption count — publish through the telemetry registry
(``serving.*`` families) and each completion emits a
``serving.request_complete`` event, which the flight recorder mirrors into
its durable ring when enabled.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..models.generation import (
    extract_token_rows,
    gather_block_view,
    scatter_token_rows,
)
from ..telemetry import get_telemetry
from .blocks import PagedKVCache
from .scheduler import Request, RequestState, Scheduler

__all__ = ["ServingConfig", "ServingEngine", "CompletedRequest"]


@dataclass
class ServingConfig:
    """Engine geometry (everything here is a static shape of the compiled
    programs — two programs total, however many requests flow through).

    - ``block_size``: tokens per KV block.  Small blocks waste less tail
      space per request; large blocks shrink the tables.  16-64 is typical.
    - ``num_blocks``: pool size (one block is reserved as the null block).
      Pool HBM = ``num_blocks * block_size`` rows per layer — budget this
      like a dense cache of total length ``num_blocks * block_size`` shared
      by ALL requests, not tiled per request.
    - ``max_slots``: the decode batch width (static).  More slots = more
      requests advanced per decode dispatch.
    - ``max_blocks_per_seq``: block-table width (static); caps any single
      request at ``max_blocks_per_seq * block_size`` cache rows.
    - ``prefill_chunk``: prompt tokens per prefill dispatch (static).
    """

    block_size: int = 16
    num_blocks: int = 64
    max_slots: int = 4
    max_blocks_per_seq: Optional[int] = None
    prefill_chunk: int = 32

    def resolved_max_blocks(self) -> int:
        if self.max_blocks_per_seq is not None:
            return self.max_blocks_per_seq
        return self.num_blocks - 1


@dataclass
class CompletedRequest:
    """Completion record: the tokens plus the request's SLO timeline."""

    id: int
    tokens: List[int]
    prompt_len: int
    new_tokens: int
    queue_wait_ms: float
    ttft_ms: Optional[float]
    mean_inter_token_ms: Optional[float]
    tokens_per_s: Optional[float]
    preemptions: int
    inter_token_ms: List[float] = field(default_factory=list)


class ServingEngine:
    """Continuous-batching serving over a model family's
    ``apply_cached``/``init_cache`` pair (any family following the
    ``make_kv_cache`` layout — gpt2/llama/mixtral, fp or int8 KV).  The
    token-identity-vs-``generate_loop`` guarantee needs a
    chunking-independent forward (dense FFN); capacity-limited MoE routing
    (mixtral) varies with prefill chunking here exactly as it does under
    offline ``prefill_chunk``.

    ::

        engine = ServingEngine(gpt2.apply_cached, gpt2.init_cache, params, cfg,
                               serving=ServingConfig(max_slots=8))
        rid = engine.submit(prompt_tokens, max_new_tokens=64)
        outputs = engine.run()          # {rid: full token list}

    or drive it tick-by-tick with :meth:`step` / :meth:`pop_finished`.
    """

    def __init__(
        self,
        apply_cached: Callable,
        init_cache: Callable,
        params,
        config,
        serving: Optional[ServingConfig] = None,
    ):
        self.serving = serving or ServingConfig()
        sc = self.serving
        if sc.prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {sc.prefill_chunk}")
        if sc.resolved_max_blocks() < 1:
            raise ValueError("max_blocks_per_seq must be >= 1")
        self._apply_cached = apply_cached
        self._config = config
        self.params = params
        self.cache = PagedKVCache(init_cache, config, sc.num_blocks, sc.block_size)
        self.sched = Scheduler(
            self.cache.allocator,
            num_slots=sc.max_slots,
            block_size=sc.block_size,
            max_blocks_per_seq=sc.resolved_max_blocks(),
            prefill_chunk=sc.prefill_chunk,
        )
        max_len = sc.resolved_max_blocks() * sc.block_size
        model_max = getattr(config, "max_seq_len", None)
        if model_max is not None and max_len > model_max:
            raise ValueError(
                f"max_blocks_per_seq * block_size = {max_len} exceeds the "
                f"model's max_seq_len {model_max}; shrink the table or blocks"
            )
        self._kv_names = self.cache.leaf_names
        self._finished: List[CompletedRequest] = []
        self._preempted_published = 0
        self._preemption_guard = None
        self._drained = False
        self.requeue_journal: Optional[List[dict]] = None
        self.ticks = 0
        self.decode_dispatches = 0
        self.prefill_dispatches = 0
        self._decode_fn = jax.jit(self._build_decode(), donate_argnums=(1,))
        self._prefill_fn = jax.jit(self._build_prefill(), donate_argnums=(1,))

    # -- compiled programs ---------------------------------------------------

    def _build_decode(self):
        apply_cached, config, names = self._apply_cached, self._config, self._kv_names

        def decode(params, pool, tables, lengths, tokens):
            views = {n: gather_block_view(pool[n], tables) for n in names}
            caches = dict(views, index=lengths)

            def one(cache, tok):
                logits, new_cache = apply_cached(params, tok[None, None], config, cache)
                return logits[0, -1], new_cache

            logits, new_caches = jax.vmap(one)(caches, tokens)
            next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            new_pool = {}
            for n in names:
                rows = extract_token_rows(new_caches[n], lengths, 1)
                new_pool[n] = scatter_token_rows(pool[n], rows, tables, lengths, 1)
            return next_tok, new_pool

        return decode

    def _build_prefill(self):
        apply_cached, config, names = self._apply_cached, self._config, self._kv_names
        chunk_len = self.serving.prefill_chunk

        def prefill(params, pool, table_row, length, chunk, n_real):
            tables = table_row[None]  # [1, M]
            start = length[None]
            cache = {n: gather_block_view(pool[n], tables)[0] for n in names}
            cache["index"] = length
            logits, new_cache = apply_cached(params, chunk, config, cache)
            next_tok = jnp.argmax(logits[0, n_real - 1], axis=-1).astype(jnp.int32)
            new_pool = {}
            for n in names:
                rows = extract_token_rows(new_cache[n][None], start, chunk_len)
                new_pool[n] = scatter_token_rows(pool[n], rows, tables, start, chunk_len)
            return next_tok, new_pool

        return prefill

    # -- request API ---------------------------------------------------------

    def install_preemption_guard(self, guard) -> None:
        """Honor a resilience :class:`PreemptionGuard`
        (``accelerator.enable_preemption_handling()`` installs one): once the
        fleet agrees a preemption signal arrived, the next :meth:`step` call
        DRAINS the engine instead of ticking — admission stops, in-flight
        slots are preempted back to the queue with their emitted tokens
        carried, and a ``serving.drained`` event records the requeue journal
        of incomplete requests so a successor process can resubmit them
        (re-prefilling prompt+emitted rebuilds each cache bit-identically,
        the same path a block-pressure preemption takes)."""
        if self._drained:
            raise RuntimeError(
                "engine already drained: the requeue journal is final and "
                "admission is closed — build a successor engine instead of "
                "re-arming this one."
            )
        self._preemption_guard = guard

    @property
    def drained(self) -> bool:
        return self._drained

    def submit(
        self,
        prompt_ids,
        max_new_tokens: int,
        arrival_t: Optional[float] = None,
    ) -> int:
        """Queue one request; returns its id.  ``max_new_tokens == 0``
        completes immediately (the offline loop's contract)."""
        if self._drained:
            raise RuntimeError(
                "engine drained after a preemption signal: admission is closed "
                "and the requeue journal is final — resubmit to a successor "
                "engine (see engine.requeue_journal)."
            )
        req = Request(list(np.asarray(prompt_ids).reshape(-1)), max_new_tokens, arrival_t)
        if req.max_new_tokens == 0:
            now = time.monotonic()
            req.state = RequestState.DONE
            req.admit_t = req.finish_t = now
        else:
            self.sched.submit(req)  # geometry validation may reject — count after
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.requests").inc()
        if req.state == RequestState.DONE:
            self._complete(req)
        return req.id

    def step(self) -> List[CompletedRequest]:
        """One engine tick: admit, one prefill chunk, one fused decode
        dispatch.  Returns the requests that completed this tick.  With an
        installed :class:`PreemptionGuard` whose signal has arrived, the
        tick drains instead (no admission, no dispatch)."""
        now = time.monotonic()
        done_before = len(self._finished)
        if self._drained or self._drain_requested():
            self.drain()
            return []
        self.ticks += 1
        self.sched.admit(now)
        self._prefill_tick(now)
        self._decode_tick(now)
        self._publish_gauges()
        return self._finished[done_before:]

    def run(self, max_ticks: Optional[int] = None) -> Dict[int, List[int]]:
        """Drive ticks until every submitted request completes; returns
        ``{request_id: full token list (prompt + generated)}``.  A
        preemption-triggered drain ends the loop early: completed requests
        are returned, incomplete ones are in :attr:`requeue_journal`."""
        ticks = 0
        while not self.sched.idle():
            self.step()
            if self._drained:
                break
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                raise RuntimeError(
                    f"engine did not drain within {max_ticks} ticks "
                    f"(active {self.sched.active}, queued {self.sched.pending})"
                )
        return {c.id: c.tokens for c in self._finished}

    def _drain_requested(self) -> bool:
        """Whether the installed guard says stop.  For a multi-host
        COORDINATED guard the LOCAL flag is consulted, never should_stop():
        that path gates a cross-host collective on a per-guard call counter
        that every process must hit in lockstep, and engine tick counts are
        data-dependent (queue depth differs per host) — one desynchronized
        gather would hang the fleet.  Fleet-wide stop agreement belongs to
        the training loop's check_preemption(); the drain itself is a local
        action (each host journals its own queue)."""
        guard = self._preemption_guard
        if guard is None:
            return False
        coordinated = getattr(guard, "_coordination_on", None)
        if coordinated is not None and coordinated():
            return guard.preempted_locally()
        return guard.should_stop()

    def drain(self) -> List[dict]:
        """Graceful drain: stop admission, preempt every in-flight slot back
        to the queue (blocks freed, emitted tokens carried — the oldest
        request ends up at the queue FRONT, preserving FIFO priority), and
        publish the requeue journal of incomplete requests as a
        ``serving.drained`` event.  Idempotent; returns the journal."""
        if self._drained:
            return self.requeue_journal or []
        while self.sched.slots:
            self.sched.preempt_one()
        journal = [
            {
                "id": req.id,
                # Full prompt + emitted tokens: a successor engine resubmits
                # prompt+emitted with max_new=remaining and greedy decode
                # finishes the request token-identically (the engine's own
                # re-prefill path).
                "prompt": list(req.prompt),
                "emitted": list(req.emitted),
                "remaining": req.remaining,
                "preemptions": req.preemptions,
            }
            for req in self.sched.queue
        ]
        self._drained = True
        self.requeue_journal = journal
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.drains").inc()
            tel.event(
                "serving.drained",
                incomplete=len(journal),
                completed=len(self._finished),
                journal=journal,
            )
        self._publish_gauges()
        return journal

    def pop_finished(self) -> List[CompletedRequest]:
        out, self._finished = self._finished, []
        return out

    # -- tick phases ---------------------------------------------------------

    def _table_row(self, blocks: List[int]) -> np.ndarray:
        m = self.serving.resolved_max_blocks()
        row = np.zeros((m,), np.int32)
        row[: len(blocks)] = blocks
        return row

    def _prefill_tick(self, now: float) -> None:
        sched = self.sched
        candidates = [
            (slot.admit_seq, idx)
            for idx, slot in sched.slots.items()
            if slot.request.state == RequestState.PREFILLING
        ]
        if not candidates:
            return
        _, idx = min(candidates)
        slot = sched.slots[idx]
        req = slot.request
        feed = req.to_feed
        start = slot.cache_len
        chunk_len = self.serving.prefill_chunk
        n_real = min(chunk_len, len(feed) - start)
        if not sched.grow_to(idx, start + n_real):
            return  # the slot itself was preempted to find blocks
        chunk = np.zeros((1, chunk_len), np.int32)
        chunk[0, :n_real] = feed[start : start + n_real]
        next_tok, self.cache.pool = self._prefill_fn(
            self.params,
            self.cache.pool,
            self._table_row(slot.blocks),
            np.int32(start),
            chunk,
            np.int32(n_real),
        )
        self.prefill_dispatches += 1
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.prefill_dispatches").inc()
        slot.cache_len = start + n_real
        if slot.cache_len == len(feed):
            # Final chunk: its last real logits row IS the next token — the
            # first generated token of a fresh request (TTFT lands here) or
            # the resume token of a re-prefilled one.
            self._emit(idx, int(next_tok), time.monotonic())
            if idx in sched.slots:
                sched.slots[idx].request.state = RequestState.DECODING

    def _decode_tick(self, now: float) -> None:
        sched = self.sched
        decoding = sorted(
            (idx for idx, slot in sched.slots.items()
             if slot.request.state == RequestState.DECODING),
            key=lambda i: sched.slots[i].admit_seq,
        )
        # Grow oldest-first so older requests steal blocks from younger ones
        # (matching the LIFO victim policy), then re-collect the survivors.
        for idx in decoding:
            if idx in sched.slots and sched.slots[idx].request.state == RequestState.DECODING:
                sched.grow_to(idx, sched.slots[idx].cache_len + 1)
        live = [
            idx for idx in decoding
            if idx in sched.slots and sched.slots[idx].request.state == RequestState.DECODING
        ]
        if not live:
            return
        s = self.serving.max_slots
        m = self.serving.resolved_max_blocks()
        tables = np.zeros((s, m), np.int32)
        lengths = np.zeros((s,), np.int32)
        tokens = np.zeros((s,), np.int32)
        for idx in live:
            slot = sched.slots[idx]
            tables[idx] = self._table_row(slot.blocks)
            lengths[idx] = slot.cache_len
            tokens[idx] = slot.request.emitted[-1]
        next_tokens, self.cache.pool = self._decode_fn(
            self.params, self.cache.pool, tables, lengths, tokens
        )
        self.decode_dispatches += 1
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.decode_dispatches").inc()
        out = np.asarray(next_tokens)
        emit_t = time.monotonic()
        for idx in live:
            sched.slots[idx].cache_len += 1
            self._emit(idx, int(out[idx]), emit_t)

    # -- completion / metrics ------------------------------------------------

    def _emit(self, idx: int, token: int, now: float) -> None:
        slot = self.sched.slots[idx]
        req = slot.request
        req.emitted.append(token)
        req.note_token(now)
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.tokens").inc()
            if len(req.emitted) == 1 and req.arrival_t is not None:
                tel.registry.histogram("serving.ttft_ms").observe(
                    (now - req.arrival_t) * 1e3
                )
            elif req.inter_token_ms:
                tel.registry.histogram("serving.inter_token_ms").observe(
                    req.inter_token_ms[-1]
                )
        if req.remaining == 0:
            self.sched.finish(idx, now)
            self._complete(req)

    def _complete(self, req: Request) -> None:
        ttft_ms = None
        if req.first_token_t is not None and req.arrival_t is not None:
            ttft_ms = (req.first_token_t - req.arrival_t) * 1e3
        queue_wait_ms = (
            (req.admit_t - req.arrival_t) * 1e3
            if req.admit_t is not None and req.arrival_t is not None
            else 0.0
        )
        mean_itl = (
            sum(req.inter_token_ms) / len(req.inter_token_ms)
            if req.inter_token_ms
            else None
        )
        tps = None
        if (
            req.finish_t is not None
            and req.first_token_t is not None
            and req.finish_t > req.first_token_t
            and len(req.emitted) > 1
        ):
            tps = (len(req.emitted) - 1) / (req.finish_t - req.first_token_t)
        rec = CompletedRequest(
            id=req.id,
            tokens=req.output,
            prompt_len=len(req.prompt),
            new_tokens=len(req.emitted),
            queue_wait_ms=queue_wait_ms,
            ttft_ms=ttft_ms,
            mean_inter_token_ms=mean_itl,
            tokens_per_s=tps,
            preemptions=req.preemptions,
            inter_token_ms=list(req.inter_token_ms),
        )
        self._finished.append(rec)
        tel = get_telemetry()
        if tel.enabled:
            reg = tel.registry
            reg.counter("serving.completed").inc()
            reg.histogram("serving.queue_wait_ms").observe(queue_wait_ms)
            if tps is not None:
                reg.histogram("serving.tokens_per_s").observe(tps)
            tel.event(
                "serving.request_complete",
                request=req.id,
                prompt_len=len(req.prompt),
                new_tokens=len(req.emitted),
                ttft_ms=round(ttft_ms, 3) if ttft_ms is not None else None,
                queue_wait_ms=round(queue_wait_ms, 3),
                preemptions=req.preemptions,
            )

    def _publish_gauges(self) -> None:
        tel = get_telemetry()
        if not tel.enabled:
            return
        reg = tel.registry
        alloc = self.cache.allocator
        reg.gauge("serving.active_slots").set(self.sched.active)
        reg.gauge("serving.queue_depth").set(self.sched.pending)
        reg.gauge("serving.blocks_used").set(alloc.used_blocks)
        reg.gauge("serving.block_occupancy").set(round(alloc.occupancy, 4))
        # Publish only preemptions since the last publish: a registry.reset()
        # (e.g. scoping a measurement window) must not be re-inflated with
        # engine-lifetime history.
        new_preempted = self.sched.preempted_count - self._preempted_published
        if new_preempted > 0:
            reg.counter("serving.preempted").inc(new_preempted)
        self._preempted_published = self.sched.preempted_count

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        alloc = self.cache.allocator
        return {
            "ticks": self.ticks,
            "decode_dispatches": self.decode_dispatches,
            "prefill_dispatches": self.prefill_dispatches,
            "active_slots": self.sched.active,
            "queue_depth": self.sched.pending,
            "blocks_used": alloc.used_blocks,
            "block_occupancy": round(alloc.occupancy, 4),
            "completed": len(self._finished),
            "preempted": self.sched.preempted_count,
            "pool_bytes": self.cache.pool_bytes(),
        }
