"""The serving engine: continuous batching over the paged KV cache.

One engine **tick** (:meth:`ServingEngine.step`) is:

1. **admit** — queue-head requests take free decode slots (FIFO);
2. **prefill** — at most ONE bounded chunk (``prefill_chunk`` tokens, padded
   to a static shape) of the oldest prefilling request runs, so a 10k-token
   prompt costs many small dispatches interleaved with decode instead of one
   huge dispatch that stalls every in-flight request;
3. **decode** — ONE fused jitted dispatch advances every decoding slot by
   one token.  On the default **paged fast path** the family's
   ``apply_paged`` consumes pool K/V *in place* through the block tables
   (``models/generation.py paged_cache_write``): no dense per-slot cache
   view is ever materialized, no updated view ever flows back out of the
   program — only the freshly written K/V rows, which scatter into the
   donated pool.  Block tables are **bucketed** to the next power of two of
   the widest live slot, so per-token gather traffic scales with the blocks
   requests actually own, not the worst-case table width (the
   ``serving.decode_gather_bytes`` counter is the accounting).  Families
   without ``apply_paged`` (capacity-routed MoE) or
   ``ServingConfig(decode_path="dense")`` fall back to the PR 9 program:
   gather the dense view, ``vmap`` the family's ``apply_cached``, extract
   and scatter the written rows.  Either way the
   1-dispatch-per-decode-step invariant from ``make_train_step`` carries
   over — the ``serving.decode_dispatches`` counter is the proof hook, and
   the perf_gate serving row holds paged-vs-dense decode throughput above a
   committed floor.

Prefill takes the same paged path: a chunk's program consumes the pool
through the (bucketed) block table and returns only the rows it writes —
the full per-slot view is materialized on neither side of the dispatch.

**Prefix caching** (``ServingConfig.prefix_cache``, default on): full
prompt blocks are content-hashed (a chain hash — K/V rows depend on the
whole prefix) into a :class:`~accelerate_tpu.serving.blocks.PrefixCache`
shared across requests.  A new request's prefill skips the shared prefix
(its blocks are refcount-retained into the slot's table; TTFT collapses to
the unshared suffix), the partial tail block is reused via copy-on-write,
and cache-only blocks are reclaimable capacity the allocator evicts
LRU-first under pressure.  Quarantine's scrub becomes
**scrub-on-last-release**: a poisoned shared block keeps serving its live
readers (their own finiteness checks guard them) and is zeroed only when
the last reference drops — never under a live reader.

Token selection is **greedy** (argmax, inside the fused program): outputs are
token-identical to the offline ``generate_loop`` with ``temperature=0`` per
request, which is the engine's equivalence oracle (``tests/test_serving.py``,
``make serving-smoke``).

Chunked-prefill padding contract: chunks are padded to the static
``prefill_chunk`` length.  Padded queries produce ignored logits; padded K/V
rows land at positions past the real prefix — positions the causal mask hides
from every existing query and that sequential future writes overwrite before
any query of that position exists.  Pool writes for positions past the block
table route to the null block.  The scheduler's geometry validation
guarantees ``ceil(rows / prefill_chunk) * prefill_chunk <= max_blocks_per_seq
* block_size``, so the padded write never clamps inside the dense view.

SLO metrics per request — TTFT, inter-token latency, queue wait, tokens/s,
preemption count — publish through the telemetry registry
(``serving.*`` families) and each completion emits a
``serving.request_complete`` event, which the flight recorder mirrors into
its durable ring when enabled.

Production-robustness layer (overload / deadlines / quarantine / journal):

- **Overload protection** — ``ServingConfig.max_queue_depth`` bounds the
  admission queue; past it ``submit`` raises :class:`AdmissionRejected`
  (``serving.shed`` counter), so a traffic burst degrades to load-shedding
  instead of unbounded queue growth.
- **Deadlines** — per-request TTFT and total-latency deadlines (defaults on
  the config).  Expired QUEUED requests are shed before a prefill chunk is
  spent on them; expired in-flight requests are cancelled with their blocks
  freed.  Both complete with ``status="deadline_expired"``
  (``serving.deadline_expired`` counter); a TTFT expiry observes its
  elapsed wait into ``serving.ttft_ms`` so the PR 13 SLO burn-rate gauges
  see the violation instead of a survivorship-biased histogram.
- **Poison quarantine** — both compiled programs carry an in-program
  per-slot logit-finiteness check (a reduction folded into the existing
  dispatch — zero extra dispatch, the health-guard trick).  A non-finite
  slot's request completes with ``status="quarantined"``
  (``serving.quarantined`` counter + event) while every other slot keeps
  decoding bit-identically (vmap lanes are independent).  The quarantined
  request's pool blocks are **scrubbed to zero before being freed**: the
  attention mask zeroes a hidden row's *probability*, but ``0 * NaN = NaN``
  in ``probs @ v``, so a NaN row left in a recycled block would poison its
  next owner.  ``ACCELERATE_TPU_FAULT_SERVING_NAN_REQUEST`` injects the
  poison for tests (trace-time-gated, like the train-step NaN knob).
- **Crash-recovery journal** — ``ServingConfig.journal_path`` arms a
  write-ahead journal (``serving/journal.py``): admissions and terminal
  transitions land on disk atomically, the drain path persists emitted
  progress, and a successor engine's :meth:`recover_from_journal` resubmits
  every non-terminal request and finishes it token-identically — even
  after a SIGKILL that skipped every handler.
"""

from __future__ import annotations

import contextlib
import inspect
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..models.generation import (
    extract_token_rows,
    gather_block_view,
    scatter_token_rows,
)
from ..telemetry import get_telemetry
from .blocks import (
    NULL_BLOCK,
    BlockOutOfMemory,
    PagedKVCache,
    PrefixCache,
    blocks_for_tokens,
)
from .journal import JournalError, ServingJournal
from .scheduler import Request, RequestState, Scheduler
from .tracing import ServingTracer, resolve_trace_dir, tracing_enabled

__all__ = [
    "AdmissionRejected",
    "ServingConfig",
    "ServingEngine",
    "CompletedRequest",
]


class AdmissionRejected(RuntimeError):
    """Typed load-shedding rejection: the admission queue is at
    ``max_queue_depth``.  Deliberately NOT a ``ValueError`` — the request
    was well-formed; the engine is overloaded.  Callers retry with backoff
    or fail over; the ``serving.shed`` counter records every rejection."""


@dataclass
class ServingConfig:
    """Engine geometry (everything here is a static shape of the compiled
    programs — two programs total, however many requests flow through).

    - ``block_size``: tokens per KV block.  Small blocks waste less tail
      space per request; large blocks shrink the tables.  16-64 is typical.
    - ``num_blocks``: pool size (one block is reserved as the null block).
      Pool HBM = ``num_blocks * block_size`` rows per layer — budget this
      like a dense cache of total length ``num_blocks * block_size`` shared
      by ALL requests, not tiled per request.
    - ``max_slots``: the decode batch width (static).  More slots = more
      requests advanced per decode dispatch.
    - ``max_blocks_per_seq``: block-table width (static); caps any single
      request at ``max_blocks_per_seq * block_size`` cache rows.
    - ``prefill_chunk``: prompt tokens per prefill dispatch (static).

    Robustness knobs (all host-side policy, no effect on the compiled
    programs):

    - ``max_queue_depth``: admission-queue bound; ``submit`` past it raises
      :class:`AdmissionRejected` (None = unbounded, the pre-overload
      behavior).
    - ``default_ttft_deadline_ms`` / ``default_deadline_ms``: deadlines
      applied to requests that do not pass their own (None = no deadline).
    - ``journal_path``: arm the crash-recovery write-ahead journal at this
      path (see ``serving/journal.py``).
    - ``host_blocks``: size of the host-DRAM KV tier (0 = disabled, the
      pre-tiering behavior).  With a tier, preemption **demotes** the
      victim's blocks to host memory instead of freeing them (re-admission
      promotes and resumes with zero re-prefill dispatches), cold
      prefix-cache chains demote on eviction pressure instead of dropping,
      and the free-and-re-prefill path survives only as the fallback when
      the host tier is full.  Host-side policy plus batched D2H/H2D copies
      between dispatches — the compiled programs are identical either way.
    - ``tier_demote_batch``: max cold prefix chains proactively demoted per
      tick when the allocator's raw free list falls under the headroom
      watermark (demote-before-shed; 0 disables the proactive sweep —
      on-demand demotion inside eviction still applies).

    Decode fast-path knobs:

    - ``decode_path``: ``"paged"`` (default) computes attention straight
      through the block tables via the family's ``apply_paged`` — falling
      back to ``"dense"`` automatically when the family has none (MoE);
      ``"dense"`` forces the PR 9 gather-view program (the always-correct
      reference path, and the perf_gate contrast arm).
    - ``paged_kernel``: route single-token fp decode attention through the
      Pallas paged-attention kernel (``ops/pallas_attention.py``).  The XLA
      paged path is the always-correct fallback (int8 pools and prefill
      chunks stay on it); the kernel's online softmax may differ from it in
      final ulps.
    - ``prefix_cache``: share full prompt blocks across requests by content
      hash (copy-on-write tail, refcounted blocks, LRU reclaim).  Host-side
      policy only — the compiled programs are identical either way.

    Speculative decode knobs (draft-then-verify; token-identical to greedy
    by the accept rule — see ``models/generation.py
    speculative_verify_greedy``):

    - ``spec_tokens``: the draft window ``k``.  0 (default) disables; at
      ``k > 0`` each decode tick asks the drafter for up to ``k`` candidate
      tokens per slot and the target verifies all slots' ``k+1``-token
      windows in ONE fused dispatch, emitting 1..k+1 tokens per slot per
      tick.  Block budgeting grows by the worst-case ``k``-row overshoot
      (``Scheduler.max_rows``).
    - ``spec_ngram_max`` / ``spec_ngram_min``: n-gram match lengths for the
      default prompt-lookup drafter (``serving/drafter.py NgramDrafter``);
      ignored when a custom ``drafter=`` is passed to the engine.

    Tracing knobs (``serving/tracing.py`` — host-side interval bookkeeping,
    no effect on the compiled programs):

    - ``trace``: per-request phase tracing.  ``None`` (default) defers to
      ``ACCELERATE_TPU_SERVING_TRACE`` (default-on; ``0`` kills).
    - ``trace_dir``: where trace JSONL persists; ``None`` defers to
      ``ACCELERATE_TPU_SERVING_TRACE_DIR``, then the enabled telemetry run
      dir, else in-memory only.
    """

    block_size: int = 16
    num_blocks: int = 64
    max_slots: int = 4
    max_blocks_per_seq: Optional[int] = None
    prefill_chunk: int = 32
    max_queue_depth: Optional[int] = None
    default_ttft_deadline_ms: Optional[float] = None
    default_deadline_ms: Optional[float] = None
    journal_path: Optional[str] = None
    host_blocks: int = 0
    tier_demote_batch: int = 8
    decode_path: str = "paged"
    paged_kernel: bool = False
    prefix_cache: bool = True
    spec_tokens: int = 0
    spec_ngram_max: int = 3
    spec_ngram_min: int = 1
    trace: Optional[bool] = None
    trace_dir: Optional[str] = None

    def resolved_max_blocks(self) -> int:
        if self.max_blocks_per_seq is not None:
            return self.max_blocks_per_seq
        return self.num_blocks - 1


@dataclass
class CompletedRequest:
    """Completion record: the tokens plus the request's SLO timeline.

    ``status`` is ``"ok"`` for a normal completion, ``"deadline_expired"``
    for a request cancelled/shed past its deadline (``tokens`` holds
    whatever was emitted before expiry), or ``"quarantined"`` for a request
    whose decode produced non-finite logits (``tokens`` excludes the
    poisoned token — it was never meaningful)."""

    id: int
    tokens: List[int]
    prompt_len: int
    new_tokens: int
    queue_wait_ms: float
    ttft_ms: Optional[float]
    mean_inter_token_ms: Optional[float]
    tokens_per_s: Optional[float]
    preemptions: int
    inter_token_ms: List[float] = field(default_factory=list)
    status: str = "ok"
    tag: Optional[str] = None
    # KV-tiering accounting: host-tier round-trips this request survived,
    # times the host tier was full so a preemption fell back to the plain
    # re-prefill, and prefill dispatches it consumed in total (the
    # zero-re-prefill oracle: a migrated resume adds none).
    migrations: int = 0
    fallback_reprefills: int = 0
    prefill_dispatches: int = 0


class ServingEngine:
    """Continuous-batching serving over a model family's
    ``apply_cached``/``init_cache`` pair (any family following the
    ``make_kv_cache`` layout — gpt2/llama/mixtral, fp or int8 KV).  The
    token-identity-vs-``generate_loop`` guarantee needs a
    chunking-independent forward (dense FFN); capacity-limited MoE routing
    (mixtral) varies with prefill chunking here exactly as it does under
    offline ``prefill_chunk``.

    ::

        engine = ServingEngine(gpt2.apply_cached, gpt2.init_cache, params, cfg,
                               serving=ServingConfig(max_slots=8))
        rid = engine.submit(prompt_tokens, max_new_tokens=64)
        outputs = engine.run()          # {rid: full token list}

    or drive it tick-by-tick with :meth:`step` / :meth:`pop_finished`.
    """

    def __init__(
        self,
        apply_cached: Callable,
        init_cache: Callable,
        params,
        config,
        serving: Optional[ServingConfig] = None,
        drafter=None,
    ):
        self.serving = serving or ServingConfig()
        sc = self.serving
        if sc.prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {sc.prefill_chunk}")
        if sc.resolved_max_blocks() < 1:
            raise ValueError("max_blocks_per_seq must be >= 1")
        if sc.spec_tokens < 0:
            raise ValueError(f"spec_tokens must be >= 0, got {sc.spec_tokens}")
        if sc.host_blocks < 0:
            raise ValueError(f"host_blocks must be >= 0, got {sc.host_blocks}")
        self._apply_cached = apply_cached
        self._config = config
        self.params = params
        self.spec_tokens = int(sc.spec_tokens)
        self.cache = PagedKVCache(
            init_cache, config, sc.num_blocks, sc.block_size,
            num_host_blocks=sc.host_blocks,
        )
        self.sched = Scheduler(
            self.cache.allocator,
            num_slots=sc.max_slots,
            block_size=sc.block_size,
            max_blocks_per_seq=sc.resolved_max_blocks(),
            prefill_chunk=sc.prefill_chunk,
            spec_overshoot=self.spec_tokens,
        )
        max_len = sc.resolved_max_blocks() * sc.block_size
        model_max = getattr(config, "max_seq_len", None)
        if model_max is not None and max_len > model_max:
            raise ValueError(
                f"max_blocks_per_seq * block_size = {max_len} exceeds the "
                f"model's max_seq_len {model_max}; shrink the table or blocks"
            )
        self._kv_names = self.cache.leaf_names
        self._finished: List[CompletedRequest] = []
        self._preempted_published = 0
        self._preemption_guard = None
        self._drained = False
        self.requeue_journal: Optional[List[dict]] = None
        self.ticks = 0
        self.decode_dispatches = 0
        self.decode_emitted_tokens = 0
        self.decode_slot_ticks = 0
        self.spec_rounds = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.prefill_dispatches = 0
        self.shed_count = 0
        self.deadline_expired_count = 0
        self.quarantined_count = 0
        self.prefix_hits = 0
        self.prefix_blocks_reused = 0
        self.cow_copies = 0
        self.decode_gather_bytes = 0
        # KV-tiering accounting (engine-side migrations; the prefix cache's
        # own demote/promote churn is folded in at publish time).
        self.tier_demotions = 0
        self.tier_promotions = 0
        self.tier_demoted_blocks = 0
        self.tier_fallback_reprefills = 0
        self._prefix_demotions_published = 0
        self._prefix_promotions_published = 0
        self._draining = False
        self._submissions = 0
        self._recovering = False
        # NaN poison injection is gated at TRACE time (the train-step trick):
        # the unarmed decode program carries no poison plumbing at all; the
        # in-program finiteness detection is always compiled in.
        from ..resilience import faultinject

        self._poison_ordinal = faultinject.serving_nan_ordinal()
        self.journal: Optional[ServingJournal] = (
            ServingJournal(sc.journal_path) if sc.journal_path else None
        )
        # Decode-path resolution: "paged" consumes the pool in place through
        # the family's apply_paged (same module as apply_cached); a family
        # without one (capacity-routed MoE — per-batch routing is not
        # row-independent) falls back to the dense gather-view program.
        if sc.decode_path not in ("paged", "dense"):
            raise ValueError(
                f"decode_path must be 'paged' or 'dense', got {sc.decode_path!r}"
            )
        self._paged_apply = None
        if sc.decode_path == "paged":
            family = inspect.getmodule(apply_cached)
            self._paged_apply = getattr(family, "apply_paged", None)
        self.decode_path = "paged" if self._paged_apply is not None else "dense"
        self._block_bytes = self.cache.block_bytes()
        self._prefix: Optional[PrefixCache] = (
            PrefixCache(self.cache.allocator, sc.block_size)
            if sc.prefix_cache else None
        )
        if self.cache.host is not None:
            # Wire the tiering policies in: eviction pressure demotes cold
            # prefix chains instead of dropping them, and preemption demotes
            # the victim's KV instead of freeing it (the scheduler falls
            # back to the plain free-and-re-prefill when the hook declines).
            if self._prefix is not None:
                self._prefix.attach_tier(self.cache)
            self.sched.on_migrate_out = self._migrate_out
        # Per-request phase tracing (host-side interval bookkeeping only).
        # The scheduler's preemption callback is the one eviction site every
        # preemption flavor funnels through (drain, block pressure, LIFO
        # victim), so the tracer sees them all without per-caller plumbing.
        self.tracer: Optional[ServingTracer] = None
        if tracing_enabled(sc.trace):
            self.tracer = ServingTracer(dir=resolve_trace_dir(sc.trace_dir))
            self.sched.on_preempt = (
                lambda req: self.tracer.on_preempt(req, time.monotonic())
            )
        # Per-width jit-cache bookkeeping for bucket-compile attribution:
        # a width this engine has not dispatched yet means the next dispatch
        # pays a trace+compile in the request's latency path.
        self._seen_widths: Dict[str, set] = {
            "decode": set(), "decode_spec": set(), "prefill": set(),
        }
        # Live /debug endpoints: the metrics HTTP server asks registered
        # engines for request/block snapshots (weakly — a collected engine
        # just drops off the page).
        from ..telemetry import export as _export

        _export.register_debug_source(self)
        # HBM ledger: the pool is a first-class reservation (its backing
        # arrays live for the engine's life), the prefix-cache residents a
        # subset entry (their bytes are INSIDE the pool — counting them
        # twice would poison the conservation residual).  A second engine
        # replaces the entries (last constructed wins); weakref.finalize
        # drops them when the owning engine is collected, token-guarded so
        # a replacement registration survives its predecessor's GC.
        from ..telemetry.memledger import get_memory_ledger

        ledger = get_memory_ledger()
        pool_token = ledger.register(
            "serving.kv_pool",
            tree=self.cache.pool,
            detail={
                "num_blocks": sc.num_blocks,
                "block_size": sc.block_size,
                "block_bytes": self._block_bytes,
            },
        )
        prefix_token = ledger.register(
            "serving.prefix_cache", nbytes=0, subset_of="serving.kv_pool"
        )
        import weakref

        weakref.finalize(self, ledger.unregister, "serving.kv_pool", pool_token)
        weakref.finalize(self, ledger.unregister, "serving.prefix_cache", prefix_token)
        self._memledger_tokens = (pool_token, prefix_token)
        if self.cache.host is not None:
            # The host tier's backing arrays live for the engine's life, so
            # the reservation is static — and it charges host DRAM, not HBM
            # (per_device stays empty; the conservation residual must not
            # absorb bytes that never touched a device).
            host_token = ledger.register(
                "serving.kv_host",
                per_device={},
                host_bytes=self.cache.host.pool_bytes(),
                detail={
                    "host_blocks": sc.host_blocks,
                    "block_size": sc.block_size,
                    "block_bytes": self._block_bytes,
                },
            )
            weakref.finalize(self, ledger.unregister, "serving.kv_host", host_token)
            self._memledger_tokens = (pool_token, prefix_token, host_token)
        self._low_headroom = False
        try:
            self._headroom_watermark_frac = float(
                os.environ.get("ACCELERATE_TPU_SERVING_HEADROOM_WATERMARK", "") or 0.1
            )
        except ValueError:
            self._headroom_watermark_frac = 0.1
        # Hysteresis band for re-arming the low-headroom event: re-arm only
        # after the pool recovers ABOVE 1.5x the watermark, so a pool
        # oscillating right at the line emits one event per genuine pressure
        # episode instead of one per tick-scale wobble.
        self._headroom_rearm_frac = min(self._headroom_watermark_frac * 1.5, 1.0)
        if self.decode_path == "paged":
            # One jitted wrapper each; bucketed table widths retrace under it
            # (jit caches per shape), so a tick is still exactly one decode
            # dispatch — just of the program matching the live bucket.
            self._decode_fn = jax.jit(self._build_decode_paged(), donate_argnums=(1,))
            self._prefill_fn = jax.jit(self._build_prefill_paged(), donate_argnums=(1,))
        else:
            self._decode_fn = jax.jit(self._build_decode(), donate_argnums=(1,))
            self._prefill_fn = jax.jit(self._build_prefill(), donate_argnums=(1,))
        # Speculative draft-then-verify: one more jitted program (the W-token
        # verify), plus a host-side drafter.  A tick with live drafts runs
        # the verify program INSTEAD of the single-token one — still exactly
        # one fused decode dispatch per tick.
        self._drafter = None
        self._decode_spec_fn = None
        if self.spec_tokens > 0:
            if drafter is None:
                from .drafter import NgramDrafter

                drafter = NgramDrafter(
                    max_ngram=sc.spec_ngram_max, min_ngram=sc.spec_ngram_min
                )
            self._drafter = drafter
            builder = (
                self._build_decode_spec_paged
                if self.decode_path == "paged" else self._build_decode_spec
            )
            self._decode_spec_fn = jax.jit(builder(), donate_argnums=(1,))
        # Pre-create the robustness + fast-path counters so the Prometheus
        # endpoint exposes them at 0 from the first scrape — a dashboard can
        # alert on rate() without waiting for the first incident (or the
        # first prefix hit) to make the series exist.
        tel = get_telemetry()
        if tel.enabled:
            for name in (
                "serving.shed", "serving.deadline_expired",
                "serving.quarantined", "serving.journal_recoveries",
                "serving.prefix_hits", "serving.prefix_blocks_reused",
                "serving.prefix_cow_copies", "serving.decode_gather_bytes",
                "serving.spec.proposed", "serving.spec.accepted",
                "serving.spec.rounds",
                "serving.tier.demotions", "serving.tier.promotions",
                "serving.tier.demoted_blocks", "serving.tier.fallback_reprefills",
            ):
                tel.registry.counter(name)
            tel.registry.gauge("serving.spec.acceptance_rate").set(0.0)
            tel.registry.gauge("serving.tokens_per_dispatch").set(0.0)
            tel.registry.gauge("serving.tier.host_bytes").set(0)
            tel.registry.gauge("serving.tier.host_occupancy").set(0.0)

    # -- compiled programs ---------------------------------------------------

    def _build_decode_paged(self):
        """The in-dispatch paged decode: the family's ``apply_paged`` reads
        pool K/V straight through the (bucketed) block tables — no dense
        per-slot view in, no updated view out, only the written rows, which
        scatter into the donated pool inside the same dispatch."""
        apply_paged, config = self._paged_apply, self._config
        kernel = self.serving.paged_kernel

        def decode(params, pool, tables, lengths, tokens, *poison):
            logits, rows = apply_paged(
                params, tokens[:, None], config, pool, tables, lengths,
                kernel=kernel,
            )
            logits = logits[:, -1]
            if poison:  # trace-time gate: unarmed programs carry no plumbing
                logits = logits * poison[0][:, None]
            next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            ok = jnp.all(jnp.isfinite(logits), axis=-1)
            new_pool = dict(pool)
            for n, r in rows.items():
                new_pool[n] = scatter_token_rows(pool[n], r, tables, lengths, 1)
            return next_tok, ok, new_pool

        return decode

    def _build_prefill_paged(self):
        """Paged prefill: the chunk's program consumes the pool through the
        bucketed table row and returns ONLY the rows it writes — the dense
        per-slot view is materialized on neither side of the dispatch (the
        PR 9 program gathered it in AND flowed the updated copy out)."""
        apply_paged, config = self._paged_apply, self._config
        chunk_len = self.serving.prefill_chunk

        def prefill(params, pool, table_row, length, chunk, n_real):
            logits, rows = apply_paged(
                params, chunk, config, pool, table_row[None], length[None]
            )
            next_tok = jnp.argmax(logits[0, n_real - 1], axis=-1).astype(jnp.int32)
            ok = jnp.all(jnp.isfinite(logits))
            new_pool = dict(pool)
            for n, r in rows.items():
                new_pool[n] = scatter_token_rows(
                    pool[n], r, table_row[None], length[None], chunk_len
                )
            return next_tok, ok, new_pool

        return prefill

    def _build_decode_spec_paged(self):
        """The speculative verify dispatch, paged flavor: every slot's
        ``[last, d_1..d_k]`` window goes through ``apply_paged`` as a
        ``[S, k+1]`` query block (causally masked against the paged K/V plus
        the in-window prefix), the shared greedy accept kernel scores all
        rows at once, and all ``k+1`` freshly written K/V rows scatter into
        the donated pool.  Rows past a slot's accepted length are stale by
        construction — the next dispatch at the rewound length re-writes
        them before its masks ever admit those positions (the offline
        loop's rewind argument, per-slot)."""
        apply_paged, config = self._paged_apply, self._config
        kernel = self.serving.paged_kernel
        from ..models.generation import speculative_verify_greedy

        def decode(params, pool, tables, lengths, tokens, draft_len, *poison):
            window = tokens.shape[1]
            logits, rows = apply_paged(
                params, tokens, config, pool, tables, lengths, kernel=kernel,
            )  # [S, W, V]
            if poison:  # trace-time gate: unarmed programs carry no plumbing
                logits = logits * poison[0][:, None, None]
            t, m = speculative_verify_greedy(logits, tokens[:, 1:], draft_len)
            ok = jnp.all(jnp.isfinite(logits), axis=(1, 2))
            new_pool = dict(pool)
            for n, r in rows.items():
                new_pool[n] = scatter_token_rows(pool[n], r, tables, lengths, window)
            return t, m, ok, new_pool

        return decode

    def _build_decode_spec(self):
        """Speculative verify, dense flavor: per-slot gather views (the PR 9
        reference path) with a W-token cached forward per lane under vmap —
        the contrast arm proving accept/rewind correctness is independent of
        the paged fast path."""
        apply_cached, config, names = self._apply_cached, self._config, self._kv_names
        from ..models.generation import speculative_verify_greedy

        def decode(params, pool, tables, lengths, tokens, draft_len, *poison):
            window = tokens.shape[1]
            views = {n: gather_block_view(pool[n], tables) for n in names}
            caches = dict(views, index=lengths)

            def one(cache, toks):
                logits, new_cache = apply_cached(params, toks[None, :], config, cache)
                return logits[0], new_cache

            logits, new_caches = jax.vmap(one)(caches, tokens)  # [S, W, V]
            if poison:  # trace-time gate: unarmed programs carry no plumbing
                logits = logits * poison[0][:, None, None]
            t, m = speculative_verify_greedy(logits, tokens[:, 1:], draft_len)
            ok = jnp.all(jnp.isfinite(logits), axis=(1, 2))
            new_pool = {}
            for n in names:
                rows = extract_token_rows(new_caches[n], lengths, window)
                new_pool[n] = scatter_token_rows(pool[n], rows, tables, lengths, window)
            return t, m, ok, new_pool

        return decode

    def _build_decode(self):
        apply_cached, config, names = self._apply_cached, self._config, self._kv_names

        def decode(params, pool, tables, lengths, tokens, *poison):
            views = {n: gather_block_view(pool[n], tables) for n in names}
            caches = dict(views, index=lengths)

            def one(cache, tok):
                logits, new_cache = apply_cached(params, tok[None, None], config, cache)
                return logits[0, -1], new_cache

            logits, new_caches = jax.vmap(one)(caches, tokens)
            if poison:  # trace-time gate: unarmed programs carry no plumbing
                logits = logits * poison[0][:, None]
            next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # Per-slot finiteness, folded into the SAME dispatch (a [S, V]
            # reduction — zero extra dispatch): a poisoned slot is detected
            # the tick it happens, before its garbage token is emitted.
            ok = jnp.all(jnp.isfinite(logits), axis=-1)
            new_pool = {}
            for n in names:
                rows = extract_token_rows(new_caches[n], lengths, 1)
                new_pool[n] = scatter_token_rows(pool[n], rows, tables, lengths, 1)
            return next_tok, ok, new_pool

        return decode

    def _build_prefill(self):
        apply_cached, config, names = self._apply_cached, self._config, self._kv_names
        chunk_len = self.serving.prefill_chunk

        def prefill(params, pool, table_row, length, chunk, n_real):
            tables = table_row[None]  # [1, M]
            start = length[None]
            cache = {n: gather_block_view(pool[n], tables)[0] for n in names}
            cache["index"] = length
            logits, new_cache = apply_cached(params, chunk, config, cache)
            next_tok = jnp.argmax(logits[0, n_real - 1], axis=-1).astype(jnp.int32)
            ok = jnp.all(jnp.isfinite(logits))
            new_pool = {}
            for n in names:
                rows = extract_token_rows(new_cache[n][None], start, chunk_len)
                new_pool[n] = scatter_token_rows(pool[n], rows, tables, start, chunk_len)
            return next_tok, ok, new_pool

        return prefill

    # -- request API ---------------------------------------------------------

    def install_preemption_guard(self, guard) -> None:
        """Honor a resilience :class:`PreemptionGuard`
        (``accelerator.enable_preemption_handling()`` installs one): once the
        fleet agrees a preemption signal arrived, the next :meth:`step` call
        DRAINS the engine instead of ticking — admission stops, in-flight
        slots are preempted back to the queue with their emitted tokens
        carried, and a ``serving.drained`` event records the requeue journal
        of incomplete requests so a successor process can resubmit them
        (re-prefilling prompt+emitted rebuilds each cache bit-identically,
        the same path a block-pressure preemption takes)."""
        if self._drained:
            raise RuntimeError(
                "engine already drained: the requeue journal is final and "
                "admission is closed — build a successor engine instead of "
                "re-arming this one."
            )
        self._preemption_guard = guard

    @property
    def drained(self) -> bool:
        return self._drained

    def submit(
        self,
        prompt_ids,
        max_new_tokens: int,
        arrival_t: Optional[float] = None,
        *,
        tag: Optional[str] = None,
        ttft_deadline_ms: Optional[float] = None,
        deadline_ms: Optional[float] = None,
    ) -> int:
        """Queue one request; returns its id.  ``max_new_tokens == 0``
        completes immediately (the offline loop's contract).

        Raises :class:`AdmissionRejected` when the queue is at
        ``max_queue_depth`` (load shedding — ``serving.shed``); ``ValueError``
        when the request's geometry can never be served.  Deadlines default
        from the :class:`ServingConfig`; an explicit per-request value wins
        (``None`` means "use the default", so a config default cannot be
        waived per request).  ``tag`` is an opaque caller label carried
        into the :class:`CompletedRequest`, the journal, and the
        ``serving.request_complete`` event — the stable identity across a
        journal recovery, where engine ids change."""
        if self._drained:
            raise RuntimeError(
                "engine drained after a preemption signal: admission is closed "
                "and the requeue journal is final — resubmit to a successor "
                "engine (see engine.requeue_journal)."
            )
        sc = self.serving
        if (
            sc.max_queue_depth is not None
            and not self._recovering
            and self.sched.pending >= sc.max_queue_depth
        ):
            self.shed_count += 1
            tel = get_telemetry()
            if tel.enabled:
                tel.registry.counter("serving.shed").inc()
            raise AdmissionRejected(
                f"admission queue full ({self.sched.pending} >= "
                f"max_queue_depth {sc.max_queue_depth}): request shed"
            )
        req = Request(
            list(np.asarray(prompt_ids).reshape(-1)),
            max_new_tokens,
            arrival_t,
            tag=tag,
            ttft_deadline_ms=(
                ttft_deadline_ms if ttft_deadline_ms is not None
                else sc.default_ttft_deadline_ms
            ),
            deadline_ms=(
                deadline_ms if deadline_ms is not None else sc.default_deadline_ms
            ),
        )
        if req.max_new_tokens == 0:
            now = time.monotonic()
            req.state = RequestState.DONE
            req.admit_t = req.finish_t = now
        else:
            self.sched.submit(req)  # geometry validation may reject — count after
        self._submissions += 1
        if self._poison_ordinal is not None and self._submissions == self._poison_ordinal:
            req._poison_pending = True  # fires on this request's first decode
        # Write-ahead: the admission lands on disk BEFORE the id is returned,
        # so every acknowledged request is recoverable after a SIGKILL.
        if self.journal is not None:
            self.journal.record_admit(req)
        if self.tracer is not None:
            self.tracer.on_submit(req)
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.requests").inc()
        if req.state == RequestState.DONE:
            self._complete(req)
        return req.id

    def step(self) -> List[CompletedRequest]:
        """One engine tick: admit, one prefill chunk, one fused decode
        dispatch.  Returns the requests that completed this tick.  With an
        installed :class:`PreemptionGuard` whose signal has arrived, the
        tick drains instead (no admission, no dispatch)."""
        now = time.monotonic()
        done_before = len(self._finished)
        if self._drained or self._drain_requested():
            self.drain()
            return []
        self.ticks += 1
        if self.tracer is not None:
            self.tracer.begin_tick(now)
        self._drain_scrubs()
        # Deadline expiry FIRST: an expired queued request is shed before a
        # slot, a prefill chunk, or any blocks are spent on it.
        self._expire_deadlines(now)
        # Demote-before-shed: with the raw free list under the watermark,
        # batch-demote cold prefix chains to host DRAM BEFORE admission, so
        # the allocations this tick makes hit the free list instead of
        # dropping cached content on demand.
        self._pressure_relief()
        admitted = self.sched.admit(now)
        if self.tracer is not None:
            admit_t = time.monotonic()
            for idx in admitted:
                self.tracer.on_admit(self.sched.slots[idx].request, admit_t, idx)
        for idx in admitted:
            # Host-tier round-trip first: a re-admitted migration victim
            # promotes its demoted KV back and resumes exactly where it
            # stopped (zero re-prefill dispatches); _attach_prefix then
            # skips it (its cache_len is already set).
            self._promote_admitted(idx)
        for idx in admitted:
            self._attach_prefix(idx)
        self._observe_requeue_waits(admitted)
        self._prefill_tick(now)
        self._decode_tick(now)
        self._drain_scrubs()
        if self.tracer is not None:
            self.tracer.end_tick(time.monotonic(), self.sched.slots)
        self._publish_gauges()
        return self._finished[done_before:]

    def run(self, max_ticks: Optional[int] = None) -> Dict[int, List[int]]:
        """Drive ticks until every submitted request completes; returns
        ``{request_id: full token list (prompt + generated)}``.  A
        preemption-triggered drain ends the loop early: completed requests
        are returned, incomplete ones are in :attr:`requeue_journal`."""
        ticks = 0
        while not self.sched.idle():
            self.step()
            if self._drained:
                break
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                raise RuntimeError(
                    f"engine did not drain within {max_ticks} ticks "
                    f"(active {self.sched.active}, queued {self.sched.pending})"
                )
        return {c.id: c.tokens for c in self._finished}

    def _drain_requested(self) -> bool:
        """Whether the installed guard says stop.  For a multi-host
        COORDINATED guard the LOCAL flag is consulted, never should_stop():
        that path gates a cross-host collective on a per-guard call counter
        that every process must hit in lockstep, and engine tick counts are
        data-dependent (queue depth differs per host) — one desynchronized
        gather would hang the fleet.  Fleet-wide stop agreement belongs to
        the training loop's check_preemption(); the drain itself is a local
        action (each host journals its own queue)."""
        guard = self._preemption_guard
        if guard is None:
            return False
        coordinated = getattr(guard, "_coordination_on", None)
        if coordinated is not None and coordinated():
            return guard.preempted_locally()
        return guard.should_stop()

    def drain(self) -> List[dict]:
        """Graceful drain: stop admission, preempt every in-flight slot back
        to the queue (blocks freed, emitted tokens carried — the oldest
        request ends up at the queue FRONT, preserving FIFO priority), and
        publish the requeue journal of incomplete requests as a
        ``serving.drained`` event.  Idempotent; returns the journal."""
        if self._drained:
            return self.requeue_journal or []
        # Migration is pointless past this line: host DRAM dies with the
        # process, so demoting a drained slot would spend a D2H copy on
        # bytes no successor can read — and leak the host blocks at exit.
        # The flag makes _migrate_out decline; every slot takes the classic
        # free-and-requeue path, and already-demoted queued victims release
        # their host blocks below (the journal recorded their progress).
        self._draining = True
        while self.sched.slots:
            self.sched.preempt_one()
        for req in self.sched.queue:
            self._release_demoted(req)
        journal = [
            {
                "id": req.id,
                # Full prompt + emitted tokens: a successor engine resubmits
                # prompt+emitted with max_new=remaining and greedy decode
                # finishes the request token-identically (the engine's own
                # re-prefill path).
                "prompt": list(req.prompt),
                "emitted": list(req.emitted),
                "remaining": req.remaining,
                "preemptions": req.preemptions,
                "tag": req.tag,
            }
            for req in self.sched.queue
        ]
        self._drained = True
        self.requeue_journal = journal
        self._drain_scrubs()
        if self.journal is not None:
            # Persist emitted progress so the successor resumes mid-request
            # (prompt+emitted) instead of re-decoding from the prompt.
            self.journal.record_progress(self.sched.queue)
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.drains").inc()
            tel.event(
                "serving.drained",
                incomplete=len(journal),
                completed=len(self._finished),
                journal=journal,
            )
        if self.tracer is not None:
            # Snapshot every still-live timeline: the successor's stitcher
            # needs this life's partial phases even though no terminal
            # record will ever land here.
            self.tracer.flush()
        self._publish_gauges()
        return journal

    def pop_finished(self) -> List[CompletedRequest]:
        out, self._finished = self._finished, []
        return out

    # -- crash recovery ------------------------------------------------------

    def recover_from_journal(self, path: Optional[str] = None) -> Dict[int, int]:
        """Rebuild a dead predecessor's queue from its write-ahead journal:
        every journaled request with no terminal record is resubmitted as
        ``prompt + emitted`` with ``max_new = remaining`` (the bit-exact
        re-prefill path), so this engine finishes each one token-identically
        to the uninterrupted run.  Returns ``{old id: new id}``.

        Call BEFORE the first ``submit`` when this engine journals to the
        same path — the first admission overwrites the file.  Deadlines
        restart from recovery time (the predecessor's arrival clock died
        with it); a request that already blew its deadline there was either
        already shed (terminal in the journal) or gets a fresh budget here.
        Terminal requests — completed, shed, quarantined — are never
        replayed."""
        path = path or self.serving.journal_path
        if path is None:
            raise ValueError("no journal path: pass one or set ServingConfig.journal_path")
        if self.journal is not None and self.journal.flushed and os.path.abspath(
            path
        ) == os.path.abspath(self.journal.path):
            raise JournalError(
                "this engine already overwrote the journal at "
                f"{path!r}; recover_from_journal must run before the first submit"
            )
        state = ServingJournal.load(path)
        pending = ServingJournal.pending(state)
        mapping: Dict[int, int] = {}
        # Recovery resubmissions bypass the max_queue_depth shed (a dead
        # engine's backlog is not a traffic burst — shedding here would
        # silently LOSE acknowledged requests) and batch the journal into
        # ONE atomic flush: flushing per resubmit would overwrite the
        # predecessor's file after the first one, so a SIGKILL mid-recovery
        # would strand the rest with no journal anywhere.
        batch = self.journal.deferred() if self.journal is not None else contextlib.nullcontext()
        self._recovering = True
        try:
            with batch:
                for rec in pending:
                    emitted = rec.get("emitted") or []
                    rid = self.submit(
                        rec["prompt"] + list(emitted),
                        rec["max_new_tokens"] - len(emitted),
                        tag=rec.get("tag"),
                        ttft_deadline_ms=rec.get("ttft_deadline_ms"),
                        deadline_ms=rec.get("deadline_ms"),
                    )
                    mapping[rec["id"]] = rid
                    if self.tracer is not None:
                        self.tracer.on_recover(rid, rec)
        finally:
            self._recovering = False
        if self.tracer is not None:
            # Land the recovered requests' snapshot lines immediately: the
            # stitcher can already pair this life with the victim's even if
            # this engine is itself killed before any completes.
            self.tracer.flush()
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.journal_recoveries").inc()
            tel.event(
                "serving.journal_recovered",
                path=path,
                recovered=len(mapping),
                terminal=len(state["done"]),
            )
        return mapping

    # -- KV tiering (host-DRAM second tier) ----------------------------------

    def _migrate_out(self, slot) -> bool:
        """Preemption-as-migration (the scheduler's ``on_migrate_out`` hook):
        copy the victim slot's blocks to the host tier, release the device
        references, and stash the host ids + resume state on the request —
        re-admission then promotes and resumes with zero re-prefill
        dispatches.  Declines (→ plain free-and-re-prefill) during a drain
        (host DRAM dies with the process; demoting would waste a copy and
        leak at exit), when any block is quarantine-dirty (a possibly
        poisoned block must be rebuilt clean, never tiered), or when the
        host tier cannot fit even after dropping cold cached prefixes (a
        live request outranks a cold chain)."""
        req = slot.request
        blocks = slot.blocks
        if self._draining or not blocks:
            return False
        alloc = self.cache.allocator
        tel = get_telemetry()
        if any(alloc.is_dirty(b) for b in blocks):
            req.fallback_reprefills += 1
            self.tier_fallback_reprefills += 1
            if tel.enabled:
                tel.registry.counter("serving.tier.fallback_reprefills").inc()
            return False
        n = len(blocks)
        if not self.cache.host_can_fit(n) and self._prefix is not None and self.cache.host is not None:
            need = n - self.cache.host.free_blocks
            if 0 < need <= self._prefix.host_count:
                self._prefix.drop_host_entries(need)
        if not self.cache.host_can_fit(n):
            req.fallback_reprefills += 1
            self.tier_fallback_reprefills += 1
            if tel.enabled:
                tel.registry.counter("serving.tier.fallback_reprefills").inc()
            return False
        host_ids = self.cache.demote(blocks)
        req.demoted_blocks = host_ids
        req.demoted_rows = slot.cache_len
        req.demoted_registered = slot.registered_blocks
        req.migrations += 1
        alloc.free(blocks)  # demotion copied; release the slot's device refs
        self.tier_demotions += 1
        self.tier_demoted_blocks += n
        if tel.enabled:
            tel.registry.counter("serving.tier.demotions").inc()
            tel.registry.counter("serving.tier.demoted_blocks").inc(n)
        if self.journal is not None:
            self.journal.record_tier(req, "host")
        return True

    def _promote_admitted(self, idx: int) -> None:
        """Re-admission half of preemption-as-migration: allocate device
        blocks for a demoted request, copy its KV back from the host tier,
        and restore the slot exactly as preemption found it — cache_len,
        registration cursor, and DECODING state when the cache already
        covers every fed token but the last emitted one (the decode
        invariant), so no prefill dispatch is ever spent on the resume.
        When the device pool cannot grant the blocks, the request falls
        back to the PR 9 re-prefill (host blocks released, counted)."""
        slot = self.sched.slots.get(idx)
        if slot is None:
            return
        req = slot.request
        host_ids = req.demoted_blocks
        if not host_ids:
            return
        tel = get_telemetry()
        try:
            dst = self.cache.allocator.alloc(len(host_ids))
        except BlockOutOfMemory:
            self._release_demoted(req)
            req.fallback_reprefills += 1
            self.tier_fallback_reprefills += 1
            if tel.enabled:
                tel.registry.counter("serving.tier.fallback_reprefills").inc()
            if self.journal is not None:
                self.journal.record_tier(req, "device")
            return
        self.cache.promote(host_ids, dst)
        slot.blocks = dst
        slot.cache_len = req.demoted_rows
        slot.registered_blocks = req.demoted_registered
        req.demoted_blocks = None
        req.demoted_rows = 0
        req.demoted_registered = 0
        if req.emitted and slot.cache_len == len(req.to_feed) - 1:
            # Mid-decode victim: the only unwritten row is the last emitted
            # token's (the next decode dispatch writes it) — resume DECODING
            # with zero re-prefill dispatches.
            req.state = RequestState.DECODING
        # else: mid-prefill victim — admit() already set PREFILLING; the
        # next chunk continues from cache_len, no rows recomputed.
        self.tier_promotions += 1
        if tel.enabled:
            tel.registry.counter("serving.tier.promotions").inc()
        if self.journal is not None:
            self.journal.record_tier(req, "device")

    def _release_demoted(self, req: Request, dirty: bool = False) -> None:
        """Free a request's demoted host blocks (deadline expiry of a queued
        victim, promotion fallback, drain, or defensively at quarantine).
        ``dirty=True`` routes them through the host tier's synchronous
        zero-scrub — the host half of the two-tier scrub contract."""
        if req.demoted_blocks:
            if dirty:
                self.cache.host.mark_dirty(req.demoted_blocks)
            self.cache.host.free(req.demoted_blocks)
        req.demoted_blocks = None
        req.demoted_rows = 0
        req.demoted_registered = 0

    def _pressure_relief(self) -> None:
        """Proactive demote-before-shed: when the allocator's RAW free list
        (free_blocks minus reclaimable cache blocks) falls under the
        headroom watermark, demote up to ``tier_demote_batch`` cold cache
        chains to host DRAM in one batch — the D2H copies happen here, off
        the allocation path, so this tick's grants pop the free list instead
        of dropping cached prefixes on demand.  The admission waterfall is
        demote → evict-drop (host full) → preempt-migrate → preempt-free
        (fallback) → terminal OOM."""
        if (
            self._prefix is None
            or self.cache.host is None
            or self.serving.tier_demote_batch <= 0
        ):
            return
        alloc = self.cache.allocator
        raw_free = alloc.free_blocks - self._prefix.reclaimable_count
        if raw_free / max(alloc.capacity, 1) >= self._headroom_watermark_frac:
            return
        reclaim = min(self.serving.tier_demote_batch, self._prefix.reclaimable_count)
        if reclaim > 0:
            self._prefix.evict(reclaim)

    # -- deadline / quarantine enforcement -----------------------------------

    def _observe_requeue_waits(self, admitted: List[int]) -> None:
        """Land the re-queue wait samples of just-(re)admitted requests in
        ``serving.requeue_wait_ms`` — the preemption-wait blind spot that
        first-admission-only ``queue_wait_ms`` cannot see."""
        tel = get_telemetry()
        if not tel.enabled:
            return
        hist = tel.registry.histogram("serving.requeue_wait_ms")
        for idx in admitted:
            slot = self.sched.slots.get(idx)
            if slot is None:
                continue
            for sample in slot.request.pop_requeue_waits():
                hist.observe(sample)

    def _expire_deadlines(self, now: float) -> None:
        """Shed expired QUEUED requests (no prefill chunk is ever spent on a
        corpse) and cancel expired in-flight ones (blocks freed, slot
        returned to the pool)."""
        expired_queued = [req for req in self.sched.queue if req.expired(now)]
        for req in expired_queued:
            self.sched.cancel_queued(req)
            self._finish_expired(req, now)
        for idx in list(self.sched.slots):
            req = self.sched.slots[idx].request
            if req.expired(now):
                self.sched.finish(idx, now)  # frees the blocks
                self._finish_expired(req, now)

    def _finish_expired(self, req: Request, now: float) -> None:
        # A queued migration victim dies with KV still in the host tier —
        # release it or the tier leaks a dead request's blocks forever.
        self._release_demoted(req)
        req.state = RequestState.DONE
        req.finish_t = now
        self.deadline_expired_count += 1
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.deadline_expired").inc()
            if req.first_token_t is None:
                # Feed the violation into the TTFT histogram so the SLO
                # burn-rate gauges see it: without this, expired requests
                # never observe a latency and the burn rate only measures
                # the survivors.
                tel.registry.histogram("serving.ttft_ms").observe(
                    (now - req.arrival_t) * 1e3
                )
        self._complete(req, status="deadline_expired")

    def _quarantine(self, idx: int, now: float) -> None:
        """A slot's logits came back non-finite: complete its request with an
        error status and mark its pool blocks for a zero-scrub.  The scrub is
        load-bearing, not hygiene — the attention mask zeroes a hidden row's
        probability, but ``0 * NaN = NaN`` in ``probs @ v``, so a NaN row
        left in a recycled block would corrupt the block's next owner.
        (Finite garbage in recycled blocks is safe for exactly that reason,
        which is why normal frees never scrub.)

        With prefix sharing the scrub happens **on last release**: a block
        another request is still reading is never zeroed under it (the live
        reader's own finiteness check guards it — if the shared content were
        truly poisoned, that reader quarantines itself the same way).  The
        block is dropped from the prefix cache immediately, so no NEW reader
        can attach to it."""
        slot = self.sched.slots[idx]
        if self._prefix is not None:
            self._prefix.invalidate_blocks(slot.blocks)
        self.cache.allocator.mark_dirty(slot.blocks)
        req = self.sched.finish(idx, now)
        # Defensive: a slotted request holds no demoted blocks by invariant
        # (promotion clears them at admission), but if any exist they route
        # through the host tier's dirty scrub — the two-tier contract.
        self._release_demoted(req, dirty=True)
        # Unshared blocks just hit refcount 0 and are scrubbed right here;
        # the null block is always included (a poisoned request's padded
        # prefill rows scatter past its table into block 0).
        self._drain_scrubs(always_null=True)
        self.quarantined_count += 1
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.quarantined").inc()
            tel.event(
                "serving.quarantined",
                request=req.id,
                tag=req.tag,
                emitted=len(req.emitted),
                prompt_len=len(req.prompt),
            )
        self._complete(req, status="quarantined")

    def _scrub_blocks(self, blocks: List[int]) -> None:
        # The NULL block is always scrubbed too: a poisoned request's padded
        # prefill rows route PAST its block table into block 0 (the
        # scatter's explicit overflow target), so genuine NaN K/V — unlike
        # the logits-only injection — can land in the one block every slot's
        # gathered view shares.  Zero is always safe there: null-block rows
        # are only ever read at masked positions.
        idx = jnp.asarray(sorted(set(blocks) | {NULL_BLOCK}), jnp.int32)
        self.cache.pool = {
            n: leaf.at[:, idx].set(0) for n, leaf in self.cache.pool.items()
        }

    def _drain_scrubs(self, always_null: bool = False) -> None:
        """Scrub-on-last-release: zero the dirty blocks whose final reference
        dropped since the previous drain and hand them back to the free
        list.  They are not allocatable in between, so a dirty block can
        never be granted unscrubbed."""
        pending = self.cache.allocator.pop_pending_scrub()
        if pending or always_null:
            self._scrub_blocks(pending)
            self.cache.allocator.finish_scrub(pending)

    # -- prefix cache --------------------------------------------------------

    def _attach_prefix(self, idx: int) -> None:
        """On admission, reuse the cached prefix of the slot's feed: matched
        full blocks are refcount-shared into the slot's table wholesale, a
        reusable partial tail is claimed via copy-on-write, and
        ``cache_len`` starts past the shared rows — prefill (and TTFT)
        collapse to the unshared suffix.  At least one feed token is always
        left to process: the final chunk's logits ARE the next token."""
        if self._prefix is None:
            return
        slot = self.sched.slots.get(idx)
        if slot is None:
            return
        if slot.blocks:
            # A promoted migration victim already owns its table and
            # cache_len — the cached-prefix attach is for EMPTY slots only.
            return
        feed = slot.request.to_feed
        max_rows = len(feed) - 1
        if max_rows < self.serving.block_size:
            return
        blocks, rows, cow_src = self._prefix.lookup(feed, max_rows)
        reused = len(blocks)
        registered = len(blocks)  # leading blocks came FROM the cache
        if cow_src is not None:
            dst = None
            try:
                dst = self.cache.allocator.alloc(1)[0]
            except BlockOutOfMemory:
                pass  # best effort: prefill the tail instead of copying it
            if dst is not None:
                self._copy_block(cow_src, dst)
                blocks.append(dst)
                rows = max_rows
                reused += 1
                self.cow_copies += 1
            # Release the lookup's temporary reference on the source either
            # way (the copy is done, or we declined it).
            self.cache.allocator.free([cow_src])
        if not blocks:
            return
        slot.blocks = blocks
        slot.cache_len = rows
        slot.registered_blocks = registered
        self.prefix_hits += 1
        self.prefix_blocks_reused += reused
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.prefix_hits").inc()
            tel.registry.counter("serving.prefix_blocks_reused").inc(reused)
            if rows > registered * self.serving.block_size:
                tel.registry.counter("serving.prefix_cow_copies").inc()

    def _copy_block(self, src: int, dst: int) -> None:
        """Copy-on-write: duplicate one physical block across every pool
        leaf so the new owner can keep writing where the shared prefix
        stops.  Runs on the admission path, never inside the decode
        dispatch."""
        self.cache.pool = {
            n: leaf.at[:, dst].set(leaf[:, src])
            for n, leaf in self.cache.pool.items()
        }

    def _register_prefix_blocks(self, idx: int) -> None:
        """Publish the slot's freshly prefilled FULL blocks under their chain
        hashes.  Only blocks entirely below ``cache_len`` (real rows — the
        padded tail of a chunk never counts) are registered, and writes only
        move forward from ``cache_len``, so a registered block is never
        written again."""
        if self._prefix is None:
            return
        slot = self.sched.slots.get(idx)
        if slot is None:
            return
        bs = self.serving.block_size
        feed = slot.request.to_feed
        full = min(slot.cache_len, len(feed)) // bs
        if full <= slot.registered_blocks:
            return
        keys = PrefixCache.chain_keys(feed, bs, limit=full)
        for i in range(slot.registered_blocks, full):
            self._prefix.register(keys[i], slot.blocks[i])
        slot.registered_blocks = full

    # -- tick phases ---------------------------------------------------------

    def _bucket_width(self, blocks_needed: int) -> int:
        """Block-table width for the paged programs: the next power of two
        covering ``blocks_needed``, capped at the configured maximum.  Each
        width compiles once (jit caches per shape); gather traffic then
        scales with what live requests actually own instead of the
        worst-case table."""
        m = self.serving.resolved_max_blocks()
        width = 1
        while width < blocks_needed:
            width *= 2
        return min(width, m)

    def _note_bucket(self, kind: str, width: Optional[int]) -> bool:
        """Record a dispatch at this table width; returns True when the
        width is FRESH for ``kind`` — the per-width jit cache misses and the
        dispatch pays a trace+compile in the request's latency path.  The
        ``serving.bucket_compile`` event makes that TTFT spike attributable
        even with tracing disabled (the dense path keys on its one static
        width: its first dispatch is the one compile)."""
        key = width if width is not None else self.serving.resolved_max_blocks()
        if key in self._seen_widths[kind]:
            return False
        self._seen_widths[kind].add(key)
        tel = get_telemetry()
        if tel.enabled:
            # "dispatch" not "kind": event() reserves "kind" for the record
            # envelope, and a field named kind would shadow it in the JSONL.
            tel.event("serving.bucket_compile", dispatch=kind, width=key)
        return True

    def _table_row(self, blocks: List[int], width: Optional[int] = None) -> np.ndarray:
        m = width if width is not None else self.serving.resolved_max_blocks()
        row = np.zeros((m,), np.int32)
        row[: len(blocks)] = blocks
        return row

    def _prefill_tick(self, now: float) -> None:
        sched = self.sched
        candidates = [
            (slot.admit_seq, idx)
            for idx, slot in sched.slots.items()
            if slot.request.state == RequestState.PREFILLING
        ]
        if not candidates:
            return
        _, idx = min(candidates)
        slot = sched.slots[idx]
        req = slot.request
        feed = req.to_feed
        start = slot.cache_len
        chunk_len = self.serving.prefill_chunk
        n_real = min(chunk_len, len(feed) - start)
        if not sched.grow_to(idx, start + n_real):
            return  # the slot itself was preempted to find blocks
        chunk = np.zeros((1, chunk_len), np.int32)
        chunk[0, :n_real] = feed[start : start + n_real]
        width = None
        if self.decode_path == "paged":
            # Bucket the table to the chunk's padded write extent — the
            # gather reads the blocks this prefill can actually touch.
            width = self._bucket_width(
                blocks_for_tokens(start + chunk_len, self.serving.block_size)
            )
        fresh = self._note_bucket("prefill", width)
        next_tok, ok, self.cache.pool = self._prefill_fn(
            self.params,
            self.cache.pool,
            self._table_row(slot.blocks, width),
            np.int32(start),
            chunk,
            np.int32(n_real),
        )
        self.prefill_dispatches += 1
        req.prefill_dispatches += 1  # per-request: the zero-re-prefill oracle
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.prefill_dispatches").inc()
        slot.cache_len = start + n_real
        poisoned = not bool(ok)  # host sync point: the dispatch is done here
        if self.tracer is not None:
            self.tracer.on_prefill(
                req, idx, time.monotonic(),
                padded_rows=chunk_len - n_real, width=width, fresh=fresh,
            )
        if poisoned:
            self._quarantine(idx, time.monotonic())
            return
        self._register_prefix_blocks(idx)
        if slot.cache_len == len(feed):
            # Final chunk: its last real logits row IS the next token — the
            # first generated token of a fresh request (TTFT lands here) or
            # the resume token of a re-prefilled one.
            self._emit(idx, int(next_tok), time.monotonic())
            if idx in sched.slots:
                sched.slots[idx].request.state = RequestState.DECODING

    def _decode_tick(self, now: float) -> None:
        sched = self.sched
        decoding = sorted(
            (idx for idx, slot in sched.slots.items()
             if slot.request.state == RequestState.DECODING),
            key=lambda i: sched.slots[i].admit_seq,
        )
        # Speculative drafts come BEFORE block growth: a spec engine's every
        # decode tick is a k+1-window verify dispatch whose write extent is
        # the full window for EVERY live slot (the program scatters all
        # rows), so growth must budget window rows whether or not a given
        # slot has drafts of its own.  Draft-less slots (and draft-less
        # ticks) ride the same program with ``draft_len = 0`` — the window
        # is FIXED at k+1 whenever speculation is on, so each bucket has
        # exactly one decode program shape and a rare draft-less tick can
        # never trigger a fresh single-token compile mid-serve.  A draft
        # never exceeds remaining-1 — the window position after the last
        # accepted draft must still be emittable.
        k = self.spec_tokens
        drafts: Dict[int, List[int]] = {}
        if k > 0:
            for idx in decoding:
                slot = sched.slots.get(idx)
                if slot is None or slot.request.state != RequestState.DECODING:
                    continue
                req = slot.request
                want = min(k, req.remaining - 1)
                if want <= 0:
                    continue
                d = self._drafter.propose(req.to_feed, want)
                if d:
                    drafts[idx] = [int(t) for t in d[:want]]
        window = k + 1 if k > 0 else 1
        # Grow oldest-first so older requests steal blocks from younger ones
        # (matching the LIFO victim policy), then re-collect the survivors.
        for idx in decoding:
            if idx in sched.slots and sched.slots[idx].request.state == RequestState.DECODING:
                sched.grow_to(idx, sched.slots[idx].cache_len + window)
        live = [
            idx for idx in decoding
            if idx in sched.slots and sched.slots[idx].request.state == RequestState.DECODING
        ]
        if not live:
            return
        s = self.serving.max_slots
        if self.decode_path == "paged":
            # Bucket the tables to the widest live slot: gather traffic (and
            # attention width) scale with the blocks requests actually own.
            m = self._bucket_width(max(len(sched.slots[idx].blocks) for idx in live))
            gathered = sum(len(sched.slots[idx].blocks) for idx in live)
        else:
            m = self.serving.resolved_max_blocks()
            # The dense program gathers every slot's full worst-case view,
            # live or not — exactly the tax the paged path removes.
            gathered = s * m
        tables = np.zeros((s, m), np.int32)
        lengths = np.zeros((s,), np.int32)
        tokens = np.zeros((s, window), np.int32)
        draft_len = np.zeros((s,), np.int32)
        for idx in live:
            slot = sched.slots[idx]
            tables[idx] = self._table_row(slot.blocks, m)
            lengths[idx] = slot.cache_len
            tokens[idx, 0] = slot.request.emitted[-1]
            d = drafts.get(idx)
            if d:
                tokens[idx, 1 : 1 + len(d)] = d
                draft_len[idx] = len(d)
        self.decode_gather_bytes += gathered * self._block_bytes
        fresh = self._note_bucket("decode_spec" if window > 1 else "decode", m)
        dispatch_t0 = time.monotonic()
        if window > 1:
            args = [self.params, self.cache.pool, tables, lengths, tokens, draft_len]
        else:
            args = [self.params, self.cache.pool, tables, lengths, tokens[:, 0]]
        if self._poison_ordinal is not None:
            # Armed: the program was traced with the poison lane.  NaN rides
            # into exactly one slot's logits on that request's first decode
            # dispatch; every other lane multiplies by 1.0 (vmap lanes are
            # independent, so their tokens are bit-identical to unarmed).
            poison = np.ones((s,), np.float32)
            for idx in live:
                req = sched.slots[idx].request
                if getattr(req, "_poison_pending", False):
                    poison[idx] = np.nan
                    req._poison_pending = False  # fires once
            args.append(poison)
        if window > 1:
            # The verify program REPLACES the single-token one this tick —
            # still exactly one fused decode dispatch per bucket.
            t_rows, m_counts, ok_flags, self.cache.pool = self._decode_spec_fn(*args)
            out = np.asarray(t_rows)
            accepts = np.asarray(m_counts)
        else:
            next_tokens, ok_flags, self.cache.pool = self._decode_fn(*args)
            out = np.asarray(next_tokens)[:, None]
            accepts = np.zeros((s,), np.int32)
        self.decode_dispatches += 1
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.decode_dispatches").inc()
            tel.registry.counter("serving.decode_gather_bytes").inc(
                gathered * self._block_bytes
            )
            tel.registry.gauge("serving.decode_bucket_width").set(m)
        oks = np.asarray(ok_flags)
        emit_t = time.monotonic()
        if self.tracer is not None:
            # emit_t is PAST the np.asarray sync point, so the interval
            # covers the real device work despite async dispatch.
            self.tracer.on_decode(
                [(sched.slots[idx].request, idx) for idx in live],
                emit_t, co_batch=len(live), width=m, fresh=fresh,
                dispatch_ms=(emit_t - dispatch_t0) * 1e3,
                phase="verify" if window > 1 else "decode",
            )
        # rounds counts verify DISPATCHES (with >= 1 healthy lane);
        # proposed/accepted are per-slot sums over the healthy lanes.
        spec_rounds = spec_proposed = spec_accepted = 0
        for idx in live:
            slot = sched.slots[idx]
            req = slot.request
            if window > 1:
                # Accept bookkeeping: the emitted chunk is t[:count] where
                # count = accepted drafts + the correction/bonus row, capped
                # at remaining (count == remaining finishes the request on
                # its exact last token).  cache_len advances by count — the
                # rewind; rows past it are stale and re-written before read.
                count = min(int(accepts[idx]) + 1, req.remaining)
            else:
                count = 1
            slot.cache_len += count
            if not bool(oks[idx]):
                # Quarantine instead of emitting the garbage argmax; the
                # other slots' emissions proceed untouched.
                self._quarantine(idx, emit_t)
                continue
            if window > 1:
                spec_rounds = 1
                spec_proposed += int(draft_len[idx])
                spec_accepted += int(accepts[idx])
            self.decode_emitted_tokens += count
            self.decode_slot_ticks += 1
            for j in range(count):
                self._emit(idx, int(out[idx, j]), emit_t)
        if spec_rounds:
            self.spec_rounds += spec_rounds
            self.spec_proposed += spec_proposed
            self.spec_accepted += spec_accepted
            if tel.enabled:
                tel.registry.counter("serving.spec.rounds").inc(spec_rounds)
                if spec_proposed:
                    tel.registry.counter("serving.spec.proposed").inc(spec_proposed)
                if spec_accepted:
                    tel.registry.counter("serving.spec.accepted").inc(spec_accepted)

    # -- completion / metrics ------------------------------------------------

    def _emit(self, idx: int, token: int, now: float) -> None:
        slot = self.sched.slots[idx]
        req = slot.request
        req.emitted.append(token)
        req.note_token(now)
        tel = get_telemetry()
        if tel.enabled:
            tel.registry.counter("serving.tokens").inc()
            if len(req.emitted) == 1 and req.arrival_t is not None:
                tel.registry.histogram("serving.ttft_ms").observe(
                    (now - req.arrival_t) * 1e3
                )
            elif req.inter_token_ms:
                tel.registry.histogram("serving.inter_token_ms").observe(
                    req.inter_token_ms[-1]
                )
        if req.remaining == 0:
            self.sched.finish(idx, now)
            self._complete(req)

    def _complete(self, req: Request, status: str = "ok") -> None:
        ttft_ms = None
        if req.first_token_t is not None and req.arrival_t is not None:
            ttft_ms = (req.first_token_t - req.arrival_t) * 1e3
        queue_wait_ms = (
            (req.admit_t - req.arrival_t) * 1e3
            if req.admit_t is not None and req.arrival_t is not None
            else 0.0
        )
        mean_itl = (
            sum(req.inter_token_ms) / len(req.inter_token_ms)
            if req.inter_token_ms
            else None
        )
        tps = None
        if (
            req.finish_t is not None
            and req.first_token_t is not None
            and req.finish_t > req.first_token_t
            and len(req.emitted) > 1
        ):
            tps = (len(req.emitted) - 1) / (req.finish_t - req.first_token_t)
        rec = CompletedRequest(
            id=req.id,
            tokens=req.output,
            prompt_len=len(req.prompt),
            new_tokens=len(req.emitted),
            queue_wait_ms=queue_wait_ms,
            ttft_ms=ttft_ms,
            mean_inter_token_ms=mean_itl,
            tokens_per_s=tps,
            preemptions=req.preemptions,
            inter_token_ms=list(req.inter_token_ms),
            status=status,
            tag=req.tag,
            migrations=req.migrations,
            fallback_reprefills=req.fallback_reprefills,
            prefill_dispatches=req.prefill_dispatches,
        )
        self._finished.append(rec)
        if self.journal is not None:
            self.journal.record_done(req.id, status)
        tel = get_telemetry()
        if tel.enabled:
            reg = tel.registry
            reg.counter("serving.completed").inc()
            reg.histogram("serving.queue_wait_ms").observe(queue_wait_ms)
            if tps is not None:
                reg.histogram("serving.tokens_per_s").observe(tps)
            tel.event(
                "serving.request_complete",
                request=req.id,
                tag=req.tag,
                status=status,
                prompt_len=len(req.prompt),
                new_tokens=len(req.emitted),
                ttft_ms=round(ttft_ms, 3) if ttft_ms is not None else None,
                queue_wait_ms=round(queue_wait_ms, 3),
                preemptions=req.preemptions,
            )
        if self.tracer is not None:
            self.tracer.on_terminal(req, status)

    def _publish_gauges(self) -> None:
        tel = get_telemetry()
        if not tel.enabled:
            return
        reg = tel.registry
        alloc = self.cache.allocator
        reg.gauge("serving.active_slots").set(self.sched.active)
        reg.gauge("serving.queue_depth").set(self.sched.pending)
        reg.gauge("serving.blocks_used").set(alloc.used_blocks)
        reg.gauge("serving.block_occupancy").set(round(alloc.occupancy, 4))
        reg.gauge("serving.prefix_cache_blocks").set(
            len(self._prefix) if self._prefix is not None else 0
        )
        reg.gauge("serving.spec.acceptance_rate").set(
            round(self.spec_accepted / max(self.spec_proposed, 1), 4)
        )
        # Per slot-lane, not per fused dispatch: continuous batching already
        # lands co_batch tokens per dispatch; this gauge isolates the
        # SPECULATIVE gain (1.0 == plain greedy, >1 == accepted drafts).
        reg.gauge("serving.tokens_per_dispatch").set(
            round(self.decode_emitted_tokens / max(self.decode_slot_ticks, 1), 4)
        )
        # HBM ledger + headroom: refresh the prefix-cache resident bytes
        # (a subset of the pool reservation) and publish the serving
        # headroom — free pool bytes, further clamped by measured free HBM
        # when the backend reports stats (absent on CPU builds, where the
        # pool bound is the whole truth).
        from ..telemetry.memledger import get_memory_ledger

        ledger = get_memory_ledger()
        prefix_blocks = len(self._prefix) if self._prefix is not None else 0
        ledger.update_bytes(
            "serving.prefix_cache",
            prefix_blocks * self._block_bytes,
            token=self._memledger_tokens[1],
        )
        headroom = alloc.free_blocks * self._block_bytes
        hbm_free = ledger.min_device_headroom()
        if hbm_free is not None:
            headroom = min(headroom, hbm_free)
        reg.gauge("serving.headroom_bytes").set(headroom)
        # Low-headroom watermark (the tiering control signal): one event per
        # pressure EPISODE, with hysteresis — the event re-arms only after
        # free capacity recovers above the re-arm line (1.5x the watermark,
        # capped at 1.0), so a pool oscillating right at the watermark
        # cannot spam the ring, while each genuine dip-recover-dip cycle
        # under tiering emits its own event instead of being silently
        # swallowed after the first.
        free_frac = alloc.free_blocks / max(alloc.capacity, 1)
        if free_frac < self._headroom_watermark_frac:
            if not self._low_headroom:
                self._low_headroom = True
                tel.event(
                    "memory.low_headroom",
                    source="serving",
                    headroom_bytes=headroom,
                    free_blocks=alloc.free_blocks,
                    capacity=alloc.capacity,
                    watermark_frac=self._headroom_watermark_frac,
                )
        elif self._low_headroom and free_frac >= self._headroom_rearm_frac:
            self._low_headroom = False
        # KV host tier: occupancy gauges plus the prefix cache's own
        # demote/promote churn (which happens inside allocator eviction,
        # out of counter reach) folded into the tier counters as deltas.
        host = self.cache.host
        if host is not None:
            reg.gauge("serving.tier.host_bytes").set(host.used_bytes())
            reg.gauge("serving.tier.host_occupancy").set(round(host.occupancy, 4))
            if self._prefix is not None:
                d = self._prefix.host_demotions - self._prefix_demotions_published
                if d > 0:
                    reg.counter("serving.tier.demotions").inc(d)
                    reg.counter("serving.tier.demoted_blocks").inc(d)
                self._prefix_demotions_published = self._prefix.host_demotions
                p = self._prefix.host_promotions - self._prefix_promotions_published
                if p > 0:
                    reg.counter("serving.tier.promotions").inc(p)
                self._prefix_promotions_published = self._prefix.host_promotions
        # Publish only preemptions since the last publish: a registry.reset()
        # (e.g. scoping a measurement window) must not be re-inflated with
        # engine-lifetime history.
        new_preempted = self.sched.preempted_count - self._preempted_published
        if new_preempted > 0:
            reg.counter("serving.preempted").inc(new_preempted)
        self._preempted_published = self.sched.preempted_count

    # -- introspection -------------------------------------------------------

    def debug_requests(self) -> List[dict]:
        """Live request snapshot for the ``/debug/requests`` endpoint: every
        queued and slotted request with its state, age, and (when tracing is
        on) its phase-so-far decomposition.  Host-side reads only — safe to
        call from the metrics server thread between ticks."""
        now = time.monotonic()
        out = []
        seen = set()
        for idx, slot in sorted(self.sched.slots.items()):
            req = slot.request
            seen.add(req.id)
            out.append(self._debug_request(req, now, slot=idx))
        for req in self.sched.queue:
            if req.id not in seen:
                out.append(self._debug_request(req, now, slot=None))
        return out

    def _debug_request(self, req: Request, now: float, slot: Optional[int]) -> dict:
        rec = {
            "id": req.id,
            "tag": req.tag,
            "state": req.state.name,
            "slot": slot,
            "age_ms": round((now - req.arrival_t) * 1e3, 3),
            "prompt_len": len(req.prompt),
            "emitted": len(req.emitted),
            "max_new": req.max_new_tokens,
            "preemptions": req.preemptions,
        }
        if self.tracer is not None:
            rec["trace"] = self.tracer.snapshot_request(req.id, now)
        return rec

    def debug_blocks(self) -> dict:
        """Pool snapshot for ``/debug/blocks``: occupancy, per-block
        refcounts (shared prefix blocks show >1), and the prefix-cache
        chains with their reclaimability."""
        alloc = self.cache.allocator
        refcounts = {
            str(b): n for b, n in sorted(alloc._ref.items()) if n > 0
        }
        out = {
            "capacity": alloc.capacity,
            "free": alloc.free_blocks,
            "used": alloc.used_blocks,
            "occupancy": round(alloc.occupancy, 4),
            "pending_scrub": sorted(alloc._pending_scrub),
            "refcounts": refcounts,
            "slots": {
                str(idx): {
                    "request": slot.request.id,
                    "blocks": list(slot.blocks),
                    "cache_len": slot.cache_len,
                }
                for idx, slot in sorted(self.sched.slots.items())
            },
        }
        if self._prefix is not None:
            out["prefix_cache"] = {
                "blocks": len(self._prefix),
                "reclaimable": self._prefix.reclaimable_count,
                # LRU order, oldest first: block plus its live refcount so a
                # stuck chain (refcount pinned > 1) is visible at a glance.
                "chain": [
                    {"block": b, "refcount": alloc.refcount(b)}
                    for b in self._prefix._entries.values()
                ],
            }
            if self.cache.host is not None:
                out["prefix_cache"]["host_entries"] = self._prefix.host_count
        if self.cache.host is not None:
            host = self.cache.host
            out["host_tier"] = {
                "capacity": host.capacity,
                "free": host.free_blocks,
                "used": host.used_blocks,
                "occupancy": round(host.occupancy, 4),
                # Which live requests currently own host-resident blocks
                # (demoted mid-flight, awaiting re-admission).
                "demoted_requests": {
                    str(req.id): len(req.demoted_blocks or ())
                    for req in self.sched.queue
                    if req.demoted_blocks
                },
            }
        return out

    def export_chrome_trace(self, path: str) -> str:
        """Dump every traced request (completed ring + live) as a
        Chrome/Perfetto trace; see ``serving/tracing.py``."""
        from .tracing import export_chrome_trace

        if self.tracer is None:
            raise RuntimeError("tracing is disabled on this engine")
        return export_chrome_trace(path, self.tracer.traces())

    def stats(self) -> dict:
        alloc = self.cache.allocator
        return {
            "ticks": self.ticks,
            "decode_dispatches": self.decode_dispatches,
            "prefill_dispatches": self.prefill_dispatches,
            "active_slots": self.sched.active,
            "queue_depth": self.sched.pending,
            "blocks_used": alloc.used_blocks,
            "block_occupancy": round(alloc.occupancy, 4),
            "completed": len(self._finished),
            "preempted": self.sched.preempted_count,
            "shed": self.shed_count,
            "deadline_expired": self.deadline_expired_count,
            "quarantined": self.quarantined_count,
            "pool_bytes": self.cache.pool_bytes(),
            "free_pool_bytes": alloc.free_blocks * self._block_bytes,
            "decode_path": self.decode_path,
            "decode_gather_bytes": self.decode_gather_bytes,
            "prefix_hits": self.prefix_hits,
            "prefix_blocks_reused": self.prefix_blocks_reused,
            "prefix_cow_copies": self.cow_copies,
            "prefix_cached_blocks": len(self._prefix) if self._prefix else 0,
            "decode_bucket_widths": sorted(self._seen_widths["decode"]),
            "spec": {
                "window": self.spec_tokens,
                "rounds": self.spec_rounds,
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "acceptance_rate": round(
                    self.spec_accepted / max(self.spec_proposed, 1), 4
                ),
                # Per slot-lane: mean tokens a slot advances per fused decode
                # dispatch it rode (1.0 == plain greedy; the speculative gain
                # net of batch width).
                "tokens_per_dispatch": round(
                    self.decode_emitted_tokens / max(self.decode_slot_ticks, 1), 4
                ),
            },
            "tiering": (
                {
                    "host_blocks": self.cache.host.capacity,
                    "host_used": self.cache.host.used_blocks,
                    "host_free": self.cache.host.free_blocks,
                    "host_occupancy": round(self.cache.host.occupancy, 4),
                    "host_bytes": self.cache.host.used_bytes(),
                    "demotions": self.tier_demotions
                    + (self._prefix.host_demotions if self._prefix else 0),
                    "promotions": self.tier_promotions
                    + (self._prefix.host_promotions if self._prefix else 0),
                    "demoted_blocks": self.tier_demoted_blocks
                    + (self._prefix.host_demotions if self._prefix else 0),
                    "fallback_reprefills": self.tier_fallback_reprefills,
                    "prefix_host_entries": (
                        self._prefix.host_count if self._prefix else 0
                    ),
                    "prefix_host_drops": (
                        self._prefix.host_drops if self._prefix else 0
                    ),
                }
                if self.cache.host is not None
                else None
            ),
            "trace_blame": (
                dict(self.tracer.blame_counts) if self.tracer is not None else None
            ),
        }
