"""Serving-trace smoke: blame decomposition + export + live /debug on CPU.

Run via ``make serving-trace-smoke`` (or ``python -m
accelerate_tpu.serving.trace_smoke``).  Drives the per-request trace
subsystem (``serving/tracing.py``) end to end:

- **blame names the injected phase** — one request is held in the queue
  (injected submit→step delay: ``queue_wait`` must dominate), another is
  forcibly preempted mid-decode and held requeued (``requeued_wait`` must
  dominate); the blame decomposer must name each correctly, and the
  ``serving.trace.blame.*`` counters must land in the registry;
- **conservation** — every completed request's phase durations sum to its
  submission→terminal wall time, ``unattributed_ms`` bounded;
- **Chrome export round-trips** — the exported trace re-parses through
  ``telemetry/timeline.py`` (the same parser that reads ``jax.profiler``
  dumps) with the slot/request tracks intact;
- **live inspection** — a real HTTP scrape of the metrics server mid-flight:
  ``/healthz`` 200, ``/debug/requests`` shows the in-flight request with its
  phase-so-far, ``/debug/blocks`` shows pool occupancy, unknown paths 404;
- **offline postmortem** — ``telemetry.report`` renders the serving-traces
  block from the JSONL alone;
- **overhead bounded** — steady-state decode throughput with tracing on
  stays close to tracing off (generous 15% smoke bound against CI timing
  noise; the 3% acceptance bound is enforced continuously by the perf-gate
  serving row, which runs with tracing default-ON and must hold its
  committed paged-vs-dense floor).

Exit code 0 only when every assertion holds.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("ACCELERATE_TPU_COMPILE_CACHE", "")
    os.environ.setdefault("ACCELERATE_TPU_SENTINEL_PROFILE", "0")
    os.environ.pop("ACCELERATE_TPU_SERVING_TRACE", None)  # default-on path

    import numpy as np

    import jax
    import jax.numpy as jnp

    from accelerate_tpu import telemetry
    from accelerate_tpu.models import gpt2
    from accelerate_tpu.serving import ServingConfig, ServingEngine
    from accelerate_tpu.serving.tracing import load_serving_traces, summarize_traces
    from accelerate_tpu.telemetry.export import MetricsExporter
    from accelerate_tpu.telemetry.timeline import build_timeline, load_trace_events

    run_dir = tempfile.mkdtemp(prefix="atpu_trace_smoke_")
    tel = telemetry.enable(dir=run_dir)
    exporter = MetricsExporter()
    exporter.start(port=0)

    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)

    def build(trace=None):
        return ServingEngine(
            gpt2.apply_cached, gpt2.init_cache, params, cfg,
            serving=ServingConfig(
                block_size=4, num_blocks=32, max_slots=2, max_blocks_per_seq=8,
                prefill_chunk=8, trace=trace, trace_dir=run_dir,
            ),
        )

    def prompt(n):
        return list(rng.integers(0, cfg.vocab_size, size=n))

    engine = build()
    assert engine.tracer is not None, "tracing default-on did not arm the tracer"

    # Warm every bucket width first so the scenario requests below pay no
    # compile_in_path — their blame must be the INJECTED phase, nothing else.
    # A short-prompt pass covers table widths 1–2, the concurrent pair covers
    # widths 4–8, and a long prompt reaches prefill width 8 (a preempted
    # request re-prefilling its emitted tokens buckets that wide); together
    # that is every width the scenario requests can dispatch at.
    engine.submit(prompt(3), 6, tag="warmup-short")
    engine.run(max_ticks=500)
    for i in range(2):
        engine.submit(prompt(12), 18, tag=f"warmup{i}")
    engine.submit(prompt(20), 4, tag="warmup-long")
    engine.run(max_ticks=500)

    # Scenario 1 — queue delay: submit, then hold the engine for 120 ms
    # before the first tick.  queue_wait must dominate the request.
    # max_new=12 keeps the request in a slot across the /debug scrape below
    # (a prefill-completing tick also decodes once, so small budgets finish
    # within the first few ticks) while keeping the decode window short
    # enough that the injected delay clears the blame floor.
    rid_queue = engine.submit(prompt(6), 12, tag="slow-queue")
    time.sleep(0.12)
    for _ in range(3):
        engine.step()

    # Mid-flight: scrape the live endpoints while the request is in a slot.
    port = exporter.port
    health = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=10
    )
    assert health.status == 200 and health.read() == b"ok\n", "/healthz broken"
    dbg = json.loads(
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/requests", timeout=10
        ).read()
    )
    inflight = [r for eng_reqs in dbg["engines"] for r in eng_reqs]
    mine = [r for r in inflight if r["tag"] == "slow-queue"]
    assert mine, f"/debug/requests lost the in-flight request: {dbg}"
    assert mine[0]["state"] in ("PREFILLING", "DECODING"), mine
    assert mine[0]["trace"]["phase_ms"].get("queue_wait", 0.0) >= 60.0, mine
    blocks = json.loads(
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/blocks", timeout=10
        ).read()
    )
    pool = blocks["engines"][0]
    assert pool["used"] > 0 and 0.0 < pool["occupancy"] <= 1.0, pool
    try:
        urllib.request.urlopen(f"http://127.0.0.1:{port}/other", timeout=10)
        raise AssertionError("unknown path did not 404")
    except urllib.error.HTTPError as err:
        assert err.code == 404, err.code
    print("# trace smoke: /healthz + /debug/requests + /debug/blocks live, 404 intact")

    # Scenario 2 — injected preemption: evict the decoding request and hold
    # it requeued for 120 ms.  requeued_wait must dominate ITS timeline.
    rid_preempt = engine.submit(prompt(6), 12, tag="slow-preempt")
    for _ in range(6):
        engine.step()
    victim = [
        idx for idx, slot in engine.sched.slots.items()
        if slot.request.id == rid_preempt
    ]
    assert victim, "preemption target never reached a slot"
    engine.sched.preempt_slot(victim[0])
    time.sleep(0.12)
    engine.run(max_ticks=1000)

    by_rid = {t.rid: t for t in engine.tracer.completed}
    t_queue, t_preempt = by_rid[rid_queue], by_rid[rid_preempt]
    assert t_queue.blame == "queue_wait", (
        f"queue-delay request blamed {t_queue.blame!r}: {t_queue.phase_ms()}"
    )
    assert t_preempt.blame == "requeued_wait", (
        f"preempted request blamed {t_preempt.blame!r}: {t_preempt.phase_ms()}"
    )
    assert any(iv.phase == "preempted" for iv in t_preempt.intervals)
    for t in engine.tracer.completed:
        window, attributed = t.window_ms(), sum(t.phase_ms().values())
        resid = t.unattributed_ms()
        assert abs(window - attributed - resid) < 1e-6, (window, attributed, resid)
        assert 0.0 <= resid <= max(5.0, 0.05 * window), (
            f"rid {t.rid}: unattributed {resid:.2f} ms of {window:.2f} ms window"
        )
    assert tel.registry.counter("serving.trace.blame.queue_wait").value >= 1
    assert tel.registry.counter("serving.trace.blame.requeued_wait").value >= 1
    print("# trace smoke: blame named the injected phases; conservation holds")

    # Chrome export → back through the jax.profiler trace parser.
    trace_path = os.path.join(run_dir, "serving.trace.json")
    engine.export_chrome_trace(trace_path)
    tl = build_timeline(load_trace_events(trace_path), source=trace_path)
    assert tl.host_events and not tl.events, "serving events misread as device ops"
    tracks = set(tl.tracks().values())
    assert any("serving engine slots/slot" in t for t in tracks), tracks
    assert any("serving requests/req" in t for t in tracks), tracks
    phases_seen = {ev.name for ev in tl.host_events}
    assert {"queue_wait", "decode", "preempted", "requeued_wait"} <= phases_seen, phases_seen
    print(f"# trace smoke: Chrome export round-tripped ({len(tl.host_events)} events, {len(tracks)} tracks)")

    # Offline postmortem from the JSONL alone.
    summary = summarize_traces(load_serving_traces(run_dir))
    assert summary["requests"] >= 3
    assert summary["by_blame"].get("queue_wait", 0) >= 1
    assert summary["by_blame"].get("requeued_wait", 0) >= 1
    from accelerate_tpu.serving.tracing import format_trace_block

    block = "\n".join(format_trace_block(summary))
    assert "serving traces (per-request blame)" in block
    print("# trace smoke: offline report block renders from JSONL")
    print(block)

    # Overhead: steady-state decode ticks, tracing on vs off.  A top-up loop
    # keeps both slots busy with an identical deterministic request stream —
    # the measured window exercises the tracer's full request lifecycle
    # (submit, admit, decode coalescing, terminal write), not just the
    # per-tick hooks.  Measurement is PAIRED: both arms are warmed, then
    # alternate 25-tick chunks for 20 rounds and the per-round rate ratio's
    # MEDIAN is the verdict — ambient load waves hit both arms of a round
    # alike, and the median sheds GC/IO spikes that best-of designs let
    # decide the outcome.  The bound is deliberately loose: a tiny-model CPU
    # tick is ~0.3 ms of host-bound Python, so the tracer's ~tens of µs per
    # tick worst-cases near 15% HERE while being <1% of a real device-bound
    # decode tick; 0.75 still fails on pathological regressions (per-tick
    # sync flushes, O(n) interval scans).
    nonce = iter(range(100_000))

    def make_arm(trace):
        eng = build(trace=trace)

        def chunk(n):
            while len(eng.sched.queue) < 2:
                eng.submit(prompt(10), 20, tag=f"perf{next(nonce)}")
            n0 = eng.decode_dispatches
            t0 = time.perf_counter()
            for _ in range(n):
                if len(eng.sched.queue) < 2:
                    eng.submit(prompt(10), 20, tag=f"perf{next(nonce)}")
                eng.step()
            return (eng.decode_dispatches - n0) / (time.perf_counter() - t0)

        for _ in range(6):  # warm: compile every width, reach steady state
            chunk(25)
        return chunk

    arm_on, arm_off = make_arm(True), make_arm(False)
    ratios = sorted(arm_on(25) / arm_off(25) for _ in range(20))
    ratio = ratios[len(ratios) // 2]
    print(
        f"# trace smoke: paired decode throughput ratio on/off median {ratio:.3f} "
        f"(spread {ratios[0]:.3f}..{ratios[-1]:.3f})"
    )
    assert ratio >= 0.75, (
        f"tracing overhead too high: on/off throughput ratio {ratio:.3f} < 0.75 "
        "(see comment — this CPU probe magnifies host-side cost ~30x vs a "
        "device-bound tick)"
    )

    exporter.stop(final_snapshot=False)
    telemetry.disable()
    print("serving trace smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
