"""Production serving layer: continuous batching over a paged KV cache.

Three pieces (see ``docs/usage_guides/serving.md``):

- **blocks** — a fixed-size-block KV pool with a free-list allocator and
  per-request block tables, so heterogeneous sequence lengths stop tiling
  HBM to the maximum context (``blocks.py``);
- **scheduler** — the continuous-batching request scheduler: admission
  queue, slot map, LIFO preemption under block pressure
  (``scheduler.py``);
- **engine** — the serving engine itself: one fused jitted decode step
  over the in-flight batch per tick plus bounded chunked prefill, with
  per-request SLO metrics (TTFT, inter-token latency, queue wait)
  published through the telemetry registry (``engine.py``).

Entry point: :meth:`accelerate_tpu.Accelerator.prepare_serving`, or
construct :class:`ServingEngine` directly from a model family's
``apply_cached``/``init_cache`` pair.

Robustness layer (overload shedding, request deadlines, poison-request
quarantine, crash-recovery journal): ``engine.py`` + ``journal.py``, proven
under fire by the seeded serving chaos campaign (``serving/chaos.py``,
``make serving-chaos-smoke``).

KV survivability layer (``host_blocks > 0``): a host-DRAM second tier for
the paged pool (``blocks.HostBlockPool``) — preemption demotes the
victim's blocks and re-admission promotes them back (zero re-prefill
dispatches), cold prefix chains spill on LRU eviction, and admission
demotes proactively under the memory-headroom watermark; proven by the
tiered chaos campaign (``make tiering-chaos-smoke``) and the perf-gate
tiering row. See ``docs/usage_guides/serving.md`` ("KV tiering & memory
pressure").

Observability layer (per-request phase traces, tail-latency blame
decomposition, Chrome-trace export, live ``/debug`` endpoints):
``tracing.py`` + the metrics HTTP server, walked through in
``docs/usage_guides/serving.md`` ("Tracing a slow request") and specified
in ``docs/package_reference/serving_tracing.md``.
"""

from .blocks import (
    BlockAllocator,
    BlockOutOfMemory,
    HostBlockPool,
    PagedKVCache,
    PrefixCache,
)
from .drafter import DraftModelDrafter, NgramDrafter
from .engine import (
    AdmissionRejected,
    CompletedRequest,
    ServingConfig,
    ServingEngine,
)
from .journal import JournalError, ServingJournal
from .scheduler import Request, RequestState, Scheduler
from .tracing import (
    RequestTrace,
    ServingTracer,
    export_chrome_trace,
    load_serving_traces,
    stitch_traces,
    summarize_traces,
)

__all__ = [
    "AdmissionRejected",
    "BlockAllocator",
    "BlockOutOfMemory",
    "HostBlockPool",
    "PagedKVCache",
    "PrefixCache",
    "CompletedRequest",
    "DraftModelDrafter",
    "JournalError",
    "NgramDrafter",
    "Request",
    "RequestState",
    "RequestTrace",
    "Scheduler",
    "ServingConfig",
    "ServingEngine",
    "ServingJournal",
    "ServingTracer",
    "export_chrome_trace",
    "load_serving_traces",
    "stitch_traces",
    "summarize_traces",
]
