"""Crash-recovery write-ahead journal for the serving engine.

The PR 12 graceful drain published a requeue journal — but only as a
telemetry event at drain time, so it existed exactly when the process died
*politely*.  A SIGKILL (OOM killer, node loss, ``kill -9``) lost every
in-flight request.  This module promotes that journal to a **write-ahead
journal on disk**: every admission and every terminal transition (complete /
deadline-shed / quarantine) rewrites one JSON file via the checkpoint
manifest's write-temp + ``os.replace`` pattern, so the file on disk is
always a complete, parseable snapshot — a kill mid-write leaves the
*previous* complete journal, never a torn one.

Recovery contract (:meth:`ServingEngine.recover_from_journal`): a successor
engine resubmits every journaled request with no terminal record as
``prompt + emitted`` with ``max_new = remaining``.  Greedy decode is
deterministic and the re-prefill path is bit-exact (the PR 12 drain oracle),
so the successor finishes every non-shed request **token-identically** to an
uninterrupted run — whether the predecessor died by SIGTERM (drain persisted
its emitted-token progress) or SIGKILL (the request replays from the
prompt; same tokens, more compute).

What is journaled when:

- **admission** (``record_admit``) — prompt, budget, tag, deadlines.  The
  write happens before ``submit`` returns the id, so an acknowledged
  request is always recoverable.
- **terminal** (``record_done``) — status ``ok`` / ``deadline_expired`` /
  ``quarantined``.  Terminal requests are never replayed (a quarantined
  request poisoned a decode once; replaying it would poison the successor).
- **drain** (``record_progress``) — emitted tokens per still-pending
  request, so a SIGTERM'd engine's successor resumes mid-request instead
  of re-decoding from the prompt.

Emitted tokens are deliberately NOT journaled per decode tick: that would
put a disk write on the hot path, and recovery does not need it for
token-identity — only for avoiding recompute, which the drain path covers.

**Tier residency** (``record_tier``): when the KV host tier migrates a
preempted request's blocks to host DRAM (and again when they promote back),
the request's entry gains a ``tier`` record — residency (``"host"`` /
``"device"``), demoted row count, and emitted-token progress at migration
time.  Host DRAM dies with the process, so a successor can never reload the
demoted bytes; the record exists so recovery can rebuild *either way* (the
emitted progress rides along exactly like a drain's ``record_progress``) and
so post-mortem forensics can see which requests were host-resident at the
kill.  Same schema version — readers ignore keys they do not use.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Dict, List, Optional

__all__ = ["ServingJournal", "JournalError", "JOURNAL_VERSION"]

JOURNAL_VERSION = 1


class JournalError(RuntimeError):
    """The journal file is missing, unreadable, or from a newer schema."""


def _fsync_enabled() -> bool:
    return os.environ.get(
        "ACCELERATE_TPU_CHECKPOINT_FSYNC", "1"
    ).strip().lower() not in ("0", "false", "no", "off")


class ServingJournal:
    """One engine's write-ahead journal: an in-memory state mirrored to
    ``path`` atomically on every mutation.

    The file is written lazily — a fresh engine pointed at a dead
    predecessor's journal can still :meth:`load` it for recovery before the
    first admission overwrites it."""

    def __init__(self, path: str):
        self.path = path
        self._requests: Dict[str, dict] = {}
        self._done: Dict[str, str] = {}
        self._flushed = False
        self._deferred = False

    @property
    def flushed(self) -> bool:
        """Whether this journal has written ``path`` at least once (after
        which a predecessor's journal at the same path is gone)."""
        return self._flushed

    @contextlib.contextmanager
    def deferred(self):
        """Batch mutations into ONE atomic flush at context exit.  Recovery
        needs this: resubmitting N pending requests one-by-one would
        overwrite the predecessor's journal after the FIRST resubmit — a
        SIGKILL mid-recovery would then lose the other N-1 on disk.  With
        the batch, the predecessor's file survives intact until every
        pending request is re-journaled in a single ``os.replace``."""
        self._deferred = True
        try:
            yield self
        finally:
            self._deferred = False
            self._flush()

    # -- mutation (each call lands on disk before returning) -----------------

    def record_admit(self, req) -> None:
        self._requests[str(req.id)] = {
            "prompt": list(req.prompt),
            "max_new_tokens": int(req.max_new_tokens),
            "tag": req.tag,
            "ttft_deadline_ms": req.ttft_deadline_ms,
            "deadline_ms": req.deadline_ms,
            "emitted": [],
            # Wall-clock admission anchor: the tracer's cross-life stitcher
            # dates the victim's life from it even when the victim never
            # flushed a trace line (monotonic clocks die with the process).
            # Same schema version — readers ignore keys they do not use.
            "arrival_wall": time.time(),
        }
        self._flush()

    def record_done(self, rid: int, status: str) -> None:
        self._done[str(rid)] = status
        self._flush()

    def record_tier(self, req, residency: str) -> None:
        """Persist a request's KV tier residency transition (``"host"`` on
        demotion, ``"device"`` on promotion or fallback re-prefill), plus its
        emitted progress at that moment — so a successor resumes a killed
        host-resident request from its last migration point instead of the
        bare prompt, exactly as if a drain had recorded progress."""
        entry = self._requests.get(str(req.id))
        if entry is None:
            return
        entry["tier"] = {
            "residency": residency,
            "demoted_rows": int(req.demoted_rows),
            "demoted_blocks": len(req.demoted_blocks or ()),
            "migrations": int(req.migrations),
        }
        entry["emitted"] = list(req.emitted)
        self._flush()

    def record_progress(self, reqs) -> None:
        """Persist emitted-token progress for still-pending requests (the
        drain path calls this once with the whole requeue set)."""
        for req in reqs:
            entry = self._requests.get(str(req.id))
            if entry is not None:
                entry["emitted"] = list(req.emitted)
        self._flush()

    def _flush(self) -> None:
        if self._deferred:
            return
        state = {
            "version": JOURNAL_VERSION,
            "requests": self._requests,
            "done": self._done,
        }
        tmp = f"{self.path}.tmp"
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            if _fsync_enabled():
                try:
                    os.fsync(f.fileno())
                except OSError:
                    pass
        os.replace(tmp, self.path)
        self._flushed = True

    # -- recovery ------------------------------------------------------------

    @staticmethod
    def load(path: str) -> dict:
        """Parse a journal file; raises :class:`JournalError` when it is
        missing, unparseable, or from a newer schema (an older engine must
        not silently drop fields it does not understand)."""
        try:
            with open(path) as f:
                state = json.load(f)
        except FileNotFoundError:
            raise JournalError(f"no journal at {path!r}") from None
        except (OSError, json.JSONDecodeError) as e:
            raise JournalError(f"unreadable journal at {path!r}: {e}") from e
        version = state.get("version")
        if not isinstance(version, int) or version > JOURNAL_VERSION:
            raise JournalError(
                f"journal {path!r} has schema version {version!r}; this "
                f"engine understands <= {JOURNAL_VERSION}"
            )
        if not isinstance(state.get("requests"), dict) or not isinstance(
            state.get("done"), dict
        ):
            raise JournalError(f"journal {path!r} is structurally invalid")
        return state

    @staticmethod
    def pending(state: dict) -> List[dict]:
        """The journaled requests with no terminal record, oldest admission
        first (ids are monotonic), each with its original id under
        ``"id"``."""
        done = state["done"]
        out = []
        for rid in sorted(state["requests"], key=int):
            if rid not in done:
                rec = dict(state["requests"][rid])
                rec["id"] = int(rid)
                out.append(rec)
        return out
