"""``accelerate-tpu estimate-memory`` — per-dtype model memory table.

Parity target: reference ``commands/estimate.py`` (312 LoC): load the model
skeleton on the meta device, print total / largest-layer sizes per dtype
(training estimate = 4x inference: params + grads + 2 optimizer moments).
"""

from __future__ import annotations



def _format_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024:
            return f"{n:.2f} {unit}"
        n /= 1024
    return f"{n:.2f} PB"


def estimate_command(args):
    from ..big_modeling import init_empty_weights
    from ..utils.modeling import compute_module_sizes

    try:
        from transformers import AutoConfig, AutoModel

        config = AutoConfig.from_pretrained(args.model_name, trust_remote_code=args.trust_remote_code)
        with init_empty_weights():
            model = AutoModel.from_config(config, trust_remote_code=args.trust_remote_code)
    except Exception as e:
        raise SystemExit(f"Could not build model skeleton for {args.model_name}: {e}")

    dtypes = args.dtypes or ["float32", "bfloat16", "int8", "int4"]
    bytes_per = {"float32": 4, "float16": 2, "bfloat16": 2, "int8": 1, "int4": 0.5}
    sizes = compute_module_sizes(model)
    total_f32 = sizes[""]
    largest_f32 = max((v for k, v in sizes.items() if k.count(".") == 0 and k), default=total_f32)

    print(f"Memory estimate for {args.model_name}:")
    header = f"{'dtype':>10} | {'largest layer':>14} | {'total size':>12} | {'training (adam)':>16}"
    print(header)
    print("-" * len(header))
    for dt in dtypes:
        factor = bytes_per.get(dt, 4) / 4
        total = total_f32 * factor
        print(
            f"{dt:>10} | {_format_bytes(largest_f32 * factor):>14} | "
            f"{_format_bytes(total):>12} | {_format_bytes(total * 4):>16}"
        )


def register_subcommand(subparsers):
    parser = subparsers.add_parser("estimate-memory", help="Estimate model memory usage")
    parser.add_argument("model_name", type=str)
    parser.add_argument("--dtypes", nargs="+", default=None)
    parser.add_argument("--trust_remote_code", action="store_true")
    parser.set_defaults(func=estimate_command)
