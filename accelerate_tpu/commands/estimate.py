"""``accelerate-tpu estimate-memory`` — per-dtype model memory table.

Parity target: reference ``commands/estimate.py`` (312 LoC): resolve a model
to a skeleton (meta device — zero real memory), print largest-layer / total /
training sizes per dtype (training ~= 4x inference for adam: params + grads +
2 fp32-ish moments).

Resolution ladder (this image has no network egress, so the Hub path of the
reference is replaced by things that work offline):

1. native family presets — ``llama3-8b``, ``mixtral-8x7b``, ``gpt2``,
   ``llama-tiny``/… compute the table from the config's closed-form
   ``num_params()`` (no tensor is ever built);
2. a local transformers checkpoint/config directory (``AutoConfig`` +
   ``init_empty_weights`` meta skeleton);
3. a Hub model id — attempted last; fails with a clear offline error.

Extras beyond the reference: ``--hbm_gb`` prints the minimum fsdp ways for
the training footprint to fit per chip; ``--json`` emits one machine-readable
line.
"""

from __future__ import annotations

import json as _json

_BYTES_PER = {
    "float32": 4.0,
    "float16": 2.0,
    "bfloat16": 2.0,
    "fp8": 1.0,
    "int8": 1.0,
    "int4": 0.5,
    "int2": 0.25,
}
_DEFAULT_DTYPES = ["float32", "bfloat16", "int8", "int4"]


def _format_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024:
            return f"{n:.2f} {unit}"
        n /= 1024
    return f"{n:.2f} PB"


def _native_presets() -> dict:
    """name -> zero-cost config factory for the bundled model families."""
    from ..models import gpt2, llama, mixtral, resnet, vit

    return {
        "llama3-8b": llama.LlamaConfig.llama3_8b,
        "llama3-70b": llama.LlamaConfig.llama3_70b,
        "llama-tiny": llama.LlamaConfig.tiny,
        "mixtral-8x7b": mixtral.MixtralConfig.mixtral_8x7b,
        "mixtral-tiny": mixtral.MixtralConfig.tiny,
        "gpt2": gpt2.GPT2Config.gpt2_small,
        "gpt2-tiny": gpt2.GPT2Config.tiny,
        "vit-b-16": vit.ViTConfig.vit_base_16,
        "vit-l-16": vit.ViTConfig.vit_large_16,
        "resnet50": resnet.ResNetConfig.resnet50,
        "resnet101": resnet.ResNetConfig.resnet101,
        "resnet18": resnet.ResNetConfig.resnet18,
    }


def _native_estimate(name: str):
    """(total_f32_bytes, largest_layer_f32_bytes, config) from a preset —
    closed-form, no arrays."""
    factory = _native_presets().get(name.lower())
    if factory is None:
        return None
    cfg = factory()
    total = cfg.num_params() * 4
    if hasattr(cfg, "largest_block_f32_bytes"):
        # Families with non-uniform blocks (conv stages) expose the exact
        # number as a config-level hook, like num_params.
        return total, cfg.largest_block_f32_bytes(), cfg
    # Largest single block: token embedding vs one decoder layer.  Vision
    # configs have no vocab; their biggest block is always a layer.
    embed = getattr(cfg, "vocab_size", 0) * cfg.hidden_size * 4
    layers = getattr(cfg, "num_layers", 1) or 1
    per_layer = max((total - embed) // layers, 0)
    return total, max(embed, per_layer), cfg


def _kv_cache_row(cfg, context: int, batch: int = 1) -> dict:
    """Decode KV-cache bytes at a context length: bf16 vs the int8 cache
    (codes + per-slot bf16 scales; ``kv_cache_quant=True``)."""
    kv_heads = getattr(cfg, "num_kv_heads", None) or getattr(cfg, "num_heads", 1)
    hd = getattr(cfg, "head_dim_", None) or getattr(cfg, "head_dim", 0)
    layers = getattr(cfg, "num_layers", 1) or 1
    slots = 2 * layers * batch * context * kv_heads  # k and v
    return {
        "context": context,
        "batch": batch,
        "bf16": slots * hd * 2,
        "int8": slots * hd + slots * 2,  # codes + bf16 scale per slot
    }


def _skeleton_estimate(model_name: str, trust_remote_code: bool):
    """(total_f32_bytes, largest_layer_f32_bytes) via a meta-device skeleton."""
    from ..big_modeling import init_empty_weights
    from ..utils.modeling import compute_module_sizes

    from transformers import AutoConfig, AutoModel

    config = AutoConfig.from_pretrained(model_name, trust_remote_code=trust_remote_code)
    with init_empty_weights():
        model = AutoModel.from_config(config, trust_remote_code=trust_remote_code)
    sizes = compute_module_sizes(model)
    total = sizes[""]
    largest = max((v for k, v in sizes.items() if k.count(".") == 0 and k), default=total)
    return total, largest


def build_rows(total_f32: float, largest_f32: float, dtypes, hbm_gb=None) -> list[dict]:
    import math

    rows = []
    for dt in dtypes:
        if dt not in _BYTES_PER:
            raise SystemExit(f"Unknown dtype {dt!r}; options: {sorted(_BYTES_PER)}")
        factor = _BYTES_PER[dt] / 4.0
        total = total_f32 * factor
        row = {
            "dtype": dt,
            "largest_layer": largest_f32 * factor,
            "total": total,
            # Reference rule of thumb: params + grads + 2 adam moments.
            "training": total * 4,
        }
        if hbm_gb:
            row["min_fsdp_ways"] = max(1, math.ceil(row["training"] / (hbm_gb * 1024**3)))
        rows.append(row)
    return rows


def estimate_command(args):
    native = _native_estimate(args.model_name)
    native_cfg = None
    if native is not None:
        total_f32, largest_f32, native_cfg = native
        source = "native preset"
    else:
        try:
            total_f32, largest_f32 = _skeleton_estimate(args.model_name, args.trust_remote_code)
            source = "meta skeleton"
        except Exception as e:
            presets = ", ".join(sorted(_native_presets()))
            raise SystemExit(
                f"Could not build model skeleton for {args.model_name!r}: {e}\n"
                f"(no network egress — use a local checkpoint path or a native "
                f"preset: {presets})"
            )

    rows = build_rows(total_f32, largest_f32, args.dtypes or _DEFAULT_DTYPES, hbm_gb=args.hbm_gb)

    if args.json:
        payload = {"model": args.model_name, "source": source, "rows": rows}
        if args.hbm_gb:
            payload["hbm_gb"] = args.hbm_gb
        print(_json.dumps(payload))
        return rows

    print(f"Memory estimate for {args.model_name} ({source}):")
    header = f"{'dtype':>10} | {'largest layer':>14} | {'total size':>12} | {'training (adam)':>16}"
    print(header)
    print("-" * len(header))
    for r in rows:
        print(
            f"{r['dtype']:>10} | {_format_bytes(r['largest_layer']):>14} | "
            f"{_format_bytes(r['total']):>12} | {_format_bytes(r['training']):>16}"
        )
    if args.hbm_gb:
        for r in rows:
            ways = r["min_fsdp_ways"]
            fits = "fits on 1 chip" if ways == 1 else f"needs fsdp>={ways} to train"
            print(f"  {r['dtype']}: {fits} at {args.hbm_gb} GB HBM/chip")
    if native_cfg is not None and getattr(native_cfg, "head_dim_", None) is not None:
        # Decode-cache advisory: where generation memory goes at long context
        # (and what kv_cache_quant=True buys).
        print("KV cache at decode (batch 1):")
        for context in (8192, 32768, 131072):
            row = _kv_cache_row(native_cfg, context)
            print(
                f"  context {context:>6}: bf16 {_format_bytes(row['bf16']):>10}"
                f"  |  int8 (kv_cache_quant) {_format_bytes(row['int8']):>10}"
            )
    return rows


def register_subcommand(subparsers):
    parser = subparsers.add_parser("estimate-memory", help="Estimate model memory usage")
    parser.add_argument("model_name", type=str,
                        help="Native preset (llama3-8b, mixtral-8x7b, gpt2, ...), local "
                             "checkpoint path, or Hub id (needs network)")
    parser.add_argument("--dtypes", nargs="+", default=None,
                        help=f"Any of {sorted(_BYTES_PER)}")
    parser.add_argument("--trust_remote_code", action="store_true")
    parser.add_argument("--hbm_gb", type=float, default=None,
                        help="Per-chip HBM to compute minimum fsdp ways for training")
    parser.add_argument("--json", action="store_true", help="One machine-readable JSON line")
    parser.set_defaults(func=estimate_command)
