"""``accelerate-tpu merge-weights`` — consolidate a sharded checkpoint directory.

Parity target: reference ``commands/merge.py`` (71 LoC) over
``merge_fsdp_weights`` (``utils/fsdp_utils.py:354``): distributed checkpoint →
one consolidated safetensors file.  Our sharded layout is one
``model_shard_{rank}.safetensors`` per process (written under
state_dict_type=SHARDED_STATE_DICT); merging concatenates by the recorded specs.
"""

from __future__ import annotations

import json
import os

import numpy as np


def merge_command(args):
    from safetensors.numpy import load_file, save_file

    in_dir = args.checkpoint_dir
    out_dir = args.output_path
    os.makedirs(out_dir, exist_ok=True)
    shard_files = sorted(
        f for f in os.listdir(in_dir) if f.startswith("model_shard_") and f.endswith(".safetensors")
    )
    if not shard_files:
        # Already consolidated: copy through.
        src = os.path.join(in_dir, "model.safetensors")
        if not os.path.exists(src):
            raise SystemExit(f"No shards or consolidated weights found in {in_dir}")
        save_file(load_file(src), os.path.join(out_dir, "model.safetensors"))
        print(f"Copied consolidated weights to {out_dir}")
        return

    meta_path = os.path.join(in_dir, "shard_index.json")
    shard_meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            shard_meta = json.load(f)

    merged: dict[str, np.ndarray] = {}
    shards = [load_file(os.path.join(in_dir, f)) for f in shard_files]
    for key in shards[0]:
        axis = shard_meta.get(key, {}).get("concat_axis")
        if axis is None:
            merged[key] = shards[0][key]
        else:
            merged[key] = np.concatenate([s[key] for s in shards], axis=axis)
    save_file(merged, os.path.join(out_dir, "model.safetensors"))
    print(f"Merged {len(shard_files)} shards -> {out_dir}/model.safetensors")


def register_subcommand(subparsers):
    parser = subparsers.add_parser("merge-weights", help="Merge sharded checkpoints")
    parser.add_argument("checkpoint_dir", type=str)
    parser.add_argument("output_path", type=str)
    parser.set_defaults(func=merge_command)
