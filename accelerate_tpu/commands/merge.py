"""``accelerate-tpu merge-weights`` — consolidate a sharded checkpoint directory.

Parity target: reference ``commands/merge.py`` (71 LoC) over
``merge_fsdp_weights`` (``utils/fsdp_utils.py:354``): distributed checkpoint →
one consolidated safetensors file.  Our sharded layout is one
``model_shard_{rank}.safetensors`` per process (written under
state_dict_type=SHARDED_STATE_DICT); merging concatenates by the recorded specs.
"""

from __future__ import annotations

import json
import os

import numpy as np


def _is_orbax_checkpoint(path: str) -> bool:
    names = set(os.listdir(path))
    return bool(names & {"_METADATA", "_CHECKPOINT_METADATA", "manifest.ocdbt"}) or any(
        os.path.isdir(os.path.join(path, n)) and n in ("d", "ocdbt.process_0") for n in names
    )


def _merge_orbax(in_dir: str, out_dir: str) -> None:
    """Consolidate an orbax sharded export (``checkpointing.save_sharded_model``
    under SHARDED_STATE_DICT) into one safetensors file: restore to host
    (orbax assembles the full arrays) and flatten dotted keys."""
    import jax
    import orbax.checkpoint as ocp
    from safetensors.numpy import save_file

    restored = ocp.StandardCheckpointer().restore(os.path.abspath(in_dir))

    flat = {}

    def walk(tree, prefix=""):
        if isinstance(tree, dict):
            for k, v in tree.items():
                walk(v, f"{prefix}{k}.")
            return
        # Orbax can restore list/tuple nodes; np.asarray on one would stack the
        # whole sequence under a single flattened key (or raise on ragged
        # members) — recurse with index keys to keep the structure explicit.
        if isinstance(tree, (list, tuple)):
            for i, v in enumerate(tree):
                walk(v, f"{prefix}{i}.")
            return
        flat[prefix[:-1]] = np.asarray(jax.device_get(tree))

    walk(restored)
    save_file(flat, os.path.join(out_dir, "model.safetensors"))
    print(f"Merged orbax sharded checkpoint -> {out_dir}/model.safetensors ({len(flat)} tensors)")


def merge_command(args):
    from safetensors.numpy import load_file, save_file

    in_dir = args.checkpoint_dir
    out_dir = args.output_path
    os.makedirs(out_dir, exist_ok=True)
    if os.path.isdir(in_dir) and _is_orbax_checkpoint(in_dir):
        return _merge_orbax(in_dir, out_dir)
    # Numeric rank order — lexicographic would interleave shard 10 before 2
    # and silently scramble the concatenation.  The regex also keeps stray
    # non-rank files (model_shard_backup.safetensors) out of the merge.
    import re

    shard_matches = sorted(
        (m for m in (re.fullmatch(r"model_shard_(\d+)\.safetensors", f) for f in os.listdir(in_dir)) if m),
        key=lambda m: int(m.group(1)),
    )
    shard_files = [m.group(0) for m in shard_matches]
    if not shard_files:
        # Already consolidated: copy through.
        src = os.path.join(in_dir, "model.safetensors")
        if not os.path.exists(src):
            raise SystemExit(f"No shards or consolidated weights found in {in_dir}")
        save_file(load_file(src), os.path.join(out_dir, "model.safetensors"))
        print(f"Copied consolidated weights to {out_dir}")
        return

    meta_path = os.path.join(in_dir, "shard_index.json")
    shard_meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            shard_meta = json.load(f)

    merged: dict[str, np.ndarray] = {}
    shards = [load_file(os.path.join(in_dir, f)) for f in shard_files]
    for key in shards[0]:
        axis = shard_meta.get(key, {}).get("concat_axis")
        if axis is None:
            merged[key] = shards[0][key]
        else:
            merged[key] = np.concatenate([s[key] for s in shards], axis=axis)
    save_file(merged, os.path.join(out_dir, "model.safetensors"))
    print(f"Merged {len(shard_files)} shards -> {out_dir}/model.safetensors")


def register_subcommand(subparsers):
    parser = subparsers.add_parser("merge-weights", help="Merge sharded checkpoints")
    parser.add_argument("checkpoint_dir", type=str)
    parser.add_argument("output_path", type=str)
    parser.set_defaults(func=merge_command)
