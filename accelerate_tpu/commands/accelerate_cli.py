"""Console entry point (parity: reference ``commands/accelerate_cli.py``)."""

from __future__ import annotations

import argparse

from . import config as config_cmd
from . import env as env_cmd
from . import estimate as estimate_cmd
from . import from_accelerate as from_accelerate_cmd
from . import launch as launch_cmd
from . import merge as merge_cmd
from . import test as test_cmd
from . import tpu as tpu_cmd


def main():
    from ._parser import DualDashParser

    parser = argparse.ArgumentParser(
        "accelerate-tpu", usage="accelerate-tpu <command> [<args>]", allow_abbrev=False
    )
    # Every subcommand parser accepts --foo-bar alongside --foo_bar
    # (reference commands/utils.py CustomArgumentParser semantics).
    subparsers = parser.add_subparsers(
        help="accelerate-tpu command helpers", dest="command", parser_class=DualDashParser
    )
    config_cmd.register_subcommand(subparsers)
    env_cmd.register_subcommand(subparsers)
    launch_cmd.register_subcommand(subparsers)
    estimate_cmd.register_subcommand(subparsers)
    merge_cmd.register_subcommand(subparsers)
    test_cmd.register_subcommand(subparsers)
    tpu_cmd.register_subcommand(subparsers)
    from_accelerate_cmd.register_subcommand(subparsers)

    args = parser.parse_args()
    if not hasattr(args, "func"):
        parser.print_help()
        raise SystemExit(1)
    args.func(args)


if __name__ == "__main__":
    main()
