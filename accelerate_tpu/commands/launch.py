"""``accelerate-tpu launch`` — process fan-out + env contract.

Parity target: reference ``commands/launch.py`` (1202 LoC) + ``utils/launch.py``
(705): merge CLI flags ← config file ← defaults, write the ``ACCELERATE_*`` env
contract, spawn workers.

TPU-native redesign of the fan-out (reference call stack 3.4): JAX wants ONE
process per host, so:

- single host: exec the script in ONE subprocess (the mesh drives all local
  chips) — no torchrun-style N-process spawn;
- multi host (``--num_machines > 1``): this host runs its one worker with
  coordinator env (``ACCELERATE_COORDINATOR_ADDRESS`` = machine 0); the user (or
  ``gcloud``/pod tooling) runs the same command on every host with its
  ``--machine_rank`` — same operational shape as the reference's
  ``tpu_pod_launcher`` ssh fan-out (``commands/launch.py:908``);
- ``--debug_cpu N``: N local CPU processes forming a real jax.distributed
  cluster (the `debug_launcher` path) for laptop/CI testing.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from .config import ClusterConfig, load_config

__all__ = ["launch_command", "launch_command_parser", "register_subcommand"]


# Reference flags (commands/launch.py:141-770) with NO native meaning on TPU.
# Each entry: flag dest -> why it does not apply / what to use instead.  A set
# flag WARNS (never crashes) so reference launch commands run unmodified.
_UNSUPPORTED_FLAGS = {
    "multi_gpu": "the GSPMD mesh covers every chip automatically; drop the flag",
    "gpu_ids": "chip selection is topology-driven (JAX mesh); use --num_processes / mesh axes",
    "use_xpu": "XPU is Intel GPU infrastructure; this backend targets TPU",
    "ipex": "IPEX is an Intel CPU/GPU optimizer; XLA owns TPU compilation",
    "dynamo_backend": "torch.compile/dynamo has no role on TPU — the whole step is XLA-compiled natively",
    "dynamo_mode": "see --dynamo_backend",
    "dynamo_use_fullgraph": "see --dynamo_backend",
    "dynamo_use_dynamic": "see --dynamo_backend",
    "rdzv_backend": "torchelastic rendezvous is replaced by the jax.distributed coordinator; use --main_process_ip/--main_process_port",
    "rdzv_conf": "see --rdzv_backend",
    "same_network": "see --rdzv_backend",
    "role": "torchelastic-only; one process per TPU host",
    "log_dir": "torchelastic log redirection; use shell redirection per host",
    "tee": "torchelastic-only; use shell redirection per host",
    "max_restarts": "elastic restarts apply to notebook_launcher(max_restarts=...); the CLI launcher runs one attempt per host",
    "monitor_interval": "see --max_restarts",
    "mpirun_hostfile": "MPI launch is replaced by per-host jax.distributed bring-up; run this command on every host with --machine_rank",
    "mpirun_ccl": "see --mpirun_hostfile",
    "deepspeed_hostfile": "DeepSpeed pdsh/mpi multi-node launch is replaced by per-host bring-up (--machine_rank per host)",
    "deepspeed_exclusion_filter": "see --deepspeed_hostfile",
    "deepspeed_inclusion_filter": "see --deepspeed_hostfile",
    "deepspeed_multinode_launcher": "see --deepspeed_hostfile",
    "deepspeed_moe_layer_cls_names": "MoE layers route through the native ep mesh axis (ops/moe.py); no ZeRO-3 leaf marking needed",
    "enable_cpu_affinity": "host-side NUMA pinning is not load-bearing for single-controller TPU hosts",
    # downcast_bf16 is NOT listed here: it maps to mixed_precision="bf16" in
    # _merge (same conversion from_accelerate.py applies to migrated configs).
    "fp8_opt_level": "MS-AMP-specific; the native fp8 path has one backend (ops/fp8.py recipe kwargs)",
    "fp8_override_linear_precision": "TransformerEngine-specific; use the native recipe kwargs",
    "fp8_use_autocast_during_eval": "TE-specific; eval dtype follows the step's mixed-precision policy",
    "fsdp_backward_prefetch": "GSPMD/XLA schedules all-gathers automatically; no manual prefetch knob",
    "fsdp_forward_prefetch": "see --fsdp_backward_prefetch",
    "fsdp_sync_module_states": "parameters are sharded jax arrays built from one host copy; nothing to broadcast",
    "fsdp_use_orig_params": "functional params make the flat-param distinction moot",
    "fsdp_cpu_ram_efficient_loading": "streaming checkpoint load is the default (utils/modeling.py load_checkpoint_in_model)",
    "quiet": None,  # native: suppress launcher banner
    # num_cpu_threads_per_process is NATIVE (build_env exports OMP_NUM_THREADS)
    # — deliberately not listed here.
}


def _flag_bool(value) -> bool:
    """Boolean-ish CLI/config value -> bool.  Reference flags pass booleans as
    strings ('--fsdp_offload_params false'), where plain truthiness would
    invert the request."""
    if value is None:
        return False
    if isinstance(value, bool):
        return value
    from ..utils.environment import str_to_bool

    return bool(str_to_bool(str(value)))


def launch_command_parser(subparsers=None):
    from ._parser import DualDashParser

    if subparsers is not None:
        parser = subparsers.add_parser("launch", help="Launch a training script on TPU hosts")
    else:
        parser = DualDashParser("accelerate-tpu launch")
    # Hardware / topology (reference "Hardware Selection"/"Resource Selection")
    parser.add_argument("--config_file", default=None)
    parser.add_argument("--num_machines", type=int, default=None, help="Number of hosts")
    parser.add_argument("--machine_rank", type=int, default=None, help="This host's rank")
    parser.add_argument("--main_process_ip", default=None)
    parser.add_argument("--main_process_port", type=int, default=None)
    parser.add_argument("--num_processes", type=int, default=None,
                        help="Total host processes (defaults to num_machines)")
    parser.add_argument("--cpu", action="store_true", help="Force CPU execution")
    parser.add_argument("--multi_gpu", action="store_true", default=None)
    parser.add_argument("--gpu_ids", default=None)
    parser.add_argument("--use_xpu", action="store_true", default=None)
    parser.add_argument("--ipex", action="store_true", default=None)
    parser.add_argument("--debug_cpu", type=int, default=0,
                        help="Spawn N local CPU processes as a simulated cluster")
    # Fleet supervision (applies to the --debug_cpu supervised launch).
    parser.add_argument("--elastic", action="store_true", default=None,
                        help="On a dead/wedged worker, relaunch the fleet at the "
                        "reduced world size (elastic resume restores the run)")
    parser.add_argument("--heartbeat_timeout", type=float, default=None,
                        help="Seconds a worker's step-loop heartbeat may go stale "
                        "before the supervisor declares it wedged (default 60)")
    parser.add_argument("--grace_period", type=float, default=None,
                        help="Seconds survivors get to exit after SIGTERM before "
                        "the supervisor SIGKILLs them (default 10)")
    parser.add_argument("--quiet", "-q", action="store_true", default=None)
    # Precision / accumulation
    parser.add_argument("--mixed_precision", default=None, choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--gradient_accumulation_steps", type=int, default=None)
    parser.add_argument("--gradient_clipping", type=float, default=None)
    # Dynamo group (reference commands/launch.py:240-270) — no TPU meaning.
    parser.add_argument("--dynamo_backend", default=None)
    parser.add_argument("--dynamo_mode", default=None)
    parser.add_argument("--dynamo_use_fullgraph", action="store_true", default=None)
    parser.add_argument("--dynamo_use_dynamic", action="store_true", default=None)
    # Elastic / rendezvous group — torchelastic-only.
    parser.add_argument("--rdzv_backend", default=None)
    parser.add_argument("--rdzv_conf", default=None)
    parser.add_argument("--same_network", action="store_true", default=None)
    parser.add_argument("--role", default=None)
    parser.add_argument("--log_dir", default=None)
    parser.add_argument("--tee", default=None)
    parser.add_argument("--max_restarts", type=int, default=None)
    parser.add_argument("--monitor_interval", type=float, default=None)
    parser.add_argument("--num_cpu_threads_per_process", type=int, default=None)
    parser.add_argument("--enable_cpu_affinity", action="store_true", default=None)
    # MPI group.
    parser.add_argument("--mpirun_hostfile", default=None)
    parser.add_argument("--mpirun_ccl", type=int, default=None)
    # TPU group (reference: tpu_launcher/tpu_pod_launcher).
    parser.add_argument("--tpu", action="store_true", default=None,
                        help="Accepted for reference parity (TPU is the default here)")
    parser.add_argument("--tpu_cluster", "--tpu_use_cluster", action="store_true", default=None,
                        dest="tpu_cluster", help="Pod fan-out via `accelerate-tpu tpu-config`")
    parser.add_argument("--no_tpu_cluster", action="store_false", dest="tpu_cluster")
    parser.add_argument("--tpu_use_sudo", action="store_true", default=None)
    parser.add_argument("--vm", action="append", default=None)
    parser.add_argument("--env", action="append", default=None,
                        help="Extra VAR=VALUE pairs for the worker environment")
    parser.add_argument("--main_training_function", default=None,
                        help="Exported as ACCELERATE_MAIN_TRAINING_FUNCTION (notebook/pod entry)")
    parser.add_argument("--downcast_bf16", action="store_true", default=None)
    # Mesh axes (native)
    parser.add_argument("--dp", type=int, default=None)
    parser.add_argument("--fsdp_size", type=int, default=None)
    parser.add_argument("--tp_size", type=int, default=None)
    parser.add_argument("--sp_size", type=int, default=None)
    parser.add_argument("--pp_size", type=int, default=None)
    parser.add_argument("--ep_size", type=int, default=None)
    # FSDP group (reference commands/launch.py:507-610) — FSDP_* env contract.
    parser.add_argument("--use_fsdp", action="store_true", default=None)
    parser.add_argument("--fsdp_sharding_strategy", default=None)
    parser.add_argument("--fsdp_reshard_after_forward", default=None,
                        help="FSDP2 spelling of the sharding strategy")
    parser.add_argument("--fsdp_min_num_params", type=int, default=None)
    parser.add_argument("--fsdp_offload_params", default=None)
    parser.add_argument("--fsdp_cpu_offload", action="store_true", default=None)
    parser.add_argument("--fsdp_auto_wrap_policy", default=None)
    parser.add_argument("--fsdp_transformer_layer_cls_to_wrap", default=None)
    parser.add_argument("--fsdp_state_dict_type", default=None)
    parser.add_argument("--fsdp_activation_checkpointing", default=None)
    parser.add_argument("--fsdp_backward_prefetch", default=None)
    parser.add_argument("--fsdp_forward_prefetch", default=None)
    parser.add_argument("--fsdp_sync_module_states", default=None)
    parser.add_argument("--fsdp_use_orig_params", default=None)
    parser.add_argument("--fsdp_cpu_ram_efficient_loading", default=None)
    parser.add_argument("--fsdp_version", type=int, default=None,
                        help="1 and 2 map to the same GSPMD sharding")
    # DeepSpeed group (reference commands/launch.py:610-700) — config dialect.
    parser.add_argument("--use_deepspeed", action="store_true", default=None)
    parser.add_argument("--deepspeed_config_file", default=None,
                        help="ds_config.json consumed as a config dialect")
    parser.add_argument("--zero_stage", type=int, default=None)
    parser.add_argument("--offload_optimizer_device", default=None)
    parser.add_argument("--offload_param_device", default=None)
    parser.add_argument("--offload_optimizer_nvme_path", default=None)
    parser.add_argument("--offload_param_nvme_path", default=None)
    parser.add_argument("--zero3_init_flag", default=None)
    parser.add_argument("--zero3_save_16bit_model", default=None)
    parser.add_argument("--deepspeed_hostfile", default=None)
    parser.add_argument("--deepspeed_exclusion_filter", default=None)
    parser.add_argument("--deepspeed_inclusion_filter", default=None)
    parser.add_argument("--deepspeed_multinode_launcher", default=None)
    parser.add_argument("--deepspeed_moe_layer_cls_names", default=None)
    # Megatron-LM group — MEGATRON_LM_* env contract.
    parser.add_argument("--use_megatron_lm", action="store_true", default=None)
    parser.add_argument("--megatron_lm_tp_degree", type=int, default=None)
    parser.add_argument("--megatron_lm_pp_degree", type=int, default=None)
    parser.add_argument("--megatron_lm_num_micro_batches", type=int, default=None)
    parser.add_argument("--megatron_lm_sequence_parallelism", default=None)
    parser.add_argument("--megatron_lm_recompute_activations", default=None)
    parser.add_argument("--megatron_lm_use_distributed_optimizer", default=None)
    parser.add_argument("--megatron_lm_gradient_clipping", type=float, default=None)
    # FP8 recipe group — native recipe kwargs (ops/fp8.py).
    parser.add_argument("--fp8_backend", default=None)
    parser.add_argument("--fp8_format", default=None)
    parser.add_argument("--fp8_margin", type=int, default=None)
    parser.add_argument("--fp8_interval", type=int, default=None)
    parser.add_argument("--fp8_amax_history_len", type=int, default=None)
    parser.add_argument("--fp8_amax_compute_algo", default=None)
    parser.add_argument("--fp8_opt_level", default=None)
    parser.add_argument("--fp8_override_linear_precision", default=None)
    parser.add_argument("--fp8_use_autocast_during_eval", action="store_true", default=None)
    # SageMaker group — documented out-of-scope (utils/launch.py:147).
    parser.add_argument("--aws_access_key_id", default=None)
    parser.add_argument("--aws_secret_access_key", default=None)
    # Misc
    parser.add_argument("--debug", action="store_true", help="ACCELERATE_DEBUG_MODE=1")
    parser.add_argument("--dry_run", action="store_true",
                        help="Print the resolved worker env contract as JSON and exit")
    parser.add_argument("-m", "--module", action="store_true",
                        help="Run the training script as a python module (python -m)")
    parser.add_argument("--no_python", action="store_true",
                        help="Execute the script directly (it is not a python file)")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    parser.set_defaults(func=launch_command)
    return parser


def _warn_unsupported(args) -> list[str]:
    """Warn (never crash) for reference flags with no TPU meaning; returns the
    warning list for tests/--dry_run introspection."""
    import warnings

    notes = []
    for dest, why in _UNSUPPORTED_FLAGS.items():
        if why is None:
            continue
        val = getattr(args, dest, None)
        # Identity checks: `0 in (None, False)` would be True and silently
        # swallow the warning for explicit zero values.
        if val is not None and val is not False:
            notes.append(f"--{dest}: unsupported on TPU — {why}")
    for note in notes:
        warnings.warn(note)
    return notes


def _resolve_mixed_precision(args, cfg: ClusterConfig):
    """CLI > config, with the reference's TPU knob mapped rather than dropped:
    ``--downcast_bf16`` (XRT-era XLA_DOWNCAST_BF16) means "run in bf16", which
    here is the explicit ``mixed_precision='bf16'`` policy — the same
    conversion ``from_accelerate`` applies to migrated configs."""
    mp = args.mixed_precision if args.mixed_precision is not None else cfg.mixed_precision
    downcast = _flag_bool(getattr(args, "downcast_bf16", None)) or _flag_bool(
        getattr(cfg, "downcast_bf16", None)
    )
    if downcast and mp in (None, "no", "None"):
        import warnings

        warnings.warn(
            "--downcast_bf16 maps to mixed_precision='bf16' on this backend "
            "(XLA_DOWNCAST_BF16 is an XRT-era flag; dtype policy is explicit here)."
        )
        return "bf16"
    return mp


def _merge(args, cfg: ClusterConfig):
    """CLI flags override config file (reference ``_validate_launch_command``
    ``commands/launch.py:987-1166``)."""
    def pick(cli, conf):
        return cli if cli is not None else conf

    merged = {
        "num_machines": pick(args.num_machines, cfg.num_machines),
        "machine_rank": pick(args.machine_rank, cfg.machine_rank),
        "main_process_ip": pick(args.main_process_ip, cfg.main_process_ip),
        "main_process_port": pick(args.main_process_port, cfg.main_process_port),
        "mixed_precision": _resolve_mixed_precision(args, cfg),
        "gradient_accumulation_steps": pick(
            args.gradient_accumulation_steps, cfg.gradient_accumulation_steps
        ),
        "dp": pick(args.dp, cfg.dp),
        "fsdp": pick(args.fsdp_size, cfg.fsdp),
        "tp": pick(args.tp_size, cfg.tp),
        "sp": pick(args.sp_size, cfg.sp),
        "pp": pick(args.pp_size, cfg.pp),
        "ep": pick(args.ep_size, cfg.ep),
        "use_fsdp": pick(args.use_fsdp, cfg.use_fsdp),
        "fsdp_sharding_strategy": pick(args.fsdp_sharding_strategy, cfg.fsdp_sharding_strategy),
        "fsdp_min_num_params": pick(args.fsdp_min_num_params, cfg.fsdp_min_num_params),
        "deepspeed_config_file": pick(
            getattr(args, "deepspeed_config_file", None), cfg.deepspeed_config_file
        ),
    }
    # Reference-surface knobs that flow straight into env vars the plugins
    # already read (FSDP_* / ACCELERATE_DEEPSPEED_* / MEGATRON_LM_* contract).
    merged["gradient_clipping"] = pick(
        getattr(args, "gradient_clipping", None), getattr(cfg, "gradient_clipping", None)
    )
    for dest in (
        "fsdp_offload_params",
        "fsdp_cpu_offload",
        "fsdp_auto_wrap_policy",
        "fsdp_transformer_layer_cls_to_wrap",
        "fsdp_state_dict_type",
        "fsdp_activation_checkpointing",
        "fsdp_reshard_after_forward",
        "fsdp_version",
        "use_deepspeed",
        "zero_stage",
        "offload_optimizer_device",
        "offload_param_device",
        "zero3_init_flag",
        "zero3_save_16bit_model",
        "use_megatron_lm",
        "megatron_lm_tp_degree",
        "megatron_lm_pp_degree",
        "megatron_lm_num_micro_batches",
        "megatron_lm_sequence_parallelism",
        "megatron_lm_recompute_activations",
        "megatron_lm_use_distributed_optimizer",
        "megatron_lm_gradient_clipping",
        "fp8_backend",
        "fp8_format",
        "fp8_margin",
        "fp8_interval",
        "fp8_amax_history_len",
        "fp8_amax_compute_algo",
        "dynamo_backend",
        "dynamo_mode",
        "dynamo_use_fullgraph",
        "dynamo_use_dynamic",
        "deepspeed_moe_layer_cls_names",
        "sp_impl",
        "main_training_function",
        "num_cpu_threads_per_process",
        "env",
    ):
        merged[dest] = pick(getattr(args, dest, None), getattr(cfg, dest, None))
    return merged


def build_env(merged: dict, debug: bool = False, cpu: bool = False) -> dict:
    """The env contract every worker reads (reference ``utils/launch.py:98-326``)."""
    env = dict(os.environ)
    env["ACCELERATE_MIXED_PRECISION"] = str(merged["mixed_precision"])
    env["ACCELERATE_GRADIENT_ACCUMULATION_STEPS"] = str(merged["gradient_accumulation_steps"])
    for axis in ("dp", "fsdp", "tp", "sp", "pp", "ep"):
        size = merged[axis]
        if size and size > 1:
            env[f"ACCELERATE_PARALLELISM_{axis.upper()}"] = str(size)
    if merged["use_fsdp"]:
        env["ACCELERATE_USE_FSDP"] = "1"
        strategy = merged["fsdp_sharding_strategy"]
        if merged.get("fsdp_reshard_after_forward") is not None:
            # FSDP2 spelling: true == FULL_SHARD, false == SHARD_GRAD_OP.
            strategy = (
                "FULL_SHARD" if _flag_bool(merged["fsdp_reshard_after_forward"]) else "SHARD_GRAD_OP"
            )
        env["FSDP_SHARDING_STRATEGY"] = str(strategy)
        env["FSDP_MIN_NUM_PARAMS"] = str(merged["fsdp_min_num_params"])
        if _flag_bool(merged.get("fsdp_offload_params")) or _flag_bool(merged.get("fsdp_cpu_offload")):
            env["FSDP_CPU_OFFLOAD"] = "1"
        if merged.get("fsdp_transformer_layer_cls_to_wrap"):
            env["FSDP_TRANSFORMER_CLS_TO_WRAP"] = str(merged["fsdp_transformer_layer_cls_to_wrap"])
        if merged.get("fsdp_state_dict_type"):
            env["FSDP_STATE_DICT_TYPE"] = str(merged["fsdp_state_dict_type"])
        if _flag_bool(merged.get("fsdp_activation_checkpointing")):
            env["FSDP_ACTIVATION_CHECKPOINTING"] = "1"
    if merged.get("deepspeed_config_file") or merged.get("use_deepspeed"):
        env["ACCELERATE_USE_DEEPSPEED"] = "true"
        if merged.get("deepspeed_config_file"):
            env["ACCELERATE_DEEPSPEED_CONFIG_FILE"] = str(merged["deepspeed_config_file"])
        for dest, var in (
            ("zero_stage", "ACCELERATE_DEEPSPEED_ZERO_STAGE"),
            ("offload_optimizer_device", "ACCELERATE_DEEPSPEED_OFFLOAD_OPTIMIZER_DEVICE"),
            ("offload_param_device", "ACCELERATE_DEEPSPEED_OFFLOAD_PARAM_DEVICE"),
            ("zero3_init_flag", "ACCELERATE_DEEPSPEED_ZERO3_INIT"),
            ("zero3_save_16bit_model", "ACCELERATE_DEEPSPEED_ZERO3_SAVE_16BIT_MODEL"),
            ("deepspeed_moe_layer_cls_names", "ACCELERATE_DEEPSPEED_MOE_LAYER_CLS_NAMES"),
        ):
            if merged.get(dest) is not None:
                env[var] = str(merged[dest])
    if merged.get("use_megatron_lm"):
        env["ACCELERATE_USE_MEGATRON_LM"] = "true"
        for dest, var in (
            ("megatron_lm_tp_degree", "MEGATRON_LM_TP_DEGREE"),
            ("megatron_lm_pp_degree", "MEGATRON_LM_PP_DEGREE"),
            ("megatron_lm_num_micro_batches", "MEGATRON_LM_NUM_MICRO_BATCHES"),
            ("megatron_lm_sequence_parallelism", "MEGATRON_LM_SEQUENCE_PARALLELISM"),
            ("megatron_lm_recompute_activations", "MEGATRON_LM_RECOMPUTE_ACTIVATIONS"),
            ("megatron_lm_use_distributed_optimizer", "MEGATRON_LM_USE_DISTRIBUTED_OPTIMIZER"),
            ("megatron_lm_gradient_clipping", "MEGATRON_LM_GRADIENT_CLIPPING"),
        ):
            if merged.get(dest) is not None:
                env[var] = str(merged[dest])
    if merged.get("gradient_clipping") is not None:
        env["ACCELERATE_GRADIENT_CLIPPING"] = str(merged["gradient_clipping"])
    for dest, var in (
        ("dynamo_backend", "ACCELERATE_DYNAMO_BACKEND"),
        ("dynamo_mode", "ACCELERATE_DYNAMO_MODE"),
        ("dynamo_use_fullgraph", "ACCELERATE_DYNAMO_USE_FULLGRAPH"),
        ("dynamo_use_dynamic", "ACCELERATE_DYNAMO_USE_DYNAMIC"),
        ("sp_impl", "ACCELERATE_SP_IMPL"),
    ):
        if merged.get(dest) is not None:
            env[var] = str(merged[dest])
    for dest, var in (
        ("fp8_backend", "ACCELERATE_FP8_BACKEND"),
        ("fp8_format", "ACCELERATE_FP8_FORMAT"),
        ("fp8_margin", "ACCELERATE_FP8_MARGIN"),
        ("fp8_interval", "ACCELERATE_FP8_INTERVAL"),
        ("fp8_amax_history_len", "ACCELERATE_FP8_AMAX_HISTORY_LEN"),
        ("fp8_amax_compute_algo", "ACCELERATE_FP8_AMAX_COMPUTE_ALGO"),
        ("main_training_function", "ACCELERATE_MAIN_TRAINING_FUNCTION"),
    ):
        if merged.get(dest) is not None:
            env[var] = str(merged[dest])
    if merged.get("num_cpu_threads_per_process"):
        env["OMP_NUM_THREADS"] = str(merged["num_cpu_threads_per_process"])
    for pair in merged.get("env") or []:
        key, _, value = str(pair).partition("=")
        if key:
            env[key] = value
    if debug:
        env["ACCELERATE_DEBUG_MODE"] = "1"
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
    nm = merged["num_machines"]
    if nm and nm > 1:
        ip = merged["main_process_ip"] or "127.0.0.1"
        port = merged["main_process_port"] or 29500
        env["ACCELERATE_COORDINATOR_ADDRESS"] = f"{ip}:{port}"
        env["ACCELERATE_NUM_PROCESSES"] = str(merged.get("num_processes") or nm)
        env["ACCELERATE_PROCESS_ID"] = str(merged["machine_rank"])
    return env


def _script_cmd(args) -> list:
    if getattr(args, "module", False) and getattr(args, "no_python", False):
        raise SystemExit("--module and --no_python cannot be used together.")
    if getattr(args, "no_python", False):
        return [args.training_script] + list(args.training_script_args)
    base = [sys.executable]
    if getattr(args, "module", False):
        base.append("-m")
    return base + [args.training_script] + list(args.training_script_args)


def launch_command(args):
    if getattr(args, "aws_access_key_id", None) or getattr(args, "aws_secret_access_key", None):
        from ..utils.launch import prepare_sagemager_args_inputs

        prepare_sagemager_args_inputs(None, args)  # documented out-of-scope error
    _warn_unsupported(args)
    cfg = load_config(args.config_file)
    merged = _merge(args, cfg)
    if args.num_processes:
        merged["num_processes"] = args.num_processes

    if getattr(args, "dry_run", False):
        import json

        env = build_env(merged, debug=args.debug, cpu=args.cpu)
        contract = {
            k: v
            for k, v in env.items()
            if k.startswith(("ACCELERATE_", "FSDP_", "MEGATRON_LM_", "OMP_", "JAX_"))
        }
        print(json.dumps(contract, indent=2, sort_keys=True))
        return

    if args.debug_cpu and args.debug_cpu > 1:
        return _debug_cpu_launch(args, merged)

    env = build_env(merged, debug=args.debug, cpu=args.cpu)
    cmd = _script_cmd(args)
    result = subprocess.run(cmd, env=env)
    if result.returncode != 0:
        raise SystemExit(result.returncode)


def _debug_cpu_launch(args, merged):
    """N localhost CPU workers forming a real jax.distributed cluster, run
    under the :class:`~accelerate_tpu.launchers.FleetSupervisor`: a worker
    that dies or wedges no longer leaves its siblings hung in their next
    collective — the fleet is torn down within a bounded grace window (and
    with ``--elastic`` relaunched at the reduced world size).  The supervisor
    owns the coordinator port (fresh per attempt), so workers see a
    consistent address and retry the connect with backoff."""
    import tempfile

    from ..launchers import FleetSupervisor

    n = args.debug_cpu
    merged = dict(merged)
    merged["num_machines"] = n
    merged["main_process_ip"] = "127.0.0.1"
    merged["num_processes"] = n
    cmd = _script_cmd(args)
    telemetry_dir = os.environ.get("ACCELERATE_TPU_TELEMETRY_DIR") or os.environ.get(
        "ACCELERATE_TPU_FLIGHTREC_DIR"
    )

    def spawn(rank, world, overrides):
        merged["machine_rank"] = rank
        merged["num_machines"] = world
        merged["num_processes"] = world
        # Any port works here — the supervisor's coordinator address override
        # below is what the workers actually dial.
        merged["main_process_port"] = 0
        env = build_env(merged, debug=args.debug, cpu=True)
        env["XLA_FLAGS"] = env.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count=8", ""
        )
        env.update(overrides)
        return subprocess.Popen(cmd, env=env)

    supervisor = FleetSupervisor(
        spawn,
        n,
        workdir=tempfile.mkdtemp(prefix="atpu_fleet_"),
        heartbeat_timeout_s=(
            args.heartbeat_timeout if args.heartbeat_timeout is not None else 60.0
        ),
        grace_s=args.grace_period if args.grace_period is not None else 10.0,
        elastic=bool(args.elastic),
        telemetry_dir=telemetry_dir,
    )
    result = supervisor.run()
    if result["verdict"] not in ("completed", "drained"):
        last = result["attempts"][-1]
        codes = [c for c in last["exit_codes"].values() if c]
        detail = f"fleet {result['verdict']}"
        if last.get("dead_rank") is not None:
            detail += f" (rank {last['dead_rank']} exited {last['exit_code']})"
        if last.get("wedged_rank") is not None:
            detail += f" (rank {last['wedged_rank']} heartbeat stalled)"
        if result.get("postmortem"):
            detail += f"; postmortem: {result['postmortem']}"
        print(detail, file=sys.stderr)
        raise SystemExit(max(codes) if codes else 1)


def register_subcommand(subparsers):
    launch_command_parser(subparsers)


def main_launch():
    """Entry for the ``accelerate-tpu-launch`` console script."""
    parser = launch_command_parser()
    args = parser.parse_args()
    launch_command(args)


if __name__ == "__main__":
    # ``python -m accelerate_tpu.commands.launch ...`` — without this guard
    # the module imports, does nothing, and exits 0, which reads as a
    # successful (but empty) launch.
    main_launch()
