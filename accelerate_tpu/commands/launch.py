"""``accelerate-tpu launch`` — process fan-out + env contract.

Parity target: reference ``commands/launch.py`` (1202 LoC) + ``utils/launch.py``
(705): merge CLI flags ← config file ← defaults, write the ``ACCELERATE_*`` env
contract, spawn workers.

TPU-native redesign of the fan-out (reference call stack 3.4): JAX wants ONE
process per host, so:

- single host: exec the script in ONE subprocess (the mesh drives all local
  chips) — no torchrun-style N-process spawn;
- multi host (``--num_machines > 1``): this host runs its one worker with
  coordinator env (``ACCELERATE_COORDINATOR_ADDRESS`` = machine 0); the user (or
  ``gcloud``/pod tooling) runs the same command on every host with its
  ``--machine_rank`` — same operational shape as the reference's
  ``tpu_pod_launcher`` ssh fan-out (``commands/launch.py:908``);
- ``--debug_cpu N``: N local CPU processes forming a real jax.distributed
  cluster (the `debug_launcher` path) for laptop/CI testing.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from .config import ClusterConfig, load_config

__all__ = ["launch_command", "launch_command_parser", "register_subcommand"]


def launch_command_parser(subparsers=None):
    if subparsers is not None:
        parser = subparsers.add_parser("launch", help="Launch a training script on TPU hosts")
    else:
        parser = argparse.ArgumentParser("accelerate-tpu launch")
    # Hardware / topology
    parser.add_argument("--config_file", default=None)
    parser.add_argument("--num_machines", type=int, default=None, help="Number of hosts")
    parser.add_argument("--machine_rank", type=int, default=None, help="This host's rank")
    parser.add_argument("--main_process_ip", default=None)
    parser.add_argument("--main_process_port", type=int, default=None)
    parser.add_argument("--num_processes", type=int, default=None,
                        help="Total host processes (defaults to num_machines)")
    parser.add_argument("--cpu", action="store_true", help="Force CPU execution")
    parser.add_argument("--debug_cpu", type=int, default=0,
                        help="Spawn N local CPU processes as a simulated cluster")
    # Precision / accumulation
    parser.add_argument("--mixed_precision", default=None, choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--gradient_accumulation_steps", type=int, default=None)
    # Mesh axes
    parser.add_argument("--dp", type=int, default=None)
    parser.add_argument("--fsdp_size", type=int, default=None)
    parser.add_argument("--tp_size", type=int, default=None)
    parser.add_argument("--sp_size", type=int, default=None)
    parser.add_argument("--pp_size", type=int, default=None)
    parser.add_argument("--ep_size", type=int, default=None)
    # FSDP strategy
    parser.add_argument("--use_fsdp", action="store_true", default=None)
    parser.add_argument("--fsdp_sharding_strategy", default=None)
    parser.add_argument("--fsdp_min_num_params", type=int, default=None)
    parser.add_argument("--deepspeed_config_file", default=None,
                        help="ds_config.json consumed as a config dialect")
    parser.add_argument("--fsdp_cpu_offload", action="store_true", default=None)
    # Misc
    parser.add_argument("--debug", action="store_true", help="ACCELERATE_DEBUG_MODE=1")
    parser.add_argument("-m", "--module", action="store_true",
                        help="Run the training script as a python module (python -m)")
    parser.add_argument("--no_python", action="store_true",
                        help="Execute the script directly (it is not a python file)")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    parser.set_defaults(func=launch_command)
    return parser


def _merge(args, cfg: ClusterConfig):
    """CLI flags override config file (reference ``_validate_launch_command``
    ``commands/launch.py:987-1166``)."""
    def pick(cli, conf):
        return cli if cli is not None else conf

    merged = {
        "num_machines": pick(args.num_machines, cfg.num_machines),
        "machine_rank": pick(args.machine_rank, cfg.machine_rank),
        "main_process_ip": pick(args.main_process_ip, cfg.main_process_ip),
        "main_process_port": pick(args.main_process_port, cfg.main_process_port),
        "mixed_precision": pick(args.mixed_precision, cfg.mixed_precision),
        "gradient_accumulation_steps": pick(
            args.gradient_accumulation_steps, cfg.gradient_accumulation_steps
        ),
        "dp": pick(args.dp, cfg.dp),
        "fsdp": pick(args.fsdp_size, cfg.fsdp),
        "tp": pick(args.tp_size, cfg.tp),
        "sp": pick(args.sp_size, cfg.sp),
        "pp": pick(args.pp_size, cfg.pp),
        "ep": pick(args.ep_size, cfg.ep),
        "use_fsdp": pick(args.use_fsdp, cfg.use_fsdp),
        "fsdp_sharding_strategy": pick(args.fsdp_sharding_strategy, cfg.fsdp_sharding_strategy),
        "fsdp_min_num_params": pick(args.fsdp_min_num_params, cfg.fsdp_min_num_params),
        "deepspeed_config_file": pick(
            getattr(args, "deepspeed_config_file", None), cfg.deepspeed_config_file
        ),
    }
    return merged


def build_env(merged: dict, debug: bool = False, cpu: bool = False) -> dict:
    """The env contract every worker reads (reference ``utils/launch.py:98-326``)."""
    env = dict(os.environ)
    env["ACCELERATE_MIXED_PRECISION"] = str(merged["mixed_precision"])
    env["ACCELERATE_GRADIENT_ACCUMULATION_STEPS"] = str(merged["gradient_accumulation_steps"])
    for axis in ("dp", "fsdp", "tp", "sp", "pp", "ep"):
        size = merged[axis]
        if size and size > 1:
            env[f"ACCELERATE_PARALLELISM_{axis.upper()}"] = str(size)
    if merged["use_fsdp"]:
        env["ACCELERATE_USE_FSDP"] = "1"
        env["FSDP_SHARDING_STRATEGY"] = str(merged["fsdp_sharding_strategy"])
        env["FSDP_MIN_NUM_PARAMS"] = str(merged["fsdp_min_num_params"])
    if merged.get("deepspeed_config_file"):
        env["ACCELERATE_USE_DEEPSPEED"] = "true"
        env["ACCELERATE_DEEPSPEED_CONFIG_FILE"] = str(merged["deepspeed_config_file"])
    if debug:
        env["ACCELERATE_DEBUG_MODE"] = "1"
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
    nm = merged["num_machines"]
    if nm and nm > 1:
        ip = merged["main_process_ip"] or "127.0.0.1"
        port = merged["main_process_port"] or 29500
        env["ACCELERATE_COORDINATOR_ADDRESS"] = f"{ip}:{port}"
        env["ACCELERATE_NUM_PROCESSES"] = str(merged.get("num_processes") or nm)
        env["ACCELERATE_PROCESS_ID"] = str(merged["machine_rank"])
    return env


def _script_cmd(args) -> list:
    if getattr(args, "module", False) and getattr(args, "no_python", False):
        raise SystemExit("--module and --no_python cannot be used together.")
    if getattr(args, "no_python", False):
        return [args.training_script] + list(args.training_script_args)
    base = [sys.executable]
    if getattr(args, "module", False):
        base.append("-m")
    return base + [args.training_script] + list(args.training_script_args)


def launch_command(args):
    cfg = load_config(args.config_file)
    merged = _merge(args, cfg)
    if args.num_processes:
        merged["num_processes"] = args.num_processes

    if args.debug_cpu and args.debug_cpu > 1:
        return _debug_cpu_launch(args, merged)

    env = build_env(merged, debug=args.debug, cpu=args.cpu)
    cmd = _script_cmd(args)
    result = subprocess.run(cmd, env=env)
    if result.returncode != 0:
        raise SystemExit(result.returncode)


def _debug_cpu_launch(args, merged):
    """N localhost CPU workers forming a real jax.distributed cluster."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    n = args.debug_cpu
    merged = dict(merged)
    merged["num_machines"] = n
    merged["main_process_ip"] = "127.0.0.1"
    merged["main_process_port"] = port
    merged["num_processes"] = n
    procs = []
    for rank in range(n):
        merged["machine_rank"] = rank
        env = build_env(merged, debug=args.debug, cpu=True)
        env["XLA_FLAGS"] = env.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count=8", ""
        )
        cmd = _script_cmd(args)
        procs.append(subprocess.Popen(cmd, env=env))
    codes = [p.wait() for p in procs]
    if any(codes):
        raise SystemExit(max(codes))


def register_subcommand(subparsers):
    launch_command_parser(subparsers)


def main_launch():
    """Entry for the ``accelerate-tpu-launch`` console script."""
    parser = launch_command_parser()
    args = parser.parse_args()
    launch_command(args)
