"""``accelerate-tpu test`` — run the bundled smoke-test payload through the
launcher (parity: reference ``commands/test.py``, 66 LoC)."""

from __future__ import annotations

import os
import subprocess
import sys


def test_command(args):
    import accelerate_tpu.test_utils.scripts.test_script as payload

    script = payload.__file__
    cmd = [sys.executable, script]
    env = dict(os.environ)
    # Make the package importable in the child even when running from a source
    # checkout (not pip-installed).
    import accelerate_tpu

    pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(accelerate_tpu.__file__)))
    env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
    if getattr(args, "config_file", None):
        env["ACCELERATE_TEST_CONFIG_FILE"] = args.config_file
    print("Running:  python " + script)
    result = subprocess.run(cmd, env=env)
    if result.returncode == 0:
        print("Test is a success! You are ready for your distributed training!")
    else:
        raise SystemExit(result.returncode)


def register_subcommand(subparsers):
    parser = subparsers.add_parser("test", help="Run the bundled sanity test")
    parser.add_argument("--config_file", default=None)
    parser.set_defaults(func=test_command)
