"""``accelerate-tpu tpu-config`` — fan out setup commands to TPU pod VMs.

Parity target: reference ``commands/tpu.py`` (157 LoC): wraps
``gcloud compute tpus tpu-vm ssh --worker=all --command=...`` to install
dependencies / run setup on every worker of a pod slice.
"""

from __future__ import annotations

import os
import shutil
import subprocess

from .config import DEFAULT_CONFIG_FILE, load_config

__all__ = ["register_subcommand", "tpu_command"]

_DESCRIPTION = "Run commands on a TPU pod's VMs (gcloud ssh fan-out)"


def register_subcommand(subparsers):
    parser = subparsers.add_parser("tpu-config", description=_DESCRIPTION, help=_DESCRIPTION)
    parser.add_argument("--config_file", type=str, default=None, help="Config yaml to read TPU name/zone from.")
    parser.add_argument("--tpu_name", type=str, default=None, help="TPU name (overrides config).")
    parser.add_argument("--tpu_zone", type=str, default=None, help="TPU zone (overrides config).")
    parser.add_argument("--command", action="append", help="Command to run on each worker (repeatable).")
    parser.add_argument(
        "--command_file", type=str, default=None, help="File with one command per line."
    )
    parser.add_argument(
        "--install_accelerate",
        action="store_true",
        help="Prepend installation of this package on each worker.",
    )
    parser.add_argument(
        "--accelerate_version",
        type=str,
        default="latest",
        help="Version to install with --install_accelerate.",
    )
    parser.add_argument("--debug", action="store_true", help="Print the gcloud command instead of running it.")
    parser.set_defaults(func=tpu_command)


def tpu_command(args):
    cfg = {}
    path = args.config_file or DEFAULT_CONFIG_FILE
    if os.path.isfile(path):
        cfg = load_config(path).__dict__
    tpu_name = args.tpu_name or cfg.get("tpu_name")
    tpu_zone = args.tpu_zone or cfg.get("tpu_zone")
    if not tpu_name or not tpu_zone:
        raise ValueError("Pass --tpu_name and --tpu_zone (or set them in the config file).")

    commands = []
    if args.command_file:
        with open(args.command_file) as f:
            commands.extend(line.strip() for line in f if line.strip())
    if args.command:
        commands.extend(args.command)
    if args.install_accelerate:
        version = (
            "accelerate-tpu"
            if args.accelerate_version == "latest"
            else f"accelerate-tpu=={args.accelerate_version}"
        )
        commands.insert(0, f"pip install {version}")
    if not commands:
        raise ValueError("Nothing to run: pass --command/--command_file/--install_accelerate.")

    # One ssh session, commands joined — exactly the reference's fan-out shape
    # (reference commands/tpu.py builds the same gcloud invocation).
    joined = "; ".join(commands)
    gcloud = [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu_name,
        "--zone", tpu_zone, "--command", joined, "--worker", "all",
    ]
    if args.debug:
        import shlex

        print(shlex.join(gcloud))
        return
    if shutil.which("gcloud") is None:
        raise RuntimeError(
            "gcloud CLI not found — install the Google Cloud SDK, or use --debug to "
            "print the command for manual execution."
        )
    print(f"Running {joined!r} on every worker of {tpu_name}...")
    subprocess.run(gcloud, check=True)
    print("Done.")
