"""Arrow-key selection menu for the config questionnaire.

Parity target: reference ``commands/menu/`` (cursor.py/input.py/keymap.py/
selection_menu.py, ~277 LoC): a BulletMenu the questionnaire uses for every
multiple-choice question.  Same UX here — up/down (or j/k) to move, enter to
pick — in one module: raw-mode key reading via termios, cursor repositioning
via ANSI escapes.  When stdin is not a TTY (tests, CI, piped input) the menu
falls back to a numbered prompt read with ``input()``, which is what makes
every flow drivable by answer injection.
"""

from __future__ import annotations

import sys

__all__ = ["BulletMenu"]


def _read_key() -> str:
    """One keypress from raw stdin; arrows normalize to 'up'/'down'.

    ESC handling must not block or leak bytes: a bare Escape press has no
    tail, and CSI sequences vary in length (arrows send ``[A``, Home/End/
    PgUp send e.g. ``[1~``) — so the tail is read with a short ``select``
    timeout and drained to the CSI final byte (0x40-0x7e) instead of a fixed
    2-byte read, which would hang on bare ESC and leave ``~`` in the stream
    to be misread as a command."""
    import os as _os
    import select
    import termios
    import tty

    fd = sys.stdin.fileno()
    old = termios.tcgetattr(fd)

    # All IO happens at the fd level (os.read): sys.stdin is a buffered
    # TextIOWrapper, so mixing sys.stdin.read with select() on the fd would
    # see an empty fd while bytes sit in Python's buffer — every arrow key
    # would misread as 'esc'.
    def _pending(timeout: float = 0.05) -> bool:
        return bool(select.select([fd], [], [], timeout)[0])

    def _read1() -> str:
        return _os.read(fd, 1).decode("latin-1")

    try:
        tty.setraw(fd)
        ch = _read1()
        if ch == "\x1b":  # escape (possibly the start of a CSI sequence)
            if not _pending():
                return "esc"  # bare Escape keypress
            tail = _read1()
            if tail != "[":
                # SS3 (ESC O <final>, keypad/application mode) and alt-<key>
                # sequences: drain any pending tail bytes so they are not
                # re-read as commands, then treat as esc.
                while _pending(0.01):
                    _read1()
                return "esc"
            # CSI: parameter bytes 0x30-0x3f and intermediates 0x20-0x2f,
            # then one final byte 0x40-0x7e terminates the sequence.
            seq = ""
            while _pending():
                b = _read1()
                seq += b
                if "\x40" <= b <= "\x7e":
                    break
            if seq == "A":
                return "up"
            if seq == "B":
                return "down"
            return "esc"
        return ch
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, old)


class BulletMenu:
    """``BulletMenu(prompt, choices).run(default) -> index``."""

    def __init__(self, prompt: str, choices: list):
        self.prompt = prompt
        self.choices = [str(c) for c in choices]

    def _interactive(self, default: int) -> int:
        n = len(self.choices)
        pos = default
        print(self.prompt)
        for i, c in enumerate(self.choices):
            print(("➔  " if i == pos else "   ") + c)
        while True:
            key = _read_key()
            if key in ("up", "k"):
                pos = (pos - 1) % n
            elif key in ("down", "j"):
                pos = (pos + 1) % n
            elif key in ("\r", "\n"):
                # Clear the menu so the questionnaire reads linearly after.
                sys.stdout.write(f"\x1b[{n + 1}A\x1b[J")
                print(f"{self.prompt} {self.choices[pos]}")
                return pos
            elif key.isdigit() and int(key) < n:
                pos = int(key)
            elif key in ("\x03", "q"):  # Ctrl-C
                raise KeyboardInterrupt
            else:
                continue
            sys.stdout.write(f"\x1b[{n}A")
            for i, c in enumerate(self.choices):
                sys.stdout.write("\x1b[2K" + ("➔  " if i == pos else "   ") + c + "\n")
            sys.stdout.flush()

    def _numbered(self, default: int) -> int:
        print(self.prompt)
        for i, c in enumerate(self.choices):
            print(f"  [{i}] {c}")
        while True:
            raw = input(f"Choice (0-{len(self.choices) - 1}) [{default}]: ").strip()
            if not raw:
                return default
            try:
                idx = int(raw)
            except ValueError:
                print("Please enter a number.")
                continue
            if 0 <= idx < len(self.choices):
                return idx
            print(f"Out of range 0-{len(self.choices) - 1}.")

    def run(self, default: int = 0) -> int:
        if sys.stdin.isatty() and sys.stdout.isatty():
            try:
                return self._interactive(default)
            except (ImportError, OSError):
                pass  # no termios (or raw mode refused): numbered fallback
        return self._numbered(default)
