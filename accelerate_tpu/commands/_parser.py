"""Argument-parser base shared by every CLI subcommand.

Reference parity (``commands/utils.py CustomArgumentParser``): every
``--foo_bar`` flag is also accepted as ``--foo-bar`` — done here by
registering the hyphen spelling as a real argparse alias at ``add_argument``
time (argparse derives ``dest`` from the first long option, so the underscore
form stays canonical).  Positional arguments and the user script's own args
(``argparse.REMAINDER``) are untouched.
"""

from __future__ import annotations

import argparse

__all__ = ["DualDashParser"]


class DualDashParser(argparse.ArgumentParser):
    def __init__(self, *args, **kwargs):
        # Prefix abbreviation would make every underscore flag ambiguous with
        # its own hyphen alias ("--config" vs --config_file/--config-file);
        # the root accelerate-tpu parser already disables it.
        kwargs.setdefault("allow_abbrev", False)
        super().__init__(*args, **kwargs)

    def add_argument(self, *names, **kwargs):
        expanded = []
        for n in names:
            expanded.append(n)
            if isinstance(n, str) and n.startswith("--") and "_" in n[2:]:
                alias = "--" + n[2:].replace("_", "-")
                if alias not in expanded:
                    expanded.append(alias)
        return super().add_argument(*expanded, **kwargs)
