"""``accelerate-tpu from-accelerate`` — migrate an HF Accelerate config YAML.

The analog of the reference's ``accelerate to-fsdp2`` migrator
(``commands/to_fsdp2.py``, 172 LoC): reads a reference
``default_config.yaml`` and writes our ``ClusterConfig``, mapping each engine
choice onto the GSPMD mesh —

- MULTI_GPU / MULTI_CPU / MULTI_XPU etc.  -> plain dp (all devices)
- FSDP (v1 or v2) + fsdp_config          -> fsdp axis + sharding strategy
- DEEPSPEED + zero stage                 -> fsdp axis (stage>=1 shards)
- MEGATRON_LM + tp/pp degrees            -> tp/pp axes
- TP (torch tensor parallel)             -> tp axis
- mixed_precision / gradient accumulation carried over verbatim
"""

from __future__ import annotations


import yaml

from .config import ClusterConfig, save_config

__all__ = ["register_subcommand", "from_accelerate_command", "convert_config"]

_DESCRIPTION = "Convert an HF Accelerate config yaml to an accelerate-tpu config"


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "from-accelerate", description=_DESCRIPTION, help=_DESCRIPTION
    )
    parser.add_argument("config_file", type=str, help="Path to the reference accelerate yaml")
    parser.add_argument("--output_file", type=str, default=None, help="Where to write ours")
    parser.add_argument(
        "--overwrite", action="store_true", help="Allow overwriting the output file"
    )
    parser.set_defaults(func=from_accelerate_command)


def convert_config(src: dict) -> ClusterConfig:
    cfg = ClusterConfig()
    dist = str(src.get("distributed_type", "NO")).upper()
    cfg.mixed_precision = str(src.get("mixed_precision", "no"))
    cfg.num_machines = int(src.get("num_machines", 1))
    cfg.machine_rank = int(src.get("machine_rank", 0))
    cfg.main_process_ip = src.get("main_process_ip")
    port = src.get("main_process_port")
    cfg.main_process_port = int(port) if port not in (None, "") else None
    cfg.gradient_accumulation_steps = int(src.get("gradient_accumulation_steps", 1))

    def _truthy(v) -> bool:
        # Single boolean-string domain with the rest of the codebase
        # (launcher _flag_bool / questionnaire _yes_no use the same parser).
        from ..utils.environment import str_to_bool

        try:
            return bool(str_to_bool(str(v)))
        except ValueError:
            return False

    # Dynamo config carries over verbatim (inert on the native path, consumed
    # by torch-bridge ingestion via ACCELERATE_DYNAMO_*).
    dyn = src.get("dynamo_config", {}) or {}
    if dyn.get("dynamo_backend"):
        cfg.dynamo_backend = str(dyn["dynamo_backend"]).lower()
    if dyn.get("dynamo_mode"):
        cfg.dynamo_mode = str(dyn["dynamo_mode"])
    if dyn.get("dynamo_use_fullgraph") is not None:
        cfg.dynamo_use_fullgraph = _truthy(dyn["dynamo_use_fullgraph"])
    if dyn.get("dynamo_use_dynamic") is not None:
        cfg.dynamo_use_dynamic = _truthy(dyn["dynamo_use_dynamic"])

    if dist in ("FSDP",):
        cfg.use_fsdp = True
        cfg.fsdp = 0  # all devices
        fsdp_cfg = src.get("fsdp_config", {}) or {}
        strategy = str(
            fsdp_cfg.get("fsdp_sharding_strategy", fsdp_cfg.get("sharding_strategy", "FULL_SHARD"))
        ).upper()
        int_map = {"1": "FULL_SHARD", "2": "SHARD_GRAD_OP", "3": "NO_SHARD", "4": "HYBRID_SHARD"}
        cfg.fsdp_sharding_strategy = int_map.get(strategy, strategy)
        cfg.fsdp_min_num_params = int(fsdp_cfg.get("fsdp_min_num_params", 0))
        # FSDP2 spelling: reshard_after_forward replaces the strategy enum.
        if fsdp_cfg.get("fsdp_reshard_after_forward") is not None:
            raf = fsdp_cfg["fsdp_reshard_after_forward"]
            if str(raf).upper() in ("TRUE", "FALSE", "1", "0", "YES", "NO"):
                cfg.fsdp_reshard_after_forward = _truthy(raf)
                cfg.fsdp_sharding_strategy = (
                    "FULL_SHARD" if cfg.fsdp_reshard_after_forward else "SHARD_GRAD_OP"
                )
        if fsdp_cfg.get("fsdp_version"):
            cfg.fsdp_version = int(fsdp_cfg["fsdp_version"])
        if fsdp_cfg.get("fsdp_offload_params") is not None:
            cfg.fsdp_cpu_offload = _truthy(fsdp_cfg["fsdp_offload_params"])
        if fsdp_cfg.get("fsdp_auto_wrap_policy"):
            cfg.fsdp_auto_wrap_policy = str(fsdp_cfg["fsdp_auto_wrap_policy"])
        if fsdp_cfg.get("fsdp_transformer_layer_cls_to_wrap"):
            cfg.fsdp_transformer_layer_cls_to_wrap = str(
                fsdp_cfg["fsdp_transformer_layer_cls_to_wrap"]
            )
        if fsdp_cfg.get("fsdp_state_dict_type"):
            cfg.fsdp_state_dict_type = str(fsdp_cfg["fsdp_state_dict_type"]).upper()
        if fsdp_cfg.get("fsdp_activation_checkpointing") is not None:
            cfg.fsdp_activation_checkpointing = _truthy(fsdp_cfg["fsdp_activation_checkpointing"])
    elif dist == "DEEPSPEED":
        ds_cfg = src.get("deepspeed_config", {}) or {}
        stage = int(ds_cfg.get("zero_stage", 2))
        cfg.use_deepspeed = True
        cfg.zero_stage = stage
        cfg.use_fsdp = stage >= 1
        cfg.fsdp = 0 if stage >= 1 else 1
        cfg.fsdp_sharding_strategy = "FULL_SHARD" if stage == 3 else "SHARD_GRAD_OP"
        if ds_cfg.get("gradient_accumulation_steps"):
            cfg.gradient_accumulation_steps = int(ds_cfg["gradient_accumulation_steps"])
        if ds_cfg.get("deepspeed_config_file"):
            # A full ds_config.json keeps flowing through the dialect
            # (utils/deepspeed.py consumes it at prepare time).
            cfg.deepspeed_config_file = str(ds_cfg["deepspeed_config_file"])
        for key in ("offload_optimizer_device", "offload_param_device"):
            if ds_cfg.get(key) not in (None, ""):
                setattr(cfg, key, str(ds_cfg[key]))
        if ds_cfg.get("gradient_clipping") not in (None, "", "none"):
            cfg.gradient_clipping = float(ds_cfg["gradient_clipping"])
        if ds_cfg.get("zero3_init_flag") is not None:
            cfg.zero3_init_flag = _truthy(ds_cfg["zero3_init_flag"])
        if ds_cfg.get("zero3_save_16bit_model") is not None:
            cfg.zero3_save_16bit_model = _truthy(ds_cfg["zero3_save_16bit_model"])
        if ds_cfg.get("deepspeed_moe_layer_cls_names"):
            cfg.deepspeed_moe_layer_cls_names = str(ds_cfg["deepspeed_moe_layer_cls_names"])
    elif dist == "MEGATRON_LM":
        mlm = src.get("megatron_lm_config", {}) or {}
        cfg.use_megatron_lm = True
        cfg.tp = cfg.megatron_lm_tp_degree = int(mlm.get("megatron_lm_tp_degree", 1))
        cfg.pp = cfg.megatron_lm_pp_degree = int(mlm.get("megatron_lm_pp_degree", 1))
        if mlm.get("megatron_lm_num_micro_batches") is not None:
            cfg.megatron_lm_num_micro_batches = int(mlm["megatron_lm_num_micro_batches"])
        if mlm.get("megatron_lm_sequence_parallelism") is not None:
            cfg.megatron_lm_sequence_parallelism = _truthy(mlm["megatron_lm_sequence_parallelism"])
        if mlm.get("megatron_lm_recompute_activations") is not None:
            cfg.megatron_lm_recompute_activations = _truthy(mlm["megatron_lm_recompute_activations"])
        if mlm.get("megatron_lm_gradient_clipping") not in (None, "", "none"):
            cfg.megatron_lm_gradient_clipping = float(mlm["megatron_lm_gradient_clipping"])
        if _truthy(mlm.get("megatron_lm_use_distributed_optimizer", "")):
            cfg.megatron_lm_use_distributed_optimizer = True
            cfg.use_fsdp = True
            cfg.fsdp = 0
            cfg.fsdp_sharding_strategy = "SHARD_GRAD_OP"
    elif dist == "TP":
        tp_cfg = src.get("tp_config", {}) or {}
        cfg.tp = int(tp_cfg.get("tp_size", 1))
    elif dist in ("XLA", "TPU"):
        # Reference TPU config: downcast_bf16/XLA_USE_BF16 become the explicit
        # bf16 policy; the mesh covers all chips (dp auto).
        if str(src.get("downcast_bf16", "")).lower() in ("1", "true", "yes"):
            cfg.downcast_bf16 = True
            if cfg.mixed_precision in ("no", "None"):
                cfg.mixed_precision = "bf16"
        if src.get("tpu_name"):
            cfg.tpu_name = str(src["tpu_name"])
        if src.get("tpu_zone"):
            cfg.tpu_zone = str(src["tpu_zone"])
    # Everything else (NO/MULTI_GPU/MULTI_CPU/...) -> dp over all devices.
    return cfg


def from_accelerate_command(args):
    with open(args.config_file) as f:
        src = yaml.safe_load(f) or {}
    cfg = convert_config(src)
    out = args.output_file
    if out is None:
        out = args.config_file.replace(".yaml", ".tpu.yaml").replace(".yml", ".tpu.yml")
        if out == args.config_file:
            out = args.config_file + ".tpu"
    import os

    if os.path.exists(out) and not args.overwrite:
        raise FileExistsError(f"{out} exists; pass --overwrite to replace it.")
    path = save_config(cfg, out)
    print(f"Converted {args.config_file} -> {path}")
