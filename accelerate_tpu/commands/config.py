"""``accelerate-tpu config`` — write/load the default YAML config.

Parity target: reference ``commands/config/`` (~1800 LoC questionnaire + YAML).
Round 1 ships the YAML schema + non-interactive ``default`` + a compact
questionnaire; the config file feeds ``launch`` exactly like the reference's.
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass
from typing import Optional

import yaml

DEFAULT_CONFIG_DIR = os.path.expanduser(
    os.environ.get("ACCELERATE_CONFIG_DIR", "~/.cache/accelerate_tpu")
)
DEFAULT_CONFIG_FILE = os.path.join(DEFAULT_CONFIG_DIR, "default_config.yaml")

__all__ = ["ClusterConfig", "load_config", "save_config", "config_command", "default_config_command"]


@dataclass
class ClusterConfig:
    compute_environment: str = "LOCAL_MACHINE"
    distributed_type: str = "TPU_JAX"
    mixed_precision: str = "no"
    num_machines: int = 1
    machine_rank: int = 0
    main_process_ip: Optional[str] = None
    main_process_port: Optional[int] = None
    gradient_accumulation_steps: int = 1
    # Mesh axes (ParallelismConfig)
    dp: int = 0  # 0 = auto (all remaining devices)
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1
    use_fsdp: bool = False
    fsdp_sharding_strategy: str = "FULL_SHARD"
    fsdp_min_num_params: int = 0
    # Guided-flow FSDP fields (reference cluster.py:383-503 question set); all
    # flow to workers through the FSDP_* env contract in commands/launch.py.
    fsdp_version: Optional[int] = None
    fsdp_reshard_after_forward: Optional[bool] = None
    fsdp_cpu_offload: Optional[bool] = None
    fsdp_auto_wrap_policy: Optional[str] = None
    fsdp_transformer_layer_cls_to_wrap: Optional[str] = None
    fsdp_state_dict_type: Optional[str] = None
    fsdp_activation_checkpointing: Optional[bool] = None
    # DeepSpeed dialect (reference cluster.py:228-380): either a full
    # ds_config.json consumed at prepare time (utils/deepspeed.py; flows via
    # ACCELERATE_DEEPSPEED_CONFIG_FILE) or the guided zero-stage fields.
    use_deepspeed: Optional[bool] = None
    deepspeed_config_file: Optional[str] = None
    zero_stage: Optional[int] = None
    offload_optimizer_device: Optional[str] = None
    offload_param_device: Optional[str] = None
    gradient_clipping: Optional[float] = None
    zero3_init_flag: Optional[bool] = None
    zero3_save_16bit_model: Optional[bool] = None
    deepspeed_moe_layer_cls_names: Optional[str] = None
    # Megatron dialect (reference cluster.py:505-560): degrees map onto the
    # tp/pp mesh axes; the rest rides the MEGATRON_LM_* env contract.
    use_megatron_lm: Optional[bool] = None
    megatron_lm_tp_degree: Optional[int] = None
    megatron_lm_pp_degree: Optional[int] = None
    megatron_lm_num_micro_batches: Optional[int] = None
    megatron_lm_sequence_parallelism: Optional[bool] = None
    megatron_lm_recompute_activations: Optional[bool] = None
    megatron_lm_use_distributed_optimizer: Optional[bool] = None
    megatron_lm_gradient_clipping: Optional[float] = None
    # Dynamo (reference cluster.py:171-207).  torch.compile has no role on the
    # native TPU path (the whole step is XLA-compiled); the fields are kept for
    # torch-bridge ingestion and flow via ACCELERATE_DYNAMO_*.
    dynamo_backend: Optional[str] = None
    dynamo_mode: Optional[str] = None
    dynamo_use_fullgraph: Optional[bool] = None
    dynamo_use_dynamic: Optional[bool] = None
    # Sequence-parallel attention implementation ("ring" | "ulysses").
    sp_impl: Optional[str] = None
    downcast_bf16: bool = False
    # Pod management (consumed by `accelerate-tpu tpu-config`).
    tpu_name: Optional[str] = None
    tpu_zone: Optional[str] = None

    def to_dict(self) -> dict:
        return asdict(self)


def save_config(config: ClusterConfig, path: str = DEFAULT_CONFIG_FILE) -> str:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(config.to_dict(), f)
    return path


def load_config(path: Optional[str] = None) -> ClusterConfig:
    path = path or DEFAULT_CONFIG_FILE
    if not os.path.exists(path):
        return ClusterConfig()
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    known = {k: v for k, v in data.items() if k in ClusterConfig.__dataclass_fields__}
    return ClusterConfig(**known)


def _ask_field(prompt: str, default=None, cast=str, check=None, error: str = "Invalid value."):
    """Ask until ``cast`` succeeds and ``check`` (if given) passes — the
    reference questionnaire's ``_ask_field`` retry contract
    (``commands/config/config_utils.py``)."""
    suffix = f" [{default}]: " if default is not None else ": "
    while True:
        raw = input(f"{prompt}{suffix}").strip()
        if not raw:
            if default is not None:
                return default
            print(error)
            continue
        try:
            value = cast(raw)
        except (ValueError, TypeError):
            print(error)
            continue
        if check is not None and not check(value):
            print(error)
            continue
        return value


def _yes_no(prompt: str, default: bool = False) -> bool:
    from ..utils.environment import str_to_bool

    def cast(raw):
        return bool(str_to_bool(str(raw)))

    hint = "[YES/no]" if default else "[yes/NO]"
    return _ask_field(
        f"{prompt} {hint}", default=default, cast=cast, error="Please answer yes or no."
    )


def _choose(prompt: str, choices: list, default: int = 0) -> str:
    from .menu import BulletMenu

    return choices[BulletMenu(prompt, choices).run(default)]


def _machine_questions(cfg: ClusterConfig):
    cfg.num_machines = _ask_field(
        "How many machines (TPU hosts) will you use (more than 1 for multi-host training)?",
        1, int, check=lambda v: v >= 1,
    )
    if cfg.num_machines > 1:
        cfg.machine_rank = _ask_field("What is the rank of this machine?", 0, int)
        cfg.main_process_ip = _ask_field(
            "What is the IP address of the machine that will host the main process (the "
            "jax.distributed coordinator)?", "127.0.0.1",
        )
        cfg.main_process_port = _ask_field(
            "What is the port you will use to communicate with the main process?", 29500, int
        )
        if _yes_no("Is this a GCP TPU pod (managed with `accelerate-tpu tpu-config`)?"):
            cfg.tpu_name = _ask_field("What is the name of the TPU pod?", "tpu-pod")
            cfg.tpu_zone = _ask_field("What zone is the TPU pod in?", "us-central2-b")


def _dynamo_questions(cfg: ClusterConfig):
    if not _yes_no(
        "Do you wish to configure torch dynamo (only affects torch-bridge ingestion; "
        "the native JAX path is already XLA-compiled)?"
    ):
        return
    cfg.dynamo_backend = _choose(
        "Which dynamo backend would you like to use?",
        ["no", "eager", "aot_eager", "inductor", "aot_ts_nvfuser", "nvprims_nvfuser",
         "cudagraphs", "ofi", "fx2trt", "onnxrt", "tensorrt", "ipex", "tvm"],
        default=2,
    )
    if cfg.dynamo_backend != "no" and _yes_no(
        "Do you want to customize the defaults sent to torch.compile?"
    ):
        cfg.dynamo_mode = _choose(
            "Which mode do you want to use?",
            ["default", "reduce-overhead", "max-autotune"],
        )
        cfg.dynamo_use_fullgraph = _yes_no(
            "Do you want the fullgraph mode or is it ok to break the model into several subgraphs?"
        )
        cfg.dynamo_use_dynamic = _yes_no("Do you want to enable dynamic shape tracing?")


def _deepspeed_questions(cfg: ClusterConfig) -> bool:
    """Returns True when the guided flow already asked for gradient
    accumulation (so the closing question is skipped)."""
    cfg.use_deepspeed = True
    asked_accum = False
    if _yes_no("Do you want to specify a json file to a DeepSpeed config?"):
        cfg.deepspeed_config_file = _ask_field(
            "Please enter the path to the json DeepSpeed config file", "ds_config.json"
        )
    else:
        cfg.zero_stage = int(
            _choose("What should be your DeepSpeed's ZeRO optimization stage?",
                    ["0", "1", "2", "3"], default=2)
        )
        if cfg.zero_stage >= 2:
            cfg.offload_optimizer_device = _choose(
                "Where to offload optimizer states?", ["none", "cpu", "nvme"]
            )
        if cfg.zero_stage == 3:
            cfg.offload_param_device = _choose(
                "Where to offload parameters?", ["none", "cpu", "nvme"]
            )
            cfg.zero3_init_flag = _yes_no(
                "Do you want to enable deepspeed.zero.Init for constructing massive models?"
            )
            cfg.zero3_save_16bit_model = _yes_no(
                "Do you want to save 16-bit model weights when using ZeRO Stage-3?"
            )
        cfg.gradient_accumulation_steps = _ask_field(
            "How many gradient accumulation steps are you passing in your script?", 1, int
        )
        asked_accum = True
        if _yes_no("Do you want to use gradient clipping?"):
            cfg.gradient_clipping = _ask_field("What is the gradient clipping value?", 1.0, float)
    if _yes_no("Do you want to enable Mixture-of-Experts training (MoE)?"):
        cfg.deepspeed_moe_layer_cls_names = _ask_field(
            "Specify the comma-separated list of transformer MoE layer class names (case-sensitive)",
            "MixtralSparseMoeBlock",
        )
        cfg.ep = _ask_field("Expert-parallel size (ep mesh axis)?", 1, int, check=lambda v: v >= 1)
    # ZeRO stages map onto the fsdp axis (stage>=1 shards grads/opt, 3 shards params).
    if cfg.zero_stage is not None and cfg.zero_stage >= 1:
        cfg.use_fsdp = True
        cfg.fsdp = 0
        cfg.fsdp_sharding_strategy = "FULL_SHARD" if cfg.zero_stage == 3 else "SHARD_GRAD_OP"
    return asked_accum


def _fsdp_questions(cfg: ClusterConfig):
    cfg.use_fsdp = True
    cfg.fsdp_version = int(_ask_field(
        "What should be your FSDP version?", 2, int, check=lambda v: v in (1, 2),
        error="1 or 2 (both map onto the same GSPMD sharding engine).",
    ))
    if cfg.fsdp_version == 2:
        # FSDP2 spelling (reference cluster.py:392-413): reshard_after_forward
        # REPLACES the strategy enum — asking both would let the launcher's
        # FSDP2 override silently discard the chosen enum.
        cfg.fsdp_reshard_after_forward = _yes_no(
            "Do you want to enable resharding after forward?", default=True
        )
        cfg.fsdp_sharding_strategy = (
            "FULL_SHARD" if cfg.fsdp_reshard_after_forward else "SHARD_GRAD_OP"
        )
    else:
        cfg.fsdp_sharding_strategy = _choose(
            "What should be your sharding strategy?",
            ["FULL_SHARD", "SHARD_GRAD_OP", "NO_SHARD", "HYBRID_SHARD"],
        )
    cfg.fsdp = _ask_field(
        "FSDP axis size (0 = all devices)?", 0, int, check=lambda v: v >= 0
    )
    cfg.fsdp_cpu_offload = _yes_no("Do you want to offload parameters and gradients to CPU?")
    policy = _choose(
        "What should be your auto wrap policy (which arrays stay replicated)?",
        ["TRANSFORMER_BASED_WRAP", "SIZE_BASED_WRAP", "NO_WRAP"],
    )
    cfg.fsdp_auto_wrap_policy = policy
    if policy == "TRANSFORMER_BASED_WRAP":
        cfg.fsdp_transformer_layer_cls_to_wrap = _ask_field(
            "Specify the comma-separated list of transformer layer class names to wrap",
            "LlamaDecoderLayer",
        )
    elif policy == "SIZE_BASED_WRAP":
        cfg.fsdp_min_num_params = _ask_field(
            "What should be your FSDP's minimum number of parameters for default auto wrapping?",
            100000000, int,
        )
    cfg.fsdp_state_dict_type = _choose(
        "What should be your FSDP's state dict type?",
        ["SHARDED_STATE_DICT", "FULL_STATE_DICT"],
    )
    cfg.fsdp_activation_checkpointing = _yes_no(
        "Do you want to enable FSDP activation checkpointing (jax.checkpoint remat)?"
    )


def _megatron_questions(cfg: ClusterConfig):
    cfg.use_megatron_lm = True
    cfg.megatron_lm_tp_degree = _ask_field(
        "What is the Tensor Parallelism degree/size?", 1, int, check=lambda v: v >= 1
    )
    cfg.tp = cfg.megatron_lm_tp_degree
    if cfg.megatron_lm_tp_degree > 1:
        cfg.megatron_lm_sequence_parallelism = _yes_no(
            "Do you want to enable Sequence Parallelism?", default=True
        )
        if cfg.megatron_lm_sequence_parallelism:
            cfg.sp = _ask_field("Sequence-parallel size (sp mesh axis)?", 1, int)
            cfg.sp_impl = _choose("Sequence-parallel attention?", ["ring", "ulysses"])
    cfg.megatron_lm_pp_degree = _ask_field(
        "What is the Pipeline Parallelism degree/size?", 1, int, check=lambda v: v >= 1
    )
    cfg.pp = cfg.megatron_lm_pp_degree
    if cfg.megatron_lm_pp_degree > 1:
        cfg.megatron_lm_num_micro_batches = _ask_field(
            "What is the number of micro-batches?", 1, int, check=lambda v: v >= 1
        )
    cfg.megatron_lm_recompute_activations = _yes_no(
        "Do you want to enable selective activation recomputation?", default=True
    )
    cfg.megatron_lm_use_distributed_optimizer = _yes_no(
        "Do you want to use distributed optimizer which shards optimizer state and "
        "gradients across data-parallel ranks?", default=True,
    )
    if cfg.megatron_lm_use_distributed_optimizer and not cfg.use_fsdp:
        cfg.use_fsdp = True
        cfg.fsdp = 0
        cfg.fsdp_sharding_strategy = "SHARD_GRAD_OP"
    cfg.megatron_lm_gradient_clipping = _ask_field(
        "What is the gradient clipping value based on global L2 norm (0 to disable)?", 1.0, float
    )


def _mesh_questions(cfg: ClusterConfig):
    cfg.tp = _ask_field("Tensor-parallel size (tp mesh axis)?", cfg.tp or 1, int)
    cfg.sp = _ask_field(
        "Sequence-parallel size (ring/ulysses long-context, sp mesh axis)?", cfg.sp or 1, int
    )
    if cfg.sp > 1:
        cfg.sp_impl = _choose("Sequence-parallel attention?", ["ring", "ulysses"])
    cfg.pp = _ask_field("Pipeline-parallel size (pp mesh axis)?", cfg.pp or 1, int)
    cfg.ep = _ask_field("Expert-parallel size (MoE, ep mesh axis)?", cfg.ep or 1, int)


def config_command(args):
    if getattr(args, "default", False):
        return default_config_command(args)
    if getattr(args, "update", False):
        return update_config_command(args)
    cfg = ClusterConfig()
    # Guided flow mirroring the reference questionnaire
    # (commands/config/cluster.py:863 get_cluster_input): machines -> dynamo ->
    # strategy (DeepSpeed | FSDP | Megatron | plain mesh) -> precision.  Every
    # multiple-choice question goes through the BulletMenu (arrow keys on a
    # TTY, numbered prompt otherwise, so tests drive it by answer injection).
    _machine_questions(cfg)
    _dynamo_questions(cfg)
    strategy = _choose(
        "Which distributed training strategy do you want to configure?",
        ["Plain data parallelism / custom mesh", "FSDP (GSPMD sharding)",
         "DeepSpeed dialect", "Megatron-LM dialect"],
    )
    asked_accum = False
    if strategy == "DeepSpeed dialect":
        asked_accum = _deepspeed_questions(cfg)
    elif strategy == "FSDP (GSPMD sharding)":
        _fsdp_questions(cfg)
        _mesh_questions(cfg)
    elif strategy == "Megatron-LM dialect":
        _megatron_questions(cfg)
    else:
        _mesh_questions(cfg)
    cfg.mixed_precision = _choose(
        "Do you wish to use mixed precision?", ["no", "bf16", "fp16", "fp8"], default=1
    )
    if cfg.mixed_precision == "bf16":
        cfg.downcast_bf16 = _yes_no(
            "Do you want pure-bf16 params (downcast_bf16: halves param/grad HBM, no fp32 master)?"
        )
    if not asked_accum:
        cfg.gradient_accumulation_steps = _ask_field(
            "How many gradient accumulation steps?", cfg.gradient_accumulation_steps, int,
            check=lambda v: v >= 1,
        )
    path = save_config(cfg, getattr(args, "config_file", None) or DEFAULT_CONFIG_FILE)
    print(f"Configuration saved to {path}")


def update_config_command(args):
    """Migrate an existing config file to the current schema (reference
    ``commands/config/update.py``): unknown keys drop with a note, missing
    keys fill with defaults, the result is rewritten in place."""
    path = getattr(args, "config_file", None) or DEFAULT_CONFIG_FILE
    if not os.path.exists(path):
        raise SystemExit(f"No config file at {path}; run `accelerate-tpu config` first.")
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    known = set(ClusterConfig.__dataclass_fields__)
    dropped = sorted(k for k in data if k not in known)
    cfg = ClusterConfig(**{k: v for k, v in data.items() if k in known})
    save_config(cfg, path)
    note = f" (dropped unknown keys: {', '.join(dropped)})" if dropped else ""
    print(f"Updated {path} to the current schema{note}")
    return dropped


def default_config_command(args):
    path = save_config(ClusterConfig(), getattr(args, "config_file", None) or DEFAULT_CONFIG_FILE)
    print(f"Default configuration saved to {path}")


def write_basic_config(mixed_precision: str = "no", save_location: str = None) -> str:
    """Programmatic default-config writer (reference
    ``commands/config/default.py:36`` — used by notebooks/CI to skip the
    questionnaire).  Returns the written path."""
    cfg = ClusterConfig(mixed_precision=str(mixed_precision))
    return save_config(cfg, save_location or DEFAULT_CONFIG_FILE)


def register_subcommand(subparsers):
    parser = subparsers.add_parser("config", help="Create the launch configuration")
    parser.add_argument("--config_file", default=None)
    parser.add_argument("--default", action="store_true", help="Write defaults without prompting")
    parser.add_argument("--update", action="store_true",
                        help="Migrate an existing config file to the current schema")
    parser.set_defaults(func=config_command)
