"""``accelerate-tpu config`` — write/load the default YAML config.

Parity target: reference ``commands/config/`` (~1800 LoC questionnaire + YAML).
Round 1 ships the YAML schema + non-interactive ``default`` + a compact
questionnaire; the config file feeds ``launch`` exactly like the reference's.
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass
from typing import Optional

import yaml

DEFAULT_CONFIG_DIR = os.path.expanduser(
    os.environ.get("ACCELERATE_CONFIG_DIR", "~/.cache/accelerate_tpu")
)
DEFAULT_CONFIG_FILE = os.path.join(DEFAULT_CONFIG_DIR, "default_config.yaml")

__all__ = ["ClusterConfig", "load_config", "save_config", "config_command", "default_config_command"]


@dataclass
class ClusterConfig:
    compute_environment: str = "LOCAL_MACHINE"
    distributed_type: str = "TPU_JAX"
    mixed_precision: str = "no"
    num_machines: int = 1
    machine_rank: int = 0
    main_process_ip: Optional[str] = None
    main_process_port: Optional[int] = None
    gradient_accumulation_steps: int = 1
    # Mesh axes (ParallelismConfig)
    dp: int = 0  # 0 = auto (all remaining devices)
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1
    use_fsdp: bool = False
    fsdp_sharding_strategy: str = "FULL_SHARD"
    fsdp_min_num_params: int = 0
    # DeepSpeed dialect: a ds_config.json consumed at prepare time
    # (utils/deepspeed.py); flows to workers via ACCELERATE_DEEPSPEED_CONFIG_FILE.
    deepspeed_config_file: Optional[str] = None
    downcast_bf16: bool = False
    # Pod management (consumed by `accelerate-tpu tpu-config`).
    tpu_name: Optional[str] = None
    tpu_zone: Optional[str] = None

    def to_dict(self) -> dict:
        return asdict(self)


def save_config(config: ClusterConfig, path: str = DEFAULT_CONFIG_FILE) -> str:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(config.to_dict(), f)
    return path


def load_config(path: Optional[str] = None) -> ClusterConfig:
    path = path or DEFAULT_CONFIG_FILE
    if not os.path.exists(path):
        return ClusterConfig()
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    known = {k: v for k, v in data.items() if k in ClusterConfig.__dataclass_fields__}
    return ClusterConfig(**known)


def _ask(prompt: str, default, cast=str):
    raw = input(f"{prompt} [{default}]: ").strip()
    return cast(raw) if raw else default


def _yes(raw) -> bool:
    from ..utils.environment import str_to_bool

    try:
        return bool(str_to_bool(str(raw)))
    except ValueError:
        return False


def config_command(args):
    if getattr(args, "default", False):
        return default_config_command(args)
    if getattr(args, "update", False):
        return update_config_command(args)
    cfg = ClusterConfig()
    # Cluster questions mirroring the reference questionnaire
    # (commands/config/cluster.py), keeping only ones with native TPU meaning.
    cfg.num_machines = _ask("How many machines (hosts)?", 1, int)
    if cfg.num_machines > 1:
        cfg.machine_rank = _ask("Rank of this machine?", 0, int)
        cfg.main_process_ip = _ask("Main process IP?", "127.0.0.1")
        cfg.main_process_port = _ask("Main process port?", 29500, int)
    cfg.mixed_precision = _ask("Mixed precision (no/bf16/fp16/fp8)?", "bf16")
    cfg.gradient_accumulation_steps = _ask("Gradient accumulation steps?", 1, int)
    cfg.use_fsdp = _yes(_ask("Use FSDP parameter sharding (yes/no)?", "no"))
    if cfg.use_fsdp:
        cfg.fsdp = _ask("FSDP axis size (0=all devices)?", 0, int) or 0
        cfg.fsdp_sharding_strategy = _ask(
            "Sharding strategy (FULL_SHARD/SHARD_GRAD_OP/NO_SHARD/HYBRID_SHARD)?", "FULL_SHARD"
        )
        cfg.fsdp_min_num_params = _ask("Min params per wrapped block (0=every block)?", 0, int)
    cfg.tp = _ask("Tensor-parallel size?", 1, int)
    cfg.sp = _ask("Sequence-parallel size (ring/ulysses long-context)?", 1, int)
    cfg.pp = _ask("Pipeline-parallel size?", 1, int)
    cfg.ep = _ask("Expert-parallel size (MoE)?", 1, int)
    if _yes(_ask("Train with a DeepSpeed config dialect (yes/no)?", "no")):
        cfg.deepspeed_config_file = _ask("Path to ds_config.json?", "ds_config.json")
    if cfg.num_machines > 1 and _yes(_ask("Is this a GCP TPU pod (yes/no)?", "no")):
        cfg.tpu_name = _ask("TPU pod name?", None)
        cfg.tpu_zone = _ask("TPU zone?", None)
    path = save_config(cfg, getattr(args, "config_file", None) or DEFAULT_CONFIG_FILE)
    print(f"Configuration saved to {path}")


def update_config_command(args):
    """Migrate an existing config file to the current schema (reference
    ``commands/config/update.py``): unknown keys drop with a note, missing
    keys fill with defaults, the result is rewritten in place."""
    path = getattr(args, "config_file", None) or DEFAULT_CONFIG_FILE
    if not os.path.exists(path):
        raise SystemExit(f"No config file at {path}; run `accelerate-tpu config` first.")
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    known = set(ClusterConfig.__dataclass_fields__)
    dropped = sorted(k for k in data if k not in known)
    cfg = ClusterConfig(**{k: v for k, v in data.items() if k in known})
    save_config(cfg, path)
    note = f" (dropped unknown keys: {', '.join(dropped)})" if dropped else ""
    print(f"Updated {path} to the current schema{note}")
    return dropped


def default_config_command(args):
    path = save_config(ClusterConfig(), getattr(args, "config_file", None) or DEFAULT_CONFIG_FILE)
    print(f"Default configuration saved to {path}")


def write_basic_config(mixed_precision: str = "no", save_location: str = None) -> str:
    """Programmatic default-config writer (reference
    ``commands/config/default.py:36`` — used by notebooks/CI to skip the
    questionnaire).  Returns the written path."""
    cfg = ClusterConfig(mixed_precision=str(mixed_precision))
    return save_config(cfg, save_location or DEFAULT_CONFIG_FILE)


def register_subcommand(subparsers):
    parser = subparsers.add_parser("config", help="Create the launch configuration")
    parser.add_argument("--config_file", default=None)
    parser.add_argument("--default", action="store_true", help="Write defaults without prompting")
    parser.add_argument("--update", action="store_true",
                        help="Migrate an existing config file to the current schema")
    parser.set_defaults(func=config_command)
