"""``accelerate-tpu env`` — platform report for bug reports (parity: reference
``commands/env.py``, 119 LoC)."""

from __future__ import annotations

import platform

from .config import DEFAULT_CONFIG_FILE, load_config


def _probe_devices(timeout_s: float = 20.0) -> dict:
    """Backend probe with a deadline: a tunneled TPU whose link is down blocks
    client creation forever, and an env report must never hang (the
    reference's env command touches no device at all).

    First gate: the shared killable-subprocess probe
    (``utils/device_probe.py`` — also used by ``bench.py`` and first-touch
    state bring-up).  On a device platform, its "<count> <kind>" answer IS the
    report — re-initializing the backend in-process would double the latency
    and re-expose the hang risk; the richer in-process query runs only on the
    cpu backend (cheap, cannot wedge)."""
    import os
    import threading

    import jax

    from ..utils.device_probe import probe_device_backend

    platforms = (jax.config.jax_platforms or "").strip()
    device_platform = platforms and any(
        p.strip() != "cpu" for p in platforms.split(",") if p.strip()
    )
    if device_platform:
        ok, detail = probe_device_backend(timeout_s=timeout_s)
        if not ok:
            return {"JAX backend": f"UNREACHABLE ({detail})"}
        count, _, kind = detail.partition(" ")
        return {
            "JAX backend": platforms.split(",")[0],
            "Device count": count,
            "Device kind": kind,
        }

    result: dict = {}

    def probe():
        try:
            import jax

            # The axon sitecustomize overrides JAX_PLATFORMS at interpreter
            # start; re-apply an explicit cpu-only request before the first
            # backend touch.
            if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
                jax.config.update("jax_platforms", "cpu")
            result.update(
                {
                    "JAX backend": jax.default_backend(),
                    "Device count": jax.device_count(),
                    "Devices": ", ".join(str(d) for d in jax.devices()[:8]),
                    "Process count": jax.process_count(),
                }
            )
        except Exception as e:  # import/config/backend-init error
            result["JAX backend"] = f"ERROR ({type(e).__name__}: {e})"

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    if not result:
        return {"JAX backend": f"UNREACHABLE (no response in {timeout_s:.0f}s)"}
    return result


def env_command(args):
    import jax

    import accelerate_tpu

    info = {
        "accelerate_tpu version": accelerate_tpu.__version__,
        "Platform": platform.platform(),
        "Python version": platform.python_version(),
        "JAX version": jax.__version__,
    }
    info.update(_probe_devices())
    try:
        import flax, optax

        info["Flax version"] = flax.__version__
        info["Optax version"] = optax.__version__
    except ImportError:
        pass
    try:
        import torch

        info["PyTorch version (ingestion)"] = torch.__version__
    except ImportError:
        pass
    info["Default config"] = DEFAULT_CONFIG_FILE
    cfg = load_config(getattr(args, "config_file", None))
    print("\nCopy-and-paste the text below in your GitHub issue\n")
    for k, v in info.items():
        print(f"- {k}: {v}")
    print("- Config:")
    for k, v in cfg.to_dict().items():
        print(f"\t- {k}: {v}")


def register_subcommand(subparsers):
    parser = subparsers.add_parser("env", help="Print environment information")
    parser.add_argument("--config_file", default=None)
    parser.set_defaults(func=env_command)
