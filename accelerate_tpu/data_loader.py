"""Data pipeline — L3.

Parity target: reference ``src/accelerate/data_loader.py`` (1429 LoC):
``SeedableRandomSampler`` (72), ``BatchSamplerShard`` (109), ``IterableDatasetShard``
(265), ``DataLoaderStateMixin`` (364), ``DataLoaderShard`` (499),
``DataLoaderDispatcher`` (696), ``prepare_data_loader`` (988), ``skip_first_batches``
(1290).  The index math (split_batches / even-batches wraparound / remainder
accounting) reproduces the reference's observable behavior exactly — it is fully
specified by the reference's ``tests/test_data_loader.py`` — but the implementation
is original and the device story is inverted:

TPU-native design: the reference shards *per process == per device* and each rank
holds a local tensor.  Here sharding happens at TWO levels:

1. **Host level** (these samplers): ``num_processes`` = number of JAX host
   processes; each host reads only its shard of the global batch.
2. **Device level** (``_GlobalBatchPlacer``): the per-host batch becomes one
   *global* ``jax.Array`` sharded over the mesh's data axes
   (``jax.make_array_from_process_local_data``), so the jit-compiled step sees the
   full logical batch and XLA partitions it.  Tensor/sequence-parallel ranks
   automatically observe the same data — the reference needed special TP-aware
   dataloader logic (``data_loader.py:756-776``); here it falls out of GSPMD.
"""

from __future__ import annotations

import contextlib
import math
import warnings
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec

from .pipeline.prefetch import DevicePrefetcher, cached_sharding, prefetch_depth_from_env
from .state import AcceleratorState, GradientState, PartialState
from .utils.imports import is_torch_available
from .utils.operations import (
    find_batch_size,
    ignorant_find_batch_size,
    recursively_apply,
    send_to_device,
    slice_tensors,
    to_numpy,
)
from .utils.random import synchronize_rng_states
from .logging import get_logger
from .telemetry import get_telemetry as _get_telemetry
from .telemetry import span as _span

logger = get_logger(__name__)

__all__ = [
    "SeedableRandomSampler",
    "BatchSamplerShard",
    "IterableDatasetShard",
    "DataLoaderStateMixin",
    "DataLoaderShard",
    "DataLoaderDispatcher",
    "prepare_data_loader",
    "skip_first_batches",
    "SkipBatchSampler",
    "SkipDataLoader",
    "get_sampler",
]


class SeedableRandomSampler:
    """Random sampler reseeded as ``initial_seed + epoch`` every epoch so every
    process draws the same permutation.

    Parity: reference ``data_loader.py:72-106``.  Implemented torch-free (numpy
    Generator) but duck-types as a torch ``Sampler`` (iterable + ``__len__``) so it
    drops into a torch ``DataLoader``.
    """

    def __init__(self, data_source, initial_seed: Optional[int] = None, generator=None):
        self.data_source = data_source
        if initial_seed is None:
            initial_seed = int(np.random.SeedSequence().generate_state(1)[0])
        self.initial_seed = initial_seed
        self.epoch = 0
        self.generator = generator  # torch generator, honored if provided

    def __len__(self):
        return len(self.data_source)

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __iter__(self):
        seed = self.epoch + self.initial_seed
        if self.generator is not None and is_torch_available():
            import torch

            self.generator.manual_seed(seed)
            yield from torch.randperm(len(self.data_source), generator=self.generator).tolist()
        else:
            rng = np.random.default_rng(seed)
            yield from rng.permutation(len(self.data_source)).tolist()
        self.epoch += 1


class BatchSamplerShard:
    """Shard a batch sampler so each process sees only its batches.

    Parity: reference ``data_loader.py:109-262``.  Two modes:

    - ``split_batches=True``: every process receives 1/Nth of *every* batch.
    - ``split_batches=False``: whole batches are dealt round-robin in fixed windows
      of ``num_processes``.

    ``even_batches=True`` wraps around to indices from the start of the epoch so
    every process always receives the same number of equally-sized batches (the
    wrapped duplicates are later dropped by ``gather_for_metrics``).
    """

    def __init__(
        self,
        batch_sampler,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        self.batch_sampler = batch_sampler
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)
        if split_batches and self.batch_size is not None and self.batch_size % num_processes != 0:
            raise ValueError(
                f"In split_batches mode the batch size ({self.batch_size}) must be a round "
                f"multiple of num_processes ({num_processes})."
            )
        if self.batch_size is None and self.even_batches:
            raise ValueError(
                "You need `even_batches=False` when the batch sampler has no fixed batch size."
            )

    def set_epoch(self, epoch: int):
        # Custom batch samplers that reshuffle per epoch (reference
        # test_data_loader.py:517 SimpleBatchSampler) must still hear
        # set_epoch once wrapped in a shard.
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)

    @property
    def total_length(self) -> int:
        return len(self.batch_sampler)

    def __len__(self) -> int:
        n = len(self.batch_sampler)
        if self.split_batches:
            return n
        if n % self.num_processes == 0:
            return n // self.num_processes
        base = n // self.num_processes
        if self.drop_last:
            return base
        if self.even_batches:
            return base + 1
        return base + 1 if self.process_index < n % self.num_processes else base

    def __iter__(self) -> Iterator[list]:
        return self._iter_split() if self.split_batches else self._iter_whole()

    def _iter_split(self) -> Iterator[list]:
        per_proc = self.batch_size // self.num_processes
        lo, hi = per_proc * self.process_index, per_proc * (self.process_index + 1)
        first_full_batch: list = []
        tail: list = []
        seen_any = False
        for batch in self.batch_sampler:
            seen_any = True
            if not first_full_batch:
                first_full_batch = list(batch)
            if len(batch) == self.batch_size:
                tail = []
                yield list(batch)[lo:hi]
            else:
                tail = list(batch)  # only ever the final, short batch
        if self.drop_last or not seen_any or not tail:
            return
        if not self.even_batches:
            if len(tail) > lo:
                yield tail[lo:hi]
            return
        # Wrap around with indices from the first batch until full.
        filler = list(first_full_batch)
        while len(filler) < self.batch_size:
            filler = filler + filler
        completed = tail + filler
        yield completed[lo:hi]

    def _iter_whole(self) -> Iterator[list]:
        first_indices: list = []  # first num_processes batches, flattened (wraparound pool)
        pending: list = []  # this process's batch from the in-flight window
        last: list = []
        count = 0
        for batch in self.batch_sampler:
            batch = list(batch)
            if not self.drop_last and count < self.num_processes:
                first_indices.extend(batch)
            if count % self.num_processes == self.process_index:
                pending = batch
            last = batch
            count += 1
            if count % self.num_processes == 0 and (
                self.batch_size is None or len(batch) == self.batch_size
            ):
                yield pending
                pending = []
        if self.drop_last or not first_indices:
            return
        if not self.even_batches:
            if pending:
                yield pending
            return
        # Even-batches tail: first flush a full-sized pending batch, then deal
        # wrapped-around batches until every process has yielded the same count.
        if len(pending) == self.batch_size:
            yield pending
        while len(first_indices) < self.num_processes * self.batch_size:
            first_indices = first_indices + first_indices
        pos = count - 1  # index of the last batch seen
        if len(last) == self.batch_size:
            last = []  # already dealt in-window
            pos += 1
        cursor = 0
        while pos % self.num_processes != 0 or len(last) > 0:
            take = cursor + self.batch_size - len(last)
            last = last + first_indices[cursor:take]
            if pos % self.num_processes == self.process_index:
                yield last
            cursor = take
            last = []
            pos += 1


class IterableDatasetShard:
    """Shard an iterable dataset: buffer one *real* batch worth of elements, then
    emit this process's slice.

    Parity: reference ``data_loader.py:265-361``, including the pad-from-first-batch
    tail behavior.
    """

    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        drop_last: bool = False,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
    ):
        if split_batches and batch_size > 1 and batch_size % num_processes != 0:
            raise ValueError(
                f"In split_batches mode the batch size ({batch_size}) must be a round "
                f"multiple of num_processes ({num_processes})."
            )
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.epoch = 0

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self):
        chunk = self.batch_size * self.num_processes
        if self.drop_last:
            return (len(self.dataset) // chunk) * self.batch_size
        return math.ceil(len(self.dataset) / chunk) * self.batch_size

    def __iter__(self):
        if (
            not hasattr(self.dataset, "set_epoch")
            and hasattr(self.dataset, "generator")
            and is_torch_available()
        ):
            import torch

            if isinstance(self.dataset.generator, torch.Generator):
                self.dataset.generator.manual_seed(self.epoch)
        real = self.batch_size if self.split_batches else self.batch_size * self.num_processes
        mine = self.batch_size // self.num_processes if self.split_batches else self.batch_size
        lo = self.process_index * mine
        buffer: list = []
        first_full: Optional[list] = None
        for element in self.dataset:
            buffer.append(element)
            if len(buffer) == real:
                yield from buffer[lo : lo + mine]
                if first_full is None:
                    first_full = list(buffer)
                buffer = []
        if self.drop_last or not buffer:
            return
        if first_full is None:
            first_full = list(buffer)
        while len(buffer) < real:
            buffer = buffer + first_full
        yield from buffer[lo : lo + mine]


# ---------------------------------------------------------------------------
# Device placement
# ---------------------------------------------------------------------------


class _GlobalBatchPlacer:
    """Turn a per-host numpy/torch batch into a global ``jax.Array`` sharded over
    the mesh's data axes (the H2D boundary of the hot loop, reference
    ``data_loader.py:575`` ``send_to_device``).

    Replaces the reference's XLA path (``MpDeviceLoaderWrapper``
    ``data_loader.py:643-693``, per-core preloading threads): here a single
    ``device_put``/``make_array_from_process_local_data`` call hands XLA one global
    array; XLA pipelines the transfer.
    """

    def __init__(
        self,
        mesh: Optional[jax.sharding.Mesh],
        non_blocking: bool = False,
        device=None,
        output_type: str = "jax",
        even_batches: bool = True,
    ):
        self.mesh = mesh
        # Informational only (the loaders propagate it through rebuilds, e.g.
        # skip_first_batches): the shard-divisibility pad below applies under
        # EITHER setting — a global jax.Array must divide across local shards.
        self.even_batches = even_batches
        self.non_blocking = non_blocking  # jax transfers are always async; kept for API parity
        self.device = device
        # "jax": yield global jax.Arrays.  "torch": yield torch views of the host
        # batch carrying the placed jax array as `._atpu_jax` — user-land torch
        # ops (criteria, metrics) work unchanged while the model call path picks
        # up the device array with no extra transfer.
        self.output_type = output_type
        self._data_axes: tuple[str, ...] = ()
        if mesh is not None:
            from .parallel.mesh import data_axes

            self._data_axes = data_axes(mesh)
        self._warned_pad = False
        # Always defined — the no-mesh path never sets them in __call__, and
        # DataLoaderShard reads them after every conversion.
        self.last_pad_rows = 0
        self.last_batch_rows = 0

    # Live jax.Device / Mesh handles are process-local and unpicklable
    # (reference test_accelerator.py:649 test_can_pickle_dataloader): drop them
    # on pickle, re-attach to the process's AcceleratorState mesh on load.
    def __getstate__(self):
        state = self.__dict__.copy()
        state["mesh"] = None
        state["device"] = None
        state["_had_mesh"] = self.mesh is not None
        state["_had_device"] = self.device is not None
        return state

    def __setstate__(self, state):
        had_mesh = state.pop("_had_mesh", False)
        had_device = state.pop("_had_device", False)
        self.__dict__.update(state)
        if had_mesh:
            from .parallel.mesh import data_axes

            self.mesh = AcceleratorState().mesh
            self._data_axes = data_axes(self.mesh)
        if had_device:
            self.device = AcceleratorState().device

    @property
    def num_data_shards(self) -> int:
        if self.mesh is None or not self._data_axes:
            return 1
        n = 1
        for a in self._data_axes:
            n *= self.mesh.shape[a]
        return n

    @property
    def local_data_shards(self) -> int:
        """Data shards resident on THIS host (the divisibility unit for the
        per-host batch)."""
        return max(self.num_data_shards // jax.process_count(), 1)

    def _wrap(self, host_arr: np.ndarray, jax_arr: jax.Array):
        if self.output_type != "torch":
            return jax_arr
        import torch

        t = torch.from_numpy(np.ascontiguousarray(host_arr))
        t._atpu_jax = jax_arr
        return t

    def __call__(self, batch):
        if self.mesh is None or not self._data_axes:
            if self.output_type != "torch":
                return send_to_device(batch, self.device)
            # Wrap the ORIGINAL host array (dtype preserved, e.g. int64 labels for
            # torch criteria) and attach the placed jax array — no D2H roundtrip.
            from .utils.operations import to_jax

            def _place_and_wrap(t):
                host = to_numpy(t)
                return self._wrap(host, jax.device_put(to_jax(t), self.device))

            return recursively_apply(_place_and_wrap, batch)
        # Hot path: one cached NamedSharding per (mesh, spec) — rebuilding
        # (and re-hashing the mesh for) an identical sharding per tensor per
        # batch was measurable host overhead between steps.
        sharding = cached_sharding(self.mesh, PartitionSpec(self._data_axes))
        local_shards = self.local_data_shards
        multi_host = jax.process_count() > 1
        # Rows added to THIS batch to make it shard-divisible, plus the padded
        # per-host row count; the owning loader publishes both on GradientState
        # so gather_for_metrics can drop exactly the duplicates — and ONLY from
        # tensors whose leading dim is the padded batch (not from arbitrary
        # gathered vectors).
        self.last_pad_rows = 0
        self.last_batch_rows = 0

        def _place(t):
            arr = to_numpy(t)
            if arr.ndim == 0:
                return self._wrap(arr, jax.device_put(arr, cached_sharding(self.mesh, PartitionSpec())))
            if arr.shape[0] % local_shards != 0:
                # Pad the batch dim by repeating the final row so GSPMD can
                # split it.  DECISION (r4, VERDICT item 8): always pad, never
                # error — a global jax.Array MUST divide across local shards,
                # so the pad is an implementation necessity of the global-array
                # design, not an even_batches choice (even_batches governs the
                # host-level index math; the shipped test_distributed_data_loop
                # script pins this contract for even_batches=False).  The pad
                # rows are tracked on GradientState and gather_for_metrics
                # drops them; the warning tells training users the repeated
                # sample slightly reweights the tail batch's gradient.
                if not self._warned_pad:
                    warnings.warn(
                        f"Per-host batch dim {arr.shape[0]} not divisible by {local_shards} local "
                        "data shards; padding by repeating the last sample (dropped again by "
                        "gather_for_metrics, but a training step on this batch counts the "
                        "repeated sample). Use even per-shard batch sizes or drop_last=True "
                        "to avoid this."
                    )
                    self._warned_pad = True
                pad = local_shards - arr.shape[0] % local_shards
                arr = np.concatenate([arr, np.repeat(arr[-1:], pad, axis=0)], axis=0)
                # Rows recorded from the PADDED leaf itself — a non-batch leaf
                # with a larger leading dim must not disable the pad-drop
                # (gather_for_metrics matches on n_proc * last_batch_rows).
                self.last_pad_rows = max(self.last_pad_rows, pad)
                self.last_batch_rows = max(self.last_batch_rows, arr.shape[0])
            if multi_host:
                # ``arr`` must be exactly this host's shard of the global batch.
                return self._wrap(arr, jax.make_array_from_process_local_data(sharding, arr))
            return self._wrap(arr, jax.device_put(arr, sharding))

        return recursively_apply(_place, batch)


class DataLoaderStateMixin:
    """Track end-of-dataloader / remainder on the shared ``GradientState``.

    Parity: reference ``data_loader.py:364-404`` — this is the link between the
    data layer and gradient-accumulation sync decisions.
    """

    def __init_subclass__(cls, **kwargs):
        cls.end_of_dataloader = False
        cls.remainder = -1

    def reset(self):
        self.end_of_dataloader = False
        self.remainder = -1

    # The GradientState borg holds weakrefs to live loaders — rebuild it on
    # unpickle instead of serializing it (loaders must pickle, reference
    # test_can_pickle_dataloader).
    def __getstate__(self):
        state = {k: v for k, v in self.__dict__.items() if k != "gradient_state"}
        if state.get("device") is not None:
            state["device"] = None
            state["_had_device"] = True
        return state

    def __setstate__(self, state):
        had_device = state.pop("_had_device", False)
        self.__dict__.update(state)
        self.gradient_state = GradientState()
        if had_device:
            self.device = AcceleratorState().device

    def begin(self):
        self.reset()
        # Snapshot the singleton's pad bookkeeping: a nested loader (eval loop
        # inside a train iteration) must not clobber the OUTER loader's
        # counters — end() restores this snapshot instead of zeroing, so a
        # gather_for_metrics on the outer padded batch still dedups.
        self._outer_pad_rows = getattr(self.gradient_state, "device_pad_rows", 0)
        self._outer_batch_rows = getattr(self.gradient_state, "device_batch_rows", 0)
        self._yielded = self.skip_batches
        with contextlib.suppress(Exception):
            length = getattr(self.dataset, "total_dataset_length", len(self.dataset))
            self.remainder = length % self.total_batch_size
        self.gradient_state._add_dataloader(self)

    def end(self):
        self.gradient_state.device_pad_rows = getattr(self, "_outer_pad_rows", 0)
        self.gradient_state.device_batch_rows = getattr(self, "_outer_batch_rows", 0)
        self.gradient_state._remove_dataloader(self)

    # -- stateful-dataloader contract (reference DataLoaderAdapter over
    # torchdata's StatefulDataLoader, data_loader.py:418-498).  Native design:
    # the loader tracks its user-visible batch position directly, so no
    # torchdata dependency and no prefetch adjustment is needed — ``_yielded``
    # is advanced at the yield site, which by construction excludes the
    # one-batch lookahead (the reference subtracts prefetched batches in
    # adjust_state_dict_for_prefetch, data_loader.py:462).

    def state_dict(self) -> dict:
        """Mid-epoch position: ``batches_yielded`` user-visible batches this
        epoch plus the epoch counter.  Valid while iterating (the batch the
        caller currently holds counts as yielded)."""
        return {
            "batches_yielded": getattr(self, "_yielded", 0),
            "iteration": self.iteration,
        }

    def load_state_dict(self, state_dict: dict) -> None:
        """Resume mid-epoch: the NEXT iteration skips the recorded batches
        (consumed once — subsequent epochs run in full), and the epoch counter
        is restored so ``set_epoch``-driven sampler shuffles line up."""
        self.skip_batches = int(state_dict.get("batches_yielded", 0))
        self.iteration = int(state_dict.get("iteration", 0))
        self._yielded = self.skip_batches
        self._skip_once = True

    def _consume_skip_once(self):
        if getattr(self, "_skip_once", False):
            self.skip_batches = 0
            self._skip_once = False

    # -- numerical-health hooks (resilience/health.py) ------------------------
    #
    # Quarantine: positions fingerprinted as (epoch, user-visible batch index)
    # are consumed but never yielded — the post-rewind replay of a run whose
    # step went non-finite twice on the same batch silently drops that batch.
    # The fingerprint is EPOCH-scoped: under a shuffling sampler the data at
    # index i differs between epochs, so only replays of the same epoch (the
    # rewind case — ``load_state_dict`` restores ``iteration``) skip it;
    # later epochs run the position normally.  ``load_state_dict`` never
    # touches the set itself, so a health-guard rewind keeps its quarantine
    # across the restore.

    def quarantine(self, fingerprints) -> None:
        """Register ``(epoch, batch_index)`` fingerprints to skip at yield
        time (``HealthGuard`` pushes its quarantine set through here)."""
        q = getattr(self, "_quarantined", None)
        if q is None:
            q = self._quarantined = set()
        q.update((int(e), int(i)) for e, i in fingerprints)

    def _is_quarantined(self, index: int) -> bool:
        q = getattr(self, "_quarantined", None)
        return bool(q) and (self.iteration, index) in q

    def _count_quarantine_skip(self, index: int) -> None:
        tel = _get_telemetry()
        if tel.enabled:
            tel.registry.counter("health.quarantine_skips").inc()
        logger.warning(
            f"health: skipping quarantined batch (epoch={self.iteration}, index={index})"
        )

    def _maybe_poison(self, batch, index: int):
        """Fault injection (``ACCELERATE_TPU_FAULT_BAD_BATCH=<i>``): NaN-lace
        the armed per-epoch position.  One cached-None check when unarmed."""
        from .resilience import faultinject

        if faultinject.bad_batch_index() is None:
            return batch
        return faultinject.maybe_poison_batch(batch, index)


class DataLoaderShard(DataLoaderStateMixin):
    """Per-process loader: RNG sync at epoch start, one-batch prefetch to detect the
    end of iteration, global-array device placement.

    Parity: reference ``data_loader.py:499-640``.  Wraps any iterable of batches
    (typically a torch ``DataLoader`` whose batch_sampler is a
    `BatchSamplerShard`); yields global jax arrays.
    """

    def __init__(
        self,
        base_loader: Iterable,
        device=None,
        rng_types: Optional[list] = None,
        synchronized_generator=None,
        skip_batches: int = 0,
        put_on_device: bool = True,
        mesh: Optional[jax.sharding.Mesh] = None,
        non_blocking: bool = False,
        output_type: str = "jax",
        _drop_last: bool = False,
        _non_blocking: bool = False,
        use_stateful_dataloader: bool = False,
        even_batches: bool = True,
        prefetch_to_device: int = 0,
        **kwargs,
    ):
        self.base_loader = base_loader
        self.device = device
        self.rng_types = rng_types
        self.synchronized_generator = synchronized_generator
        self.skip_batches = skip_batches
        self.put_on_device = put_on_device
        self.use_stateful_dataloader = use_stateful_dataloader
        self.even_batches = even_batches
        self.prefetch_to_device = prefetch_to_device
        self.gradient_state = GradientState()
        self.iteration = 0
        self._yielded = 0
        self._placer = (
            _GlobalBatchPlacer(
                mesh,
                non_blocking,
                device=device,
                output_type=output_type,
                even_batches=even_batches,
            )
            if put_on_device
            else None
        )
        self._total_batch_size = kwargs.pop("total_batch_size", None)

    # Convenience pass-throughs so the wrapper quacks like the inner loader.
    @property
    def dataset(self):
        return getattr(self.base_loader, "dataset", self.base_loader)

    @property
    def batch_sampler(self):
        return getattr(self.base_loader, "batch_sampler", None)

    @property
    def sampler(self):
        sampler = getattr(self.base_loader, "sampler", None)
        if sampler is None and self.batch_sampler is not None:
            sampler = getattr(self.batch_sampler, "sampler", None)
            if sampler is None and hasattr(self.batch_sampler, "batch_sampler"):
                sampler = getattr(self.batch_sampler.batch_sampler, "sampler", None)
        return sampler

    def __len__(self):
        return len(self.base_loader) - self.skip_batches

    @property
    def batch_size(self):
        """Per-device micro batch (reference ``DataLoader.batch_size``
        semantics: the script's batch_size is PER data shard).  Consumed by
        the DeepSpeed-dialect ``fill_auto`` to resolve
        ``train_micro_batch_size_per_gpu: auto``."""
        total = self.total_batch_size
        if total is None:
            return None
        mesh = getattr(self._placer, "mesh", None)
        if mesh is None:
            return total
        from .parallel.mesh import data_axes

        shards = 1
        for a in data_axes(mesh):
            shards *= mesh.shape[a]
        return max(total // max(shards, 1), 1)

    @property
    def total_batch_size(self) -> int:
        if self._total_batch_size is not None:
            return self._total_batch_size
        bs = getattr(self.batch_sampler, "batch_size", None)
        if bs is None:
            bs = getattr(self.base_loader, "batch_size", None) or 1
        sampler = self.batch_sampler
        if isinstance(sampler, BatchSamplerShard):
            return sampler.batch_size * (1 if sampler.split_batches else sampler.num_processes)
        return bs

    @property
    def total_dataset_length(self) -> int:
        return len(self.dataset)

    def set_epoch(self, epoch: int):
        if self.iteration != epoch:
            self.iteration = epoch
        for obj in (self.base_loader, self.batch_sampler, self.sampler, self.dataset):
            if obj is not None and hasattr(obj, "set_epoch") and obj is not self:
                obj.set_epoch(epoch)

    def _convert(self, batch):
        if self._placer is not None:
            return self._placer(batch)
        return batch

    def _convert_tracked(self, b):
        """Convert one batch and capture its pad bookkeeping.  Runs on the
        calling thread in the synchronous path and on the prefetch worker in
        the async path (the placer is only ever touched by one of them)."""
        with _span("dataloader.next_batch"):
            out = self._convert(b)
        tel = _get_telemetry()
        if tel.enabled:
            tel.registry.counter("dataloader.batches").inc()
            tel.heartbeat()  # host-side data stalls must not trip the watchdog
        if self._placer is None:
            return out, (0, 0)
        return out, (self._placer.last_pad_rows, self._placer.last_batch_rows)

    def _effective_prefetch_depth(self) -> int:
        """Configured depth, else the ``ACCELERATE_TPU_PREFETCH`` env knob
        (resolved per epoch so tests and launchers can flip it)."""
        depth = self.prefetch_to_device or prefetch_depth_from_env()
        return depth if self._placer is not None else 0

    def _iter_prefetched(self, iterator, depth: int):
        """Async-prefetch epoch: a background thread converts + device_puts
        up to ``depth`` batches ahead; this thread only pops and yields.
        Ordering, skip accounting, pad bookkeeping and the
        flip-end_of_dataloader-before-final-yield contract all match the
        synchronous path."""
        # Skipped batches are consumed (never converted) before the worker
        # starts — same positions the synchronous path drops.
        for _ in range(self.skip_batches):
            try:
                next(iterator)
            except StopIteration:
                break
        prefetcher = DevicePrefetcher(iterator, self._convert_tracked, depth)
        emitted = 0
        try:
            for converted, pad, is_last in prefetcher:
                if is_last:
                    self.end_of_dataloader = True
                pos = self.skip_batches + emitted
                emitted += 1
                self._yielded = pos + 1
                if self._is_quarantined(pos):
                    # Consumed (position advances for state_dict) but never
                    # yielded — the health-guard replay-skip.
                    self._count_quarantine_skip(pos)
                    continue
                self.gradient_state.device_pad_rows = pad[0]
                self.gradient_state.device_batch_rows = pad[1]
                yield self._maybe_poison(converted, pos)
        finally:
            # Runs on break/close too: an abandoned epoch must not leave a
            # worker thread converting batches into a dead queue.
            prefetcher.close()
        if emitted == 0:
            # skip_batches covered the whole (non-empty) epoch — the sync
            # path still flags end-of-dataloader in that case.
            self.end_of_dataloader = True

    def __iter__(self):
        if self.rng_types is not None:
            synchronize_rng_states(self.rng_types, self.synchronized_generator)
        self.begin()
        self.set_epoch(self.iteration)
        depth = self._effective_prefetch_depth()
        if depth > 0:
            import itertools

            iterator = iter(self.base_loader)
            try:
                first = next(iterator)
            except StopIteration:
                self.end()
                return
            yield from self._iter_prefetched(itertools.chain([first], iterator), depth)
            self.iteration += 1
            self._yielded = 0
            self._consume_skip_once()
            self.end()
            return
        iterator = iter(self.base_loader)
        # One-batch lookahead so the final yield can flip end_of_dataloader BEFORE
        # user code processes it — this is what lets `accumulate()` force a sync on
        # the last batch (reference data_loader.py:557-640).
        try:
            current = next(iterator)
        except StopIteration:
            self.end()
            return
        batch_index = 0
        current_converted = None
        current_pad = (0, 0)
        _convert_tracked = self._convert_tracked

        def _emits(index: int) -> bool:
            # A quarantined position is consumed (state_dict position still
            # advances) but neither converted nor yielded.
            return index >= self.skip_batches and not self._is_quarantined(index)

        while True:
            if current_converted is None and _emits(batch_index):
                current_converted, current_pad = _convert_tracked(current)
            try:
                upcoming = next(iterator)
            except StopIteration:
                self.end_of_dataloader = True
                if batch_index >= self.skip_batches:
                    self._yielded = batch_index + 1
                    if _emits(batch_index):
                        self.gradient_state.device_pad_rows = current_pad[0]
                        self.gradient_state.device_batch_rows = current_pad[1]
                        yield self._maybe_poison(current_converted, batch_index)
                    else:
                        self._count_quarantine_skip(batch_index)
                break
            # Double buffering (reference MpDeviceLoader's background preload,
            # data_loader.py:643-693): issue batch n+1's async device transfer
            # BEFORE yielding batch n, so the H2D overlaps the user's step.
            if _emits(batch_index + 1):
                upcoming_converted, upcoming_pad = _convert_tracked(upcoming)
            else:
                upcoming_converted, upcoming_pad = None, (0, 0)
            if batch_index >= self.skip_batches:
                self._yielded = batch_index + 1
                if _emits(batch_index):
                    self.gradient_state.device_pad_rows = current_pad[0]
                    self.gradient_state.device_batch_rows = current_pad[1]
                    yield self._maybe_poison(current_converted, batch_index)
                else:
                    self._count_quarantine_skip(batch_index)
            batch_index += 1
            current = upcoming
            current_converted, current_pad = upcoming_converted, upcoming_pad
        self.iteration += 1
        # A state_dict taken between epochs must record position 0 of the NEXT
        # epoch — leaving _yielded at the full count would make a resumed run
        # silently skip that entire epoch.
        self._yielded = 0
        self._consume_skip_once()
        self.end()


class DataLoaderDispatcher(DataLoaderStateMixin):
    """Main-process-reads loader: process 0 iterates the dataset and broadcasts
    each global batch; other processes receive their slice.

    Parity: reference ``data_loader.py:696-967`` (``_fetch_batches``/``__iter__``).
    Used when the dataset cannot be sharded by index (e.g. streaming
    ``IterableDataset`` with ``dispatch_batches=True``).
    """

    def __init__(
        self,
        base_loader: Iterable,
        split_batches: bool = False,
        skip_batches: int = 0,
        put_on_device: bool = True,
        mesh: Optional[jax.sharding.Mesh] = None,
        slice_fn: Optional[Callable] = None,
        non_blocking: bool = False,
        output_type: str = "jax",
        even_batches: bool = True,
        prefetch_to_device: int = 0,
        **kwargs,
    ):
        self.base_loader = base_loader
        self.split_batches = split_batches
        self.skip_batches = skip_batches
        self.use_stateful_dataloader = kwargs.pop("use_stateful_dataloader", False)
        self.even_batches = even_batches
        self.prefetch_to_device = prefetch_to_device
        self._warned_prefetch_multihost = False
        self._yielded = 0
        self.state = PartialState()
        self.gradient_state = GradientState()
        self._placer = (
            _GlobalBatchPlacer(
                mesh, non_blocking, output_type=output_type, even_batches=even_batches
            )
            if put_on_device
            else None
        )
        self.slice_fn = slice_fn or slice_tensors
        self.iteration = 0
        # Micro-batches assembled per step (only consulted when not
        # split_batches): batch-size semantics must match the shard path
        # (script batch_size is PER data shard — reference ``_fetch_batches``
        # reads num_processes batches; device shards are the "processes" of
        # the mesh).  Without a mesh this is the host count.
        if self._placer is not None and self._placer.num_data_shards > 1:
            self._num_parts = self._placer.num_data_shards
        else:
            self._num_parts = max(self.state.num_processes, 1)

    @property
    def dataset(self):
        return getattr(self.base_loader, "dataset", self.base_loader)

    def __len__(self):
        n = len(self.base_loader)
        if not self.split_batches:
            n = math.ceil(n / self._num_parts)
        return n - self.skip_batches

    @property
    def total_batch_size(self) -> int:
        bs = getattr(self.base_loader, "batch_size", 1) or 1
        return bs if self.split_batches else bs * self._num_parts

    @property
    def total_dataset_length(self) -> int:
        return len(self.dataset)

    def set_epoch(self, epoch: int):
        self.iteration = epoch
        if hasattr(self.base_loader, "set_epoch"):
            self.base_loader.set_epoch(epoch)
        elif hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def _fetch_global_batch(self, iterator):
        """Process 0 assembles the global batch (one micro-batch per data shard
        unless split_batches) and broadcasts structure + payload."""
        from .utils.operations import broadcast_object_list, concatenate

        stop = False
        batch = None
        if self.state.is_main_process or self.state.num_processes == 1:
            try:
                if self.split_batches:
                    batch = next(iterator)
                else:
                    parts = []
                    for _ in range(self._num_parts):
                        try:
                            parts.append(next(iterator))
                        except StopIteration:
                            break
                    if not parts:
                        stop = True
                    else:
                        batch = concatenate(parts, dim=0) if len(parts) > 1 else parts[0]
            except StopIteration:
                stop = True
        if self.state.num_processes > 1:
            info = [stop, None]
            if self.state.is_main_process:
                info = [stop, batch]
            broadcast_object_list(info)
            stop, batch = info
        return stop, batch

    def _effective_prefetch_depth(self) -> int:
        depth = self.prefetch_to_device or prefetch_depth_from_env()
        if depth <= 0 or self._placer is None:
            return 0
        if self.state.num_processes > 1:
            # Process 0's fetch drives a broadcast collective; moving it onto
            # a worker thread while user code runs its own collectives on the
            # main thread risks cross-process ordering mismatches.  The shard
            # loader (per-process reads, no fetch collective) prefetches on
            # any topology.
            if not self._warned_prefetch_multihost:
                self._warned_prefetch_multihost = True
                warnings.warn(
                    "prefetch_to_device is disabled for DataLoaderDispatcher on "
                    "multi-process runs (the dispatch broadcast must stay on the "
                    "main thread); use sharded dataloaders for async prefetch."
                )
            return 0
        return depth

    def _iter_prefetched(self, iterator, depth: int):
        def _source():
            while True:
                stop, batch = self._fetch_global_batch(iterator)
                if stop:
                    return
                yield batch

        src = _source()
        for _ in range(self.skip_batches):
            try:
                next(src)
            except StopIteration:
                break
        prefetcher = DevicePrefetcher(src, self._emit_tracked, depth)
        emitted = 0
        try:
            for placed, meta, is_last in prefetcher:
                pad, bs = meta
                if is_last:
                    self.end_of_dataloader = True
                    if bs is not None:
                        self.remainder = bs % self.total_batch_size or self.remainder
                pos = self.skip_batches + emitted
                emitted += 1
                self._yielded = pos + 1
                if self._is_quarantined(pos):
                    self._count_quarantine_skip(pos)
                    continue
                if self._placer is not None:
                    self.gradient_state.device_pad_rows = pad[0]
                    self.gradient_state.device_batch_rows = pad[1]
                yield self._maybe_poison(placed, pos)
        finally:
            prefetcher.close()
        if emitted == 0:
            self.end_of_dataloader = True

    def __iter__(self):
        self.begin()
        self.set_epoch(self.iteration)
        iterator = iter(self.base_loader) if (self.state.is_main_process or self.state.num_processes == 1) else iter(())
        depth = self._effective_prefetch_depth()
        if depth > 0:
            yield from self._iter_prefetched(iterator, depth)
        else:
            batch_index = 0
            prev = None
            while True:
                stop, batch = self._fetch_global_batch(iterator)
                if stop:
                    if prev is not None:
                        self.end_of_dataloader = True
                        bs = ignorant_find_batch_size(prev)
                        if bs is not None:
                            self.remainder = bs % self.total_batch_size or self.remainder
                        if batch_index - 1 >= self.skip_batches:
                            self._yielded = batch_index
                            if self._is_quarantined(batch_index - 1):
                                self._count_quarantine_skip(batch_index - 1)
                            else:
                                yield self._maybe_poison(self._emit(prev), batch_index - 1)
                    break
                if prev is not None and batch_index - 1 >= self.skip_batches:
                    self._yielded = batch_index
                    if self._is_quarantined(batch_index - 1):
                        self._count_quarantine_skip(batch_index - 1)
                    else:
                        yield self._maybe_poison(self._emit(prev), batch_index - 1)
                prev = batch
                batch_index += 1
        self.iteration += 1
        # A state_dict taken between epochs must record position 0 of the NEXT
        # epoch — leaving _yielded at the full count would make a resumed run
        # silently skip that entire epoch.
        self._yielded = 0
        self._consume_skip_once()
        self.end()

    @_span("dataloader.next_batch")
    def _emit_tracked(self, global_batch):
        """Slice this host's shard and place it; returns ``(placed,
        ((pad_rows, batch_rows), raw_batch_size))``.  Worker-thread-safe: no
        GradientState writes here — the consumer publishes the pad meta at
        yield time."""
        # Every host received the full global batch via broadcast; cut THIS host's
        # slice before placement (the reference sliced per-rank here,
        # data_loader.py:844-916) — the placer's multi-host path expects exactly
        # the process-local shard.
        tel = _get_telemetry()
        if tel.enabled:
            tel.registry.counter("dataloader.batches").inc()
            tel.heartbeat()
        raw_bs = ignorant_find_batch_size(global_batch)
        if self.state.num_processes > 1:
            bs = raw_bs
            if bs is not None:
                if bs % self.state.num_processes != 0:
                    from .utils.operations import pad_input_tensors

                    global_batch = pad_input_tensors(global_batch, bs, self.state.num_processes)
                    bs = find_batch_size(global_batch)
                per_host = bs // self.state.num_processes
                lo = per_host * self.state.process_index
                global_batch = self.slice_fn(
                    global_batch,
                    slice(lo, lo + per_host),
                    process_index=self.state.process_index,
                    num_processes=self.state.num_processes,
                )
        if self._placer is not None:
            placed = self._placer(global_batch)
            return placed, (
                (self._placer.last_pad_rows, self._placer.last_batch_rows),
                raw_bs,
            )
        return global_batch, ((0, 0), raw_bs)

    def _emit(self, global_batch):
        placed, (pad, _) = self._emit_tracked(global_batch)
        if self._placer is not None:
            self.gradient_state.device_pad_rows = pad[0]
            self.gradient_state.device_batch_rows = pad[1]
        return placed


# ---------------------------------------------------------------------------
# Factory
# ---------------------------------------------------------------------------


def get_sampler(dataloader):
    """Fish the underlying sampler out of a torch DataLoader (reference
    ``data_loader.py get_sampler``)."""
    if hasattr(dataloader, "batch_sampler") and dataloader.batch_sampler is not None:
        return getattr(dataloader.batch_sampler, "sampler", None)
    return getattr(dataloader, "sampler", None)


def prepare_data_loader(
    dataloader,
    device=None,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
    split_batches: bool = False,
    put_on_device: bool = True,
    rng_types: Optional[list] = None,
    dispatch_batches: Optional[bool] = None,
    even_batches: bool = True,
    slice_fn_for_dispatch: Optional[Callable] = None,
    use_seedable_sampler: bool = False,
    data_seed: Optional[int] = None,
    non_blocking: bool = False,
    use_stateful_dataloader: bool = False,
    mesh: Optional[jax.sharding.Mesh] = None,
    output_type: str = "jax",
    static_shape_tail: bool = False,
    prefetch_to_device: int = 0,
):
    """Shard a (torch) dataloader for the current topology and wrap it for global
    device placement.

    Parity: reference ``data_loader.py:988-1287``.  Routing:

    - sized map-style dataset → rebuild the inner loader with `BatchSamplerShard`
      → `DataLoaderShard`
    - iterable dataset → `IterableDatasetShard` → `DataLoaderShard`
    - ``dispatch_batches=True`` → `DataLoaderDispatcher` (process-0 reads)

    ``num_processes`` defaults to the number of HOST processes; device-level
    sharding happens via ``mesh`` (defaults to ``AcceleratorState().mesh`` when
    initialized).
    """
    state = PartialState()
    if num_processes is None:
        num_processes = state.num_processes
    if process_index is None:
        process_index = state.process_index
    if mesh is None and AcceleratorState._shared_state != {}:
        mesh = AcceleratorState().mesh

    # Batch-size semantics parity (reference data_loader.py:988 docstring): the
    # script's batch_size is PER data shard (per device); the observed global batch
    # is batch_size * num_data_shards.  Each host therefore loads
    # local_shards * batch_size samples per step and the placer shards them over
    # the mesh's data axes.  split_batches=True inverts this: batch_size is the
    # global batch, split S ways.
    total_shards = 1
    if mesh is not None:
        from .parallel.mesh import data_axes as _data_axes

        for a in _data_axes(mesh):
            total_shards *= mesh.shape[a]
    if total_shards % num_processes != 0:
        raise ValueError(
            f"Total data shards ({total_shards}) must be a multiple of the number of host "
            f"processes ({num_processes})."
        )
    local_shards = max(total_shards // num_processes, 1)

    is_torch_loader = False
    if is_torch_available():
        import torch.utils.data

        is_torch_loader = isinstance(dataloader, torch.utils.data.DataLoader)

    if dispatch_batches is None:
        dispatch_batches = False

    if dispatch_batches:
        base = dataloader
        if is_torch_loader and use_seedable_sampler:
            # The seedable-sampler contract holds on the dispatcher path too
            # (reference data_loader.py:1038-1048 swaps the sampler before
            # choosing a wrapper): replace a RandomSampler inside the LIVE
            # loader so process 0 reads the epoch-seeded permutation.
            import torch.utils.data

            samp = get_sampler(dataloader)
            if isinstance(samp, torch.utils.data.RandomSampler):
                seedable = SeedableRandomSampler(
                    samp.data_source,
                    initial_seed=data_seed if data_seed is not None else 42,
                    generator=getattr(samp, "generator", None),
                )
                if getattr(dataloader, "batch_sampler", None) is not None:
                    dataloader.batch_sampler.sampler = seedable
                else:  # pragma: no cover - batch_sampler=None loaders
                    dataloader.sampler = seedable
        return DataLoaderDispatcher(
            base,
            split_batches=split_batches,
            put_on_device=put_on_device,
            mesh=mesh,
            slice_fn=slice_fn_for_dispatch,
            non_blocking=non_blocking,
            output_type=output_type,
            use_stateful_dataloader=use_stateful_dataloader,
            even_batches=even_batches,
            prefetch_to_device=prefetch_to_device,
        )

    if not is_torch_loader:
        # Generic iterable of batches: no index-level sharding possible on the
        # host side (single-host covers it via device sharding).
        if num_processes > 1:
            raise ValueError(
                "Multi-host sharding of a non-torch dataloader requires dispatch_batches=True "
                "or a torch DataLoader."
            )
        return DataLoaderShard(
            dataloader,
            device=device,
            rng_types=rng_types,
            put_on_device=put_on_device,
            mesh=mesh,
            non_blocking=non_blocking,
            output_type=output_type,
            use_stateful_dataloader=use_stateful_dataloader,
            even_batches=even_batches,
            prefetch_to_device=prefetch_to_device,
        )

    import torch.utils.data

    dataset = dataloader.dataset
    synchronized_generator = None
    sampler = get_sampler(dataloader)

    if isinstance(dataset, torch.utils.data.IterableDataset):
        if dataloader.batch_size is None:
            # Sample streaming (reference: batch_size=None passes items through
            # unbatched); multi-host shards round-robin by sample.
            host_batch_size = None
            shard_batch_size = 1
        elif split_batches:
            host_batch_size = dataloader.batch_size // num_processes
            shard_batch_size = dataloader.batch_size
        else:
            host_batch_size = dataloader.batch_size * local_shards
            shard_batch_size = host_batch_size
        new_dataset = (
            IterableDatasetShard(
                dataset,
                batch_size=shard_batch_size,
                drop_last=dataloader.drop_last,
                num_processes=num_processes,
                process_index=process_index,
                split_batches=split_batches,
            )
            if num_processes > 1
            else dataset
        )
        base = torch.utils.data.DataLoader(
            new_dataset,
            batch_size=host_batch_size,
            collate_fn=dataloader.collate_fn,
            num_workers=dataloader.num_workers,
            drop_last=dataloader.drop_last,
            pin_memory=False,
        )
        return DataLoaderShard(
            base,
            device=device,
            rng_types=rng_types,
            put_on_device=put_on_device,
            mesh=mesh,
            non_blocking=non_blocking,
            output_type=output_type,
            use_stateful_dataloader=use_stateful_dataloader,
            even_batches=even_batches,
            prefetch_to_device=prefetch_to_device,
            total_batch_size=(dataloader.batch_size or 1)
            * (1 if split_batches else total_shards),
        )

    # Map-style dataset path.
    if use_seedable_sampler and isinstance(sampler, torch.utils.data.RandomSampler):
        sampler = SeedableRandomSampler(
            sampler.data_source,
            initial_seed=data_seed if data_seed is not None else 42,
            generator=getattr(sampler, "generator", None),
        )
        synchronized_generator = None
    elif isinstance(sampler, torch.utils.data.RandomSampler):
        # Keep torch semantics: synchronize the generator across processes at
        # epoch start (reference rng sync via rng_types=["generator"]).
        if getattr(sampler, "generator", None) is None and rng_types and "generator" in rng_types:
            import torch

            sampler.generator = torch.Generator()
            sampler.generator.manual_seed(data_seed if data_seed is not None else 42)
        synchronized_generator = getattr(sampler, "generator", None)

    batch_sampler = dataloader.batch_sampler
    scale = 1 if split_batches else local_shards
    if scale > 1 or (use_seedable_sampler and sampler is not None):
        if sampler is None:
            raise ValueError(
                "Cannot scale the per-device batch size of a DataLoader built directly from a "
                "batch_sampler with no underlying sampler; pass batch_size/sampler instead."
            )
        batch_sampler = torch.utils.data.BatchSampler(
            sampler,
            batch_size=(batch_sampler.batch_size if batch_sampler is not None else dataloader.batch_size)
            * scale,
            drop_last=getattr(batch_sampler, "drop_last", False),
        )
    # Reference parity ("No change if no multiprocess", reference
    # data_loader.py:1190): at num_processes == 1 the sampler is left alone by
    # default.  ``static_shape_tail=True`` opts single-process loaders into the
    # same even_batches wrap used for sharding, so the tail batch wraps to FULL
    # size and every batch has one static shape (a single XLA trace, no tail
    # recompile/padding).  The wrap duplicates leading samples into the final
    # batch — gather_for_metrics' remainder dedup drops them for metrics, but
    # the training loss on that step sees them, hence opt-in.  A custom batch
    # sampler with no fixed batch_size can never be equalized (even_batches
    # needs a target size) and stays unwrapped either way.
    wrap = num_processes > 1 or (
        static_shape_tail and getattr(batch_sampler, "batch_size", None) is not None
    )
    new_batch_sampler = (
        BatchSamplerShard(
            batch_sampler,
            num_processes=num_processes,
            process_index=process_index,
            split_batches=split_batches,
            even_batches=even_batches,
        )
        if wrap
        else batch_sampler
    )

    base = torch.utils.data.DataLoader(
        dataset,
        batch_sampler=new_batch_sampler,
        collate_fn=dataloader.collate_fn,
        num_workers=dataloader.num_workers,
        pin_memory=False,
    )
    return DataLoaderShard(
        base,
        device=device,
        rng_types=rng_types,
        synchronized_generator=synchronized_generator,
        put_on_device=put_on_device,
        mesh=mesh,
        non_blocking=non_blocking,
        output_type=output_type,
        use_stateful_dataloader=use_stateful_dataloader,
        even_batches=even_batches,
        prefetch_to_device=prefetch_to_device,
    )


# ---------------------------------------------------------------------------
# Mid-epoch resume
# ---------------------------------------------------------------------------


class SkipBatchSampler:
    """Batch sampler skipping the first ``skip_batches`` batches (reference
    ``data_loader.py:1290``)."""

    def __init__(self, batch_sampler, skip_batches: int = 0):
        self.batch_sampler = batch_sampler
        self.skip_batches = skip_batches

    def __iter__(self):
        for index, samples in enumerate(self.batch_sampler):
            if index >= self.skip_batches:
                yield samples

    @property
    def total_length(self):
        return len(self.batch_sampler)

    def __len__(self):
        return len(self.batch_sampler) - self.skip_batches


class SkipDataLoader(DataLoaderShard):
    """Dataloader yielding everything after the first ``skip_batches`` batches
    (reference ``data_loader.py SkipDataLoader``)."""

    def __init__(self, base_loader, skip_batches: int = 0, **kwargs):
        super().__init__(base_loader, skip_batches=skip_batches, **kwargs)


def skip_first_batches(dataloader, num_batches: int = 0):
    """Resume mid-epoch: a loader that skips ``num_batches`` (reference
    ``data_loader.py:1353``).  Prepared loaders keep their sharding/placement;
    raw loaders are wrapped."""
    if isinstance(dataloader, DataLoaderDispatcher):
        out = DataLoaderDispatcher(
            dataloader.base_loader,
            split_batches=dataloader.split_batches,
            skip_batches=num_batches,
            put_on_device=dataloader._placer is not None,
            mesh=dataloader._placer.mesh if dataloader._placer else None,
            slice_fn=dataloader.slice_fn,
            output_type=dataloader._placer.output_type if dataloader._placer else "jax",
            use_stateful_dataloader=dataloader.use_stateful_dataloader,
            even_batches=getattr(dataloader, "even_batches", True),
            prefetch_to_device=getattr(dataloader, "prefetch_to_device", 0),
        )
        return out
    if isinstance(dataloader, DataLoaderShard):
        return DataLoaderShard(
            dataloader.base_loader,
            device=dataloader.device,
            rng_types=dataloader.rng_types,
            synchronized_generator=dataloader.synchronized_generator,
            skip_batches=num_batches,
            put_on_device=dataloader.put_on_device,
            mesh=dataloader._placer.mesh if dataloader._placer else None,
            output_type=dataloader._placer.output_type if dataloader._placer else "jax",
            total_batch_size=dataloader._total_batch_size,
            use_stateful_dataloader=dataloader.use_stateful_dataloader,
            even_batches=getattr(dataloader, "even_batches", True),
            prefetch_to_device=getattr(dataloader, "prefetch_to_device", 0),
        )
    return SkipDataLoader(dataloader, skip_batches=num_batches, put_on_device=False)
